// graftrpc reactor: the native dispatch plane for the actor-call hot
// path (SURVEY §2.1 — the reference's equivalent component is the gRPC
// direct-call stack in src/ray/rpc/ + core_worker client pool; here a
// single epoll thread per process moves length-prefixed frames between
// co-located workers over unix sockets).
//
// Division of labor with the Python seam (core/_native/graftrpc.py):
// this file only MOVES frames — accept, reassemble split reads, coalesce
// writes, batch wakeups. It never interprets a frame body beyond the
// length prefix; opcodes and the header layout are defined here solely
// so the wire contract is lint-checkable against the Python constants
// (tools/lint/wire_schema.py, same discipline as the store sidecar).
//
// Wire format (little-endian):
//   frame  : u32 len | header | payload          (len = header + payload)
//   header : u8 op | u8 flags | u16 chan | u64 seq      (kFrameHeaderSize)
// Ops: 1 CALL (task batch)  2 REPLY  3 INTERN (spec template)
//      4 PING               5 GOAWAY
//
// Threading:
//   - one reactor thread owns epoll, all reads, and all epoll_ctl calls;
//   - senders (any thread; in practice the worker's io loop via ctypes,
//     which releases the GIL) append to a per-connection write buffer
//     under its mutex and try ONE immediate nonblocking write when the
//     buffer is empty — the common case completes entirely in the caller
//     thread with zero reactor involvement (write coalescing: whatever
//     queues behind a busy socket is flushed by the reactor in one burst
//     when EPOLLOUT fires);
//   - inbound frames land in a locked inbox; a pipe byte is written only
//     on the empty->nonempty transition (batched wakeups: a burst of
//     frames costs the event loop ONE reader callback, which drains the
//     whole inbox via rpc_core_drain).
//
// Lifetime: connections are closed only by the reactor (or by stop after
// the reactor has joined), always under the connection's write mutex, so
// a concurrent sender can never write into a recycled fd number.
// rpc_core_stop must not race rpc_core_send — the Python seam closes the
// endpoint only after its event loop stops dispatching.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include "prof_core.h"
#include "scope_core.h"

namespace {

#pragma pack(push, 1)
struct FrameHeader {  // 12 bytes on the wire, little-endian
  uint8_t op;
  uint8_t flags;
  uint16_t chan;
  uint64_t seq;
};
#pragma pack(pop)

constexpr int kFrameHeaderSize = 12;
static_assert(sizeof(FrameHeader) == kFrameHeaderSize, "header packing");

// Opcodes are interpreted by the Python seam; defined here so lint can
// cross-check the two tables (wire_schema pass).
[[maybe_unused]] constexpr uint8_t kOpCall = 1, kOpReply = 2, kOpIntern = 3,
                                   kOpPing = 4, kOpGoaway = 5;

constexpr uint32_t kMaxFrame = 64u << 20;  // sanity cap per frame
constexpr uint32_t kClosedLen = 0xFFFFFFFFu;  // drain record: conn closed

struct Conn {
  uint32_t id = 0;
  int fd = -1;                 // -1 once closed (under wmu)
  std::mutex wmu;              // guards fd validity, outbuf, epollout
  std::string outbuf;          // bytes the socket wouldn't take yet
  bool epollout = false;       // EPOLLOUT armed (reactor keeps in sync)
  std::atomic<bool> dead{false};
  // Read side: reactor-thread-only, no lock needed.
  std::string inbuf;
  size_t inoff = 0;
};

struct InRec {
  uint32_t conn;
  uint32_t len;       // kClosedLen => connection closed, no bytes
  std::string data;   // header + payload
};

enum CmdKind { kCmdAdd = 1, kCmdArmWrite = 2, kCmdClose = 3, kCmdStop = 4 };

struct Cmd {
  CmdKind kind;
  uint32_t conn;
};

struct Endpoint {
  int epfd = -1;
  int listen_fd = -1;
  int wake_r = -1, wake_w = -1;      // reactor wakeup (commands pending)
  int notify_r = -1, notify_w = -1;  // inbox nonempty signal to Python
  pthread_t reactor;
  bool reactor_started = false;

  std::mutex mu;  // conns map, inbox, cmds, next_id
  std::unordered_map<uint32_t, std::shared_ptr<Conn>> conns;
  std::deque<InRec> inbox;
  std::vector<Cmd> cmds;
  uint32_t next_id = 2;  // 0 = wake pipe, 1 = listen fd in epoll data
  std::atomic<bool> stopping{false};
};

void Notify(Endpoint* ep, InRec&& rec) {
  bool was_empty;
  {
    std::lock_guard<std::mutex> g(ep->mu);
    was_empty = ep->inbox.empty();
    ep->inbox.push_back(std::move(rec));
  }
  if (was_empty) {
    // graftscope: one wake record per empty->nonempty transition — the
    // recv-side wakeup-batching ratio falls straight out of
    // RpcRecv.calls / RpcWake.calls.
    scope_emit(kScopeRpcWake, 0, 0, 0, 0, 0, 0);
    char b = 1;
    (void)!::write(ep->notify_w, &b, 1);
  }
}

void Wake(Endpoint* ep) {
  char b = 1;
  (void)!::write(ep->wake_w, &b, 1);
}

std::shared_ptr<Conn> FindConn(Endpoint* ep, uint32_t id) {
  std::lock_guard<std::mutex> g(ep->mu);
  auto it = ep->conns.find(id);
  return it == ep->conns.end() ? nullptr : it->second;
}

// Reactor-side close: drop from epoll + map, close the fd under wmu so
// no sender can race the fd into a recycled descriptor, then (unless
// locally initiated) report the loss to Python as a close record.
void CloseConn(Endpoint* ep, const std::shared_ptr<Conn>& c, bool report) {
  {
    std::lock_guard<std::mutex> g(c->wmu);
    if (c->fd < 0) return;  // already closed
    // dead is only ever touched under wmu: relaxed, the mutex orders it.
    c->dead.store(true, std::memory_order_relaxed);
    ::epoll_ctl(ep->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
    ::close(c->fd);
    c->fd = -1;
  }
  {
    std::lock_guard<std::mutex> g(ep->mu);
    ep->conns.erase(c->id);
  }
  if (report) Notify(ep, InRec{c->id, kClosedLen, std::string()});
}

// Flush as much of outbuf as the socket takes; returns false on a fatal
// write error. Caller holds wmu.
bool FlushLocked(Conn* c) {
  while (!c->outbuf.empty()) {
    ssize_t w = ::send(c->fd, c->outbuf.data(), c->outbuf.size(),
                       MSG_NOSIGNAL);
    if (w > 0) {
      c->outbuf.erase(0, (size_t)w);
      continue;
    }
    if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return true;
    return false;
  }
  return true;
}

void SetEpollOut(Endpoint* ep, Conn* c, bool on) {  // caller holds wmu
  if (c->epollout == on || c->fd < 0) return;
  epoll_event ev;
  std::memset(&ev, 0, sizeof(ev));
  ev.events = EPOLLIN | (on ? (uint32_t)EPOLLOUT : 0u);
  ev.data.u64 = c->id;
  if (::epoll_ctl(ep->epfd, EPOLL_CTL_MOD, c->fd, &ev) == 0) {
    c->epollout = on;
  }
}

void RegisterConn(Endpoint* ep, const std::shared_ptr<Conn>& c) {
  epoll_event ev;
  std::memset(&ev, 0, sizeof(ev));
  bool failed = false;
  {
    std::lock_guard<std::mutex> g(c->wmu);
    if (c->fd < 0) return;
    bool arm = !c->outbuf.empty();
    c->epollout = arm;
    ev.events = EPOLLIN | (arm ? (uint32_t)EPOLLOUT : 0u);
    ev.data.u64 = c->id;
    if (::epoll_ctl(ep->epfd, EPOLL_CTL_ADD, c->fd, &ev) != 0) {
      c->dead.store(true, std::memory_order_relaxed);  // under wmu
      ::close(c->fd);
      c->fd = -1;
      failed = true;
    }
  }
  if (failed) {
    std::lock_guard<std::mutex> g(ep->mu);
    ep->conns.erase(c->id);
  }
}

// Slice complete frames out of c->inbuf and deliver them to the inbox.
// Returns false if the peer sent a malformed length (connection dropped).
bool ExtractFrames(Endpoint* ep, Conn* c) {
  for (;;) {
    size_t avail = c->inbuf.size() - c->inoff;
    if (avail < 4) break;
    uint32_t len;
    std::memcpy(&len, c->inbuf.data() + c->inoff, 4);
    if (len < (uint32_t)kFrameHeaderSize || len > kMaxFrame) return false;
    if (avail < 4 + (size_t)len) break;
    InRec rec;
    rec.conn = c->id;
    rec.len = len;
    rec.data.assign(c->inbuf.data() + c->inoff + 4, len);
    if (scope_enabled()) {
      // Frame header leads the record data; peek it for the trace tag.
      FrameHeader h;
      std::memcpy(&h, rec.data.data(), sizeof(h));
      scope_emit(kScopeRpcRecv, h.op, h.chan, len, h.seq, 0, 0);
    }
    Notify(ep, std::move(rec));
    c->inoff += 4 + (size_t)len;
  }
  if (c->inoff == c->inbuf.size()) {
    c->inbuf.clear();
    c->inoff = 0;
  } else if (c->inoff > (1u << 20)) {  // keep the partial tail compact
    c->inbuf.erase(0, c->inoff);
    c->inoff = 0;
  }
  return true;
}

void HandleReadable(Endpoint* ep, const std::shared_ptr<Conn>& c) {
  char buf[65536];
  for (;;) {
    ssize_t r = ::read(c->fd, buf, sizeof(buf));
    if (r > 0) {
      c->inbuf.append(buf, (size_t)r);
      if (!ExtractFrames(ep, c.get())) {
        CloseConn(ep, c, /*report=*/true);
        return;
      }
      // Short read: the socket is likely drained; level-triggered epoll
      // re-reports if more arrived meanwhile.
      if ((size_t)r < sizeof(buf)) return;
      continue;
    }
    if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
    if (r < 0 && errno == EINTR) continue;
    CloseConn(ep, c, /*report=*/true);  // EOF or hard error
    return;
  }
}

void HandleWritable(Endpoint* ep, const std::shared_ptr<Conn>& c) {
  bool fatal = false;
  uint64_t t0 = scope_enabled() ? scope_now_ns() : 0;
  size_t flushed = 0;
  {
    std::lock_guard<std::mutex> g(c->wmu);
    if (c->fd < 0) return;
    size_t before = c->outbuf.size();
    if (!FlushLocked(c.get())) {
      fatal = true;
    } else if (c->outbuf.empty()) {
      SetEpollOut(ep, c.get(), false);
    }
    flushed = before - c->outbuf.size();
  }
  if (t0 != 0 && flushed > 0) {
    // Span-in-one record: seq_or_oid = start_ns, t_ns = end_ns.
    uint64_t t1 = scope_now_ns();
    scope_emit(kScopeRpcFlush, 0, 0, (uint32_t)flushed, t0, t1, t1 - t0);
  }
  if (fatal) CloseConn(ep, c, /*report=*/true);
}

void HandleAccept(Endpoint* ep) {
  for (;;) {
    int fd = ::accept(ep->listen_fd, nullptr, nullptr);
    if (fd < 0) return;
    ::fcntl(fd, F_SETFL, O_NONBLOCK);
    auto c = std::make_shared<Conn>();
    c->fd = fd;
    {
      std::lock_guard<std::mutex> g(ep->mu);
      c->id = ep->next_id++;
      ep->conns[c->id] = c;
    }
    RegisterConn(ep, c);
  }
}

bool HandleCommands(Endpoint* ep) {  // returns false on stop
  char scratch[64];
  while (::read(ep->wake_r, scratch, sizeof(scratch)) > 0) {
  }
  std::vector<Cmd> cmds;
  {
    std::lock_guard<std::mutex> g(ep->mu);
    cmds.swap(ep->cmds);
  }
  for (const Cmd& cmd : cmds) {
    if (cmd.kind == kCmdStop) return false;
    auto c = FindConn(ep, cmd.conn);
    if (c == nullptr) continue;
    if (cmd.kind == kCmdAdd) {
      RegisterConn(ep, c);
    } else if (cmd.kind == kCmdArmWrite) {
      std::lock_guard<std::mutex> g(c->wmu);
      if (c->fd >= 0 && !c->outbuf.empty()) SetEpollOut(ep, c.get(), true);
    } else if (cmd.kind == kCmdClose) {
      CloseConn(ep, c, /*report=*/false);
    }
  }
  return true;
}

void* ReactorLoop(void* argp) {
  auto* ep = static_cast<Endpoint*>(argp);
  prof_register_thread("graftrpc-reactor");
  epoll_event evs[64];
  for (;;) {
    int n = ::epoll_wait(ep->epfd, evs, 64, -1);
    if (n < 0) {
      if (errno == EINTR) continue;
      return nullptr;
    }
    for (int i = 0; i < n; i++) {
      uint64_t tag = evs[i].data.u64;
      if (tag == 0) {
        if (!HandleCommands(ep)) return nullptr;
        continue;
      }
      if (tag == 1) {
        HandleAccept(ep);
        continue;
      }
      auto c = FindConn(ep, (uint32_t)tag);
      if (c == nullptr) continue;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        // Drain any final bytes first, then report the close.
        HandleReadable(ep, c);
        CloseConn(ep, c, /*report=*/true);
        continue;
      }
      if (evs[i].events & EPOLLOUT) HandleWritable(ep, c);
      if (evs[i].events & EPOLLIN) HandleReadable(ep, c);
    }
  }
}

int MakePipe(int* r, int* w, bool nonblock_read) {
  int fds[2];
  if (::pipe(fds) != 0) return -1;
  if (nonblock_read) ::fcntl(fds[0], F_SETFL, O_NONBLOCK);
  *r = fds[0];
  *w = fds[1];
  return 0;
}

}  // namespace

extern "C" {

// Starts an endpoint: reactor thread + optional listening socket
// (listen_path may be NULL for a connect-only endpoint). Returns the
// endpoint handle or NULL; *notify_fd_out receives the inbox-signal
// pipe's read end (register with the event loop, then rpc_core_drain).
void* rpc_core_start(const char* listen_path, int* notify_fd_out) {
  auto* ep = new Endpoint();
  if (MakePipe(&ep->wake_r, &ep->wake_w, true) != 0) {
    delete ep;
    return nullptr;
  }
  if (MakePipe(&ep->notify_r, &ep->notify_w, true) != 0) {
    ::close(ep->wake_r);
    ::close(ep->wake_w);
    delete ep;
    return nullptr;
  }
  ep->epfd = ::epoll_create1(0);
  if (ep->epfd < 0) {
    ::close(ep->wake_r);
    ::close(ep->wake_w);
    ::close(ep->notify_r);
    ::close(ep->notify_w);
    delete ep;
    return nullptr;
  }
  epoll_event ev;
  std::memset(&ev, 0, sizeof(ev));
  ev.events = EPOLLIN;
  ev.data.u64 = 0;
  ::epoll_ctl(ep->epfd, EPOLL_CTL_ADD, ep->wake_r, &ev);
  if (listen_path != nullptr && listen_path[0] != 0) {
    ep->listen_fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
    sockaddr_un addr;
    std::memset(&addr, 0, sizeof(addr));
    addr.sun_family = AF_UNIX;
    std::snprintf(addr.sun_path, sizeof(addr.sun_path), "%s", listen_path);
    ::unlink(listen_path);
    if (::bind(ep->listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
        ::listen(ep->listen_fd, 128) != 0) {
      ::close(ep->listen_fd);
      ::close(ep->epfd);
      ::close(ep->wake_r);
      ::close(ep->wake_w);
      ::close(ep->notify_r);
      ::close(ep->notify_w);
      delete ep;
      return nullptr;
    }
    ::fcntl(ep->listen_fd, F_SETFL, O_NONBLOCK);
    std::memset(&ev, 0, sizeof(ev));
    ev.events = EPOLLIN;
    ev.data.u64 = 1;
    ::epoll_ctl(ep->epfd, EPOLL_CTL_ADD, ep->listen_fd, &ev);
  }
  if (pthread_create(&ep->reactor, nullptr, ReactorLoop, ep) != 0) {
    if (ep->listen_fd >= 0) ::close(ep->listen_fd);
    ::close(ep->epfd);
    ::close(ep->wake_r);
    ::close(ep->wake_w);
    ::close(ep->notify_r);
    ::close(ep->notify_w);
    delete ep;
    return nullptr;
  }
  ep->reactor_started = true;
  *notify_fd_out = ep->notify_r;
  return ep;
}

// Connect to a peer endpoint's listening socket. Returns the connection
// id (> 1) or -1. Callable from any thread.
int rpc_core_connect(void* handle, const char* path) {
  auto* ep = static_cast<Endpoint*>(handle);
  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_un addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  std::snprintf(addr.sun_path, sizeof(addr.sun_path), "%s", path);
  if (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  ::fcntl(fd, F_SETFL, O_NONBLOCK);
  auto c = std::make_shared<Conn>();
  c->fd = fd;
  uint32_t id;
  {
    std::lock_guard<std::mutex> g(ep->mu);
    id = ep->next_id++;
    c->id = id;
    ep->conns[id] = c;
    ep->cmds.push_back(Cmd{kCmdAdd, id});
  }
  Wake(ep);
  return (int)id;
}

// Send one frame (data = header + payload; the u32 length prefix is
// added here). Appends to the connection's write buffer and attempts an
// immediate nonblocking flush when nothing was queued; bytes the socket
// won't take are flushed by the reactor on EPOLLOUT. Returns 0, or -1
// if the connection is unknown/closed or the write failed fatally.
int rpc_core_send(void* handle, uint32_t conn, const char* data,
                  uint32_t len) {
  auto* ep = static_cast<Endpoint*>(handle);
  if (len < (uint32_t)kFrameHeaderSize || len > kMaxFrame) return -1;
  auto c = FindConn(ep, conn);
  if (c == nullptr) return -1;
  if (scope_enabled()) {
    // Peek the header only — this plane never interprets payloads. The
    // chan field carries the submitter's trace tag (graftscope.py).
    FrameHeader h;
    std::memcpy(&h, data, sizeof(h));
    scope_emit(kScopeRpcSend, h.op, h.chan, len, h.seq, 0, 0);
  }
  bool need_arm = false;
  {
    std::lock_guard<std::mutex> g(c->wmu);
    if (c->fd < 0 || c->dead.load(std::memory_order_relaxed)) return -1;
    bool was_idle = c->outbuf.empty();
    char prefix[4];
    std::memcpy(prefix, &len, 4);
    if (was_idle) {
      // Fast path: write prefix+frame straight from the caller thread.
      iovec iov[2] = {{prefix, 4}, {(void*)data, len}};
      msghdr msg;
      std::memset(&msg, 0, sizeof(msg));
      msg.msg_iov = iov;
      msg.msg_iovlen = 2;
      ssize_t w = ::sendmsg(c->fd, &msg, MSG_NOSIGNAL);
      if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
        c->dead.store(true, std::memory_order_relaxed);  // under wmu
        return -1;
      }
      size_t wrote = w > 0 ? (size_t)w : 0;
      if (wrote >= 4 + (size_t)len) return 0;  // fully sent, no wakeup
      if (wrote < 4) c->outbuf.append(prefix + wrote, 4 - wrote);
      size_t body_off = wrote > 4 ? wrote - 4 : 0;
      c->outbuf.append(data + body_off, len - body_off);
      need_arm = !c->epollout;
    } else {
      c->outbuf.append(prefix, 4);
      c->outbuf.append(data, len);
      need_arm = !c->epollout;
    }
  }
  if (need_arm) {
    {
      std::lock_guard<std::mutex> g(ep->mu);
      ep->cmds.push_back(Cmd{kCmdArmWrite, conn});
    }
    Wake(ep);
  }
  return 0;
}

// Drain inbox records into buf:
//   u32 conn | u32 len | len bytes (header + payload)
// len == 0xFFFFFFFF marks a closed connection (no bytes follow).
// Returns bytes written; if the FIRST pending record exceeds cap,
// returns -(required capacity) so the caller can grow its buffer.
// Also consumes the notify-pipe signal.
int rpc_core_drain(void* handle, char* buf, int cap) {
  auto* ep = static_cast<Endpoint*>(handle);
  char scratch[64];
  while (::read(ep->notify_r, scratch, sizeof(scratch)) > 0) {
  }
  std::lock_guard<std::mutex> g(ep->mu);
  int n = 0;
  while (!ep->inbox.empty()) {
    InRec& rec = ep->inbox.front();
    int need = 8 + (rec.len == kClosedLen ? 0 : (int)rec.data.size());
    if (n + need > cap) {
      if (n == 0) return -need;
      break;
    }
    std::memcpy(buf + n, &rec.conn, 4);
    std::memcpy(buf + n + 4, &rec.len, 4);
    if (rec.len != kClosedLen) {
      std::memcpy(buf + n + 8, rec.data.data(), rec.data.size());
    }
    n += need;
    ep->inbox.pop_front();
  }
  return n;
}

// Request a local close of a connection (no close record is delivered —
// the caller initiated it).
void rpc_core_close_conn(void* handle, uint32_t conn) {
  auto* ep = static_cast<Endpoint*>(handle);
  {
    std::lock_guard<std::mutex> g(ep->mu);
    ep->cmds.push_back(Cmd{kCmdClose, conn});
  }
  Wake(ep);
}

// Stop the reactor and free everything. Must not race rpc_core_send.
void rpc_core_stop(void* handle) {
  auto* ep = static_cast<Endpoint*>(handle);
  // No reader pairs with this: stop is actually signaled via kCmdStop +
  // pthread_join below. Relaxed keeps the vestigial flag honest.
  ep->stopping.store(true, std::memory_order_relaxed);
  {
    std::lock_guard<std::mutex> g(ep->mu);
    ep->cmds.push_back(Cmd{kCmdStop, 0});
  }
  Wake(ep);
  if (ep->reactor_started) pthread_join(ep->reactor, nullptr);
  std::vector<std::shared_ptr<Conn>> conns;
  {
    std::lock_guard<std::mutex> g(ep->mu);
    for (auto& kv : ep->conns) conns.push_back(kv.second);
    ep->conns.clear();
  }
  for (auto& c : conns) {
    std::lock_guard<std::mutex> g(c->wmu);
    if (c->fd >= 0) {
      ::close(c->fd);
      c->fd = -1;
    }
    c->dead.store(true, std::memory_order_relaxed);  // under wmu
  }
  if (ep->listen_fd >= 0) ::close(ep->listen_fd);
  ::close(ep->epfd);
  ::close(ep->wake_r);
  ::close(ep->wake_w);
  ::close(ep->notify_r);
  ::close(ep->notify_w);
  delete ep;
}

}  // extern "C"
