// Native unit tests for the graftrpc reactor (rpc_core.cc). Same
// no-framework style as object_store_test.cc: plain asserts, built and
// run by `make rpc-test` (and under TSAN/ASAN in CI). Exercises the
// frame plane end to end: round-trips (small and multi-megabyte),
// byte-at-a-time split reads, concurrent bursts from several client
// threads with echo replies, write backpressure through the EPOLLOUT
// path, and peer-crash close records.

#undef NDEBUG
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <atomic>
#include <string>
#include <thread>
#include <vector>

#include <poll.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

extern "C" {
void* rpc_core_start(const char* listen_path, int* notify_fd_out);
int rpc_core_connect(void* handle, const char* path);
int rpc_core_send(void* handle, uint32_t conn, const char* data,
                  uint32_t len);
int rpc_core_drain(void* handle, char* buf, int cap);
void rpc_core_close_conn(void* handle, uint32_t conn);
void rpc_core_stop(void* handle);
}

namespace {

constexpr int kHdr = 12;  // u8 op | u8 flags | u16 chan | u64 seq
constexpr uint32_t kClosed = 0xFFFFFFFFu;

std::string Frame(uint8_t op, uint64_t seq, const std::string& payload) {
  std::string f(kHdr, '\0');
  f[0] = (char)op;
  uint16_t chan = 0;
  std::memcpy(&f[2], &chan, 2);
  std::memcpy(&f[4], &seq, 8);
  f += payload;
  return f;
}

struct Rec {
  uint32_t conn;
  uint32_t len;  // kClosed => close record
  std::string data;
};

// Drain every pending record (grows the buffer when a record exceeds it).
void DrainInto(void* ep, std::vector<Rec>* out) {
  static thread_local std::vector<char> buf(1 << 16);
  for (;;) {
    int n = rpc_core_drain(ep, buf.data(), (int)buf.size());
    if (n < 0) {
      buf.resize((size_t)(-n));
      continue;
    }
    int off = 0;
    while (off < n) {
      Rec r;
      std::memcpy(&r.conn, buf.data() + off, 4);
      std::memcpy(&r.len, buf.data() + off + 4, 4);
      off += 8;
      if (r.len != kClosed) {
        r.data.assign(buf.data() + off, r.len);
        off += (int)r.len;
      }
      out->push_back(std::move(r));
    }
    return;
  }
}

// Wait (poll on the notify fd, then drain) until `want` records arrived.
void WaitRecords(void* ep, int notify_fd, size_t want, std::vector<Rec>* out,
                 int timeout_ms = 10000) {
  int waited = 0;
  while (out->size() < want) {
    DrainInto(ep, out);
    if (out->size() >= want) break;
    pollfd p{notify_fd, POLLIN, 0};
    int rc = ::poll(&p, 1, 50);
    if (rc == 0) {
      waited += 50;
      assert(waited < timeout_ms && "timed out waiting for records");
    }
  }
}

std::string SockPath(const char* name) {
  return std::string("/tmp/raytpu_rpc_test_") + name + "_" +
         std::to_string(::getpid()) + ".sock";
}

void TestRoundTripAndEcho() {
  std::string sock = SockPath("echo");
  int srv_fd = -1, cli_fd = -1;
  void* srv = rpc_core_start(sock.c_str(), &srv_fd);
  assert(srv != nullptr);
  void* cli = rpc_core_start(nullptr, &cli_fd);  // connect-only endpoint
  assert(cli != nullptr);
  int conn = rpc_core_connect(cli, sock.c_str());
  assert(conn > 1);

  std::string f = Frame(1, 7, "ping-payload");
  assert(rpc_core_send(cli, (uint32_t)conn, f.data(), (uint32_t)f.size()) ==
         0);
  std::vector<Rec> got;
  WaitRecords(srv, srv_fd, 1, &got);
  assert(got[0].len == f.size() && got[0].data == f);
  assert(got[0].data[0] == 1);  // op
  uint64_t seq;
  std::memcpy(&seq, got[0].data.data() + 4, 8);
  assert(seq == 7);

  // Echo a reply on the server-side connection id.
  std::string reply = Frame(2, 7, "pong");
  assert(rpc_core_send(srv, got[0].conn, reply.data(),
                       (uint32_t)reply.size()) == 0);
  std::vector<Rec> back;
  WaitRecords(cli, cli_fd, 1, &back);
  assert(back[0].data == reply && back[0].conn == (uint32_t)conn);

  // Undersized (sub-header) and oversized frames are rejected up front.
  assert(rpc_core_send(cli, (uint32_t)conn, f.data(), 4) == -1);
  assert(rpc_core_send(cli, (uint32_t)conn, f.data(), (65u << 20)) == -1);

  rpc_core_stop(cli);
  rpc_core_stop(srv);
  ::unlink(sock.c_str());
  std::printf("  round-trip/echo OK\n");
}

void TestLargeFramesAndBackpressure() {
  // 24 x 1MiB frames back to back: far beyond any socket buffer, so the
  // sender's immediate-write fast path must hand leftovers to the
  // reactor's EPOLLOUT flush, and the receiver must reassemble frames
  // that arrive split across many reads.
  std::string sock = SockPath("large");
  int srv_fd = -1, cli_fd = -1;
  void* srv = rpc_core_start(sock.c_str(), &srv_fd);
  void* cli = rpc_core_start(nullptr, &cli_fd);
  assert(srv && cli);
  int conn = rpc_core_connect(cli, sock.c_str());
  assert(conn > 1);
  const int kFrames = 24;
  for (int i = 0; i < kFrames; i++) {
    std::string payload(1 << 20, (char)('a' + i));
    std::string f = Frame(1, (uint64_t)i, payload);
    assert(rpc_core_send(cli, (uint32_t)conn, f.data(),
                         (uint32_t)f.size()) == 0);
  }
  std::vector<Rec> got;
  WaitRecords(srv, srv_fd, kFrames, &got, 30000);
  assert(got.size() == (size_t)kFrames);
  for (int i = 0; i < kFrames; i++) {  // in order, intact
    uint64_t seq;
    std::memcpy(&seq, got[i].data.data() + 4, 8);
    assert(seq == (uint64_t)i);
    assert(got[i].data.size() == (size_t)kHdr + (1 << 20));
    assert(got[i].data[kHdr] == (char)('a' + i));
    assert(got[i].data.back() == (char)('a' + i));
  }
  rpc_core_stop(cli);
  rpc_core_stop(srv);
  ::unlink(sock.c_str());
  std::printf("  large/backpressure OK\n");
}

void TestSplitReads() {
  // A raw socket dribbling one frame a few bytes at a time: the reactor
  // must buffer partial prefixes/headers/payloads across reads.
  std::string sock = SockPath("split");
  int srv_fd = -1;
  void* srv = rpc_core_start(sock.c_str(), &srv_fd);
  assert(srv != nullptr);
  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  sockaddr_un addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  std::snprintf(addr.sun_path, sizeof(addr.sun_path), "%s", sock.c_str());
  assert(::connect(fd, (sockaddr*)&addr, sizeof(addr)) == 0);

  std::string f = Frame(3, 42, std::string(1000, 'z'));
  uint32_t len = (uint32_t)f.size();
  std::string wire(4, '\0');
  std::memcpy(&wire[0], &len, 4);
  wire += f;
  for (size_t off = 0; off < wire.size(); off += 3) {
    size_t n = std::min<size_t>(3, wire.size() - off);
    assert(::write(fd, wire.data() + off, n) == (ssize_t)n);
    if (off % 300 == 0) ::usleep(1000);
  }
  std::vector<Rec> got;
  WaitRecords(srv, srv_fd, 1, &got);
  assert(got[0].data == f);

  // A malformed length prefix (> 64MiB cap) drops the connection.
  uint32_t evil = 0x7FFFFFFFu;
  assert(::write(fd, &evil, 4) == 4);
  std::vector<Rec> closed;
  WaitRecords(srv, srv_fd, 1, &closed);
  assert(closed[0].len == kClosed && closed[0].conn == got[0].conn);
  ::close(fd);
  rpc_core_stop(srv);
  ::unlink(sock.c_str());
  std::printf("  split-reads OK\n");
}

void TestConcurrentClientsWithEchoes() {
  // 4 client endpoints (one per thread) x 200 frames each, with a server
  // thread echoing every frame back. Verifies per-connection ordering,
  // payload integrity, and that the locked inbox + command queue hold up
  // under concurrency (the TSAN target's main course).
  std::string sock = SockPath("burst");
  int srv_fd = -1;
  void* srv = rpc_core_start(sock.c_str(), &srv_fd);
  assert(srv != nullptr);
  std::atomic<bool> stop_echo{false};
  std::atomic<int> echoed{0};
  const int kThreads = 4, kEach = 200;
  std::thread echo([&] {
    std::vector<Rec> got;
    while (!stop_echo.load()) {
      got.clear();
      DrainInto(srv, &got);
      if (got.empty()) {
        pollfd p{srv_fd, POLLIN, 0};
        ::poll(&p, 1, 20);
        continue;
      }
      for (const Rec& r : got) {
        if (r.len == kClosed) continue;
        assert(rpc_core_send(srv, r.conn, r.data.data(),
                             (uint32_t)r.data.size()) == 0);
        echoed.fetch_add(1);
      }
    }
  });
  auto client = [&](int t) {
    int fd = -1;
    void* cli = rpc_core_start(nullptr, &fd);
    assert(cli != nullptr);
    int conn = rpc_core_connect(cli, sock.c_str());
    assert(conn > 1);
    std::vector<Rec> replies;
    for (int i = 0; i < kEach; i++) {
      std::string payload(64 + (i % 512), (char)('A' + t));
      std::string f = Frame(1, (uint64_t)i, payload);
      assert(rpc_core_send(cli, (uint32_t)conn, f.data(),
                           (uint32_t)f.size()) == 0);
    }
    WaitRecords(cli, fd, kEach, &replies, 30000);
    assert(replies.size() == (size_t)kEach);
    for (int i = 0; i < kEach; i++) {  // echoes return in send order
      uint64_t seq;
      std::memcpy(&seq, replies[i].data.data() + 4, 8);
      assert(seq == (uint64_t)i);
      assert(replies[i].data.size() == (size_t)kHdr + 64 + (i % 512));
      assert(replies[i].data[kHdr] == (char)('A' + t));
    }
    rpc_core_stop(cli);
  };
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; t++) ts.emplace_back(client, t);
  for (auto& th : ts) th.join();
  assert(echoed.load() == kThreads * kEach);
  stop_echo.store(true);
  echo.join();
  rpc_core_stop(srv);
  ::unlink(sock.c_str());
  std::printf("  concurrent-bursts OK\n");
}

void TestPeerCrashDeliversClose() {
  std::string sock = SockPath("crash");
  int srv_fd = -1, cli_fd = -1;
  void* srv = rpc_core_start(sock.c_str(), &srv_fd);
  void* cli = rpc_core_start(nullptr, &cli_fd);
  assert(srv && cli);
  int conn = rpc_core_connect(cli, sock.c_str());
  assert(conn > 1);
  std::string f = Frame(1, 1, "about-to-die");
  assert(rpc_core_send(cli, (uint32_t)conn, f.data(), (uint32_t)f.size()) ==
         0);
  std::vector<Rec> got;
  WaitRecords(srv, srv_fd, 1, &got);
  uint32_t srv_conn = got[0].conn;

  // "Crash" the client endpoint: the server must observe a close record
  // for its side of the connection, and replying must start failing.
  rpc_core_stop(cli);
  std::vector<Rec> closed;
  WaitRecords(srv, srv_fd, 1, &closed);
  assert(closed[0].conn == srv_conn && closed[0].len == kClosed);
  int rc = rpc_core_send(srv, srv_conn, f.data(), (uint32_t)f.size());
  assert(rc == -1);  // conn already reaped

  // Local close on the other direction: caller-initiated, no record.
  int conn2_fd = -1;
  void* cli2 = rpc_core_start(nullptr, &conn2_fd);
  int conn2 = rpc_core_connect(cli2, sock.c_str());
  assert(conn2 > 1);
  rpc_core_close_conn(cli2, (uint32_t)conn2);
  for (int i = 0; i < 100; i++) {
    if (rpc_core_send(cli2, (uint32_t)conn2, f.data(),
                      (uint32_t)f.size()) == -1) {
      break;
    }
    ::usleep(1000);
  }
  assert(rpc_core_send(cli2, (uint32_t)conn2, f.data(),
                       (uint32_t)f.size()) == -1);
  rpc_core_stop(cli2);
  rpc_core_stop(srv);
  ::unlink(sock.c_str());
  std::printf("  peer-crash/close OK\n");
}

}  // namespace

int main() {
  TestRoundTripAndEcho();
  TestLargeFramesAndBackpressure();
  TestSplitReads();
  TestConcurrentClientsWithEchoes();
  TestPeerCrashDeliversClose();
  std::printf("rpc_core_test: ALL OK\n");
  return 0;
}
