// Unit tests for the graftscope recorder (scope_core.cc). Run plain and
// under TSAN/ASAN in CI — the drain-while-writing test is the one the
// sanitizers care about: a torn read that escapes the lap check is a
// data race TSAN flags and a correctness bug this test flags.

#include "scope_core.h"

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,     \
                   #cond);                                             \
      std::abort();                                                    \
    }                                                                  \
  } while (0)

namespace {

struct Rec {
  uint8_t kind, op;
  uint16_t chan;
  uint32_t size;
  uint64_t seq_or_oid, t_ns;
};

std::vector<Rec> Drain() {
  std::vector<Rec> out;
  std::vector<char> buf(1 << 20);
  for (;;) {
    int n = scope_drain(buf.data(), (int)buf.size());
    CHECK(n >= 0);
    CHECK(n % kScopeRecordSize == 0);
    for (int i = 0; i < n; i += kScopeRecordSize) {
      ScopeWireRec w;
      std::memcpy(&w, buf.data() + i, kScopeRecordSize);
      out.push_back(Rec{w.kind, w.op, w.chan, w.size, w.seq_or_oid,
                        w.t_ns});
    }
    if (n == 0) return out;
  }
}

int TestRoundtrip() {
  Drain();  // discard anything earlier tests left behind
  scope_emit(kScopeRpcSend, 1, 0x1234, 99, 77, 5, 0);
  scope_emit(kScopeScEnd, 6, 0, 1000, 0xdeadbeef, 42, 1000);
  auto recs = Drain();
  CHECK(recs.size() == 2);
  CHECK(recs[0].kind == kScopeRpcSend);
  CHECK(recs[0].op == 1);
  CHECK(recs[0].chan == 0x1234);
  CHECK(recs[0].size == 99);
  CHECK(recs[0].seq_or_oid == 77);
  CHECK(recs[0].t_ns == 5);
  CHECK(recs[1].kind == kScopeScEnd);
  CHECK(recs[1].seq_or_oid == 0xdeadbeef);
  // t_ns == 0 stamps "now" from the monotonic clock.
  uint64_t before = scope_now_ns();
  scope_emit(kScopeRpcWake, 0, 0, 0, 0, 0, 0);
  uint64_t after = scope_now_ns();
  recs = Drain();
  CHECK(recs.size() == 1);
  CHECK(recs[0].t_ns >= before && recs[0].t_ns <= after);
  return 0;
}

int TestWraparound() {
  Drain();
  uint64_t dropped0 = scope_dropped();
  // 3x any plausible ring capacity: the drain must return only the
  // freshest window, count the rest as dropped, and keep seqs ordered.
  const uint64_t kN = 3 * 4096;
  for (uint64_t i = 0; i < kN; i++) {
    scope_emit(kScopeRpcSend, 1, 0, 8, i, 1, 0);
  }
  auto recs = Drain();
  CHECK(!recs.empty());
  CHECK(recs.size() < kN);
  CHECK(scope_dropped() - dropped0 == kN - recs.size());
  // Survivors are the most recent, in order.
  for (size_t i = 0; i < recs.size(); i++) {
    CHECK(recs[i].seq_or_oid == kN - recs.size() + i);
  }
  return 0;
}

int TestDrainWhileWriting() {
  Drain();
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> written{0};
  const int kWriters = 4;
  std::vector<std::thread> writers;
  for (int w = 0; w < kWriters; w++) {
    writers.emplace_back([&stop, &written, w] {
      uint64_t seq = 0;
      while (!stop.load(std::memory_order_relaxed)) {
        // seq encodes (writer, ordinal) so the drainer can check
        // per-writer monotonicity through wraparound.
        scope_emit(kScopeRpcSend, (uint8_t)(w + 1), (uint16_t)w, 24,
                   ((uint64_t)w << 48) | seq++, 1, 0);
        written.fetch_add(1, std::memory_order_relaxed);
      }
    });
  }
  uint64_t last_seq[kWriters] = {0};
  bool seen[kWriters] = {false};
  uint64_t got = 0;
  std::vector<char> buf(1 << 20);
  while (written.load() < 400000) {
    // One bounded drain pass per iteration (Drain()'s run-until-empty
    // loop could chase the writers forever).
    int n = scope_drain(buf.data(), (int)buf.size());
    CHECK(n >= 0 && n % kScopeRecordSize == 0);
    std::vector<Rec> recs;
    for (int i = 0; i < n; i += kScopeRecordSize) {
      ScopeWireRec w;
      std::memcpy(&w, buf.data() + i, kScopeRecordSize);
      recs.push_back(
          Rec{w.kind, w.op, w.chan, w.size, w.seq_or_oid, w.t_ns});
    }
    for (const Rec& r : recs) {
      CHECK(r.kind == kScopeRpcSend);
      int w = (int)(r.seq_or_oid >> 48);
      CHECK(w >= 0 && w < kWriters);
      CHECK(r.op == (uint8_t)(w + 1));
      CHECK(r.chan == (uint16_t)w);
      CHECK(r.size == 24);
      uint64_t seq = r.seq_or_oid & 0xFFFFFFFFFFFFull;
      if (seen[w]) CHECK(seq > last_seq[w]);
      last_seq[w] = seq;
      seen[w] = true;
      got++;
    }
  }
  stop.store(true);
  for (auto& t : writers) t.join();
  // On a 1-core host every write may land between two of the loop's
  // passes — the rings still hold the freshest window, so the final
  // drain validates and counts too.
  for (const Rec& r : Drain()) {
    CHECK(r.kind == kScopeRpcSend);
    int w = (int)(r.seq_or_oid >> 48);
    CHECK(w >= 0 && w < kWriters);
    uint64_t seq = r.seq_or_oid & 0xFFFFFFFFFFFFull;
    if (seen[w]) CHECK(seq > last_seq[w]);
    last_seq[w] = seq;
    seen[w] = true;
    got++;
  }
  CHECK(got > 0);
  return 0;
}

int TestDisable() {
  Drain();
  uint64_t calls0[3 * kScopeKindCount];
  scope_counters(calls0, kScopeKindCount);
  scope_set_enabled(0);
  CHECK(scope_enabled() == 0);
  scope_emit(kScopeRpcSend, 1, 0, 8, 1, 1, 0);
  scope_emit(kScopeCopyLink, 0, 0, 0, 0, 0, 0);
  CHECK(Drain().empty());
  uint64_t calls1[3 * kScopeKindCount];
  scope_counters(calls1, kScopeKindCount);
  for (int i = 0; i < 3 * kScopeKindCount; i++) CHECK(calls0[i] == calls1[i]);
  scope_set_enabled(1);
  CHECK(scope_enabled() == 1);
  scope_emit(kScopeRpcSend, 1, 0, 8, 2, 1, 0);
  CHECK(Drain().size() == 1);
  return 0;
}

int TestCounters() {
  scope_set_enabled(1);
  uint64_t c0[3 * kScopeKindCount];
  CHECK(scope_counters(c0, kScopeKindCount) == kScopeKindCount);
  scope_emit(kScopeCopyScatter, 0, 0, 1000, 10, 20, 7);
  scope_emit(kScopeCopyScatter, 0, 0, 500, 30, 40, 3);
  uint64_t c1[3 * kScopeKindCount];
  scope_counters(c1, kScopeKindCount);
  int k = kScopeCopyScatter;
  CHECK(c1[k * 3 + 0] - c0[k * 3 + 0] == 2);     // calls
  CHECK(c1[k * 3 + 1] - c0[k * 3 + 1] == 1500);  // bytes
  CHECK(c1[k * 3 + 2] - c0[k * 3 + 2] == 10);    // ns
  Drain();
  return 0;
}

int TestHistograms() {
  scope_set_enabled(1);
  uint64_t h0[kScopeHistBuckets * kScopeKindCount];
  CHECK(scope_histograms(h0, kScopeKindCount) == kScopeKindCount);
  int k = kScopeScEnd;
  // dur_ns == 0 must not touch the histogram (no duration recorded).
  scope_emit((uint8_t)k, 0, 0, 8, 1, 1, 0);
  // Sub-microsecond and ~1.5us land in bucket 0; each doubling above
  // 2^(shift+1) moves one bucket; huge durations clamp into the last.
  scope_emit((uint8_t)k, 0, 0, 8, 2, 1, 100);
  scope_emit((uint8_t)k, 0, 0, 8, 3, 1, 1500);
  scope_emit((uint8_t)k, 0, 0, 8, 4, 1, 1ull << (kScopeHistShift + 3));
  scope_emit((uint8_t)k, 0, 0, 8, 5, 1, 1ull << 62);
  uint64_t h1[kScopeHistBuckets * kScopeKindCount];
  scope_histograms(h1, kScopeKindCount);
  uint64_t* a = h0 + k * kScopeHistBuckets;
  uint64_t* b = h1 + k * kScopeHistBuckets;
  CHECK(b[0] - a[0] == 2);
  CHECK(b[3] - a[3] == 1);
  CHECK(b[kScopeHistBuckets - 1] - a[kScopeHistBuckets - 1] == 1);
  uint64_t total = 0;
  for (int i = 0; i < kScopeHistBuckets; i++) total += b[i] - a[i];
  CHECK(total == 4);
  // Disabled recorder leaves the histograms untouched too.
  scope_set_enabled(0);
  scope_emit((uint8_t)k, 0, 0, 8, 6, 1, 1500);
  uint64_t h2[kScopeHistBuckets * kScopeKindCount];
  scope_histograms(h2, kScopeKindCount);
  for (int i = 0; i < kScopeHistBuckets * kScopeKindCount; i++) {
    CHECK(h1[i] == h2[i]);
  }
  scope_set_enabled(1);
  Drain();
  return 0;
}

}  // namespace

int main() {
  scope_set_enabled(1);
  int rc = 0;
  rc |= TestRoundtrip();
  std::printf("scope roundtrip ok\n");
  rc |= TestCounters();
  std::printf("scope counters ok\n");
  rc |= TestHistograms();
  std::printf("scope histograms ok\n");
  rc |= TestWraparound();
  std::printf("scope wraparound ok\n");
  rc |= TestDisable();
  std::printf("scope disable ok\n");
  rc |= TestDrainWhileWriting();
  std::printf("scope drain-while-writing ok\n");
  if (rc == 0) std::printf("scope_core_test: ALL OK\n");
  return rc;
}
