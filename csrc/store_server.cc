// Native fast-path server + client for the shared-memory object store.
//
// TPU-native analogue of the reference's plasma store socket protocol
// (reference: src/ray/object_manager/plasma/{store_runner.cc,client.cc} —
// there the store IS a socket server speaking flatbuffers; here the
// Python agent's asyncio RPC remains the control plane while THIS
// sidecar carries the hot object ops). The agent starts one server
// thread inside its process sharing the native Store handle; workers
// connect a blocking unix-socket client and perform put(ingest)/get/
// release/delete/contains with ZERO Python or event-loop work on either
// side — the whole round-trip is two small socket writes between two C
// threads.
//
// The Python agent still owns object lifecycle bookkeeping (primary
// ledger, seal waiters, spill policy). A lock-protected EVENT JOURNAL
// records every ingest/delete the sidecar admits; a pipe byte wakes the
// agent's event loop, which drains the journal via store_server_drain()
// and applies the bookkeeping. Full-store ingests are REFUSED (rc -2):
// the worker falls back to the RPC path whose admission can spill.
//
// Wire format (little-endian, fixed header):
//   request : u8 op | 20B oid | u64 a | u64 b | u16 nlen | name[nlen]
//   response: i32 rc | u64 ds | u64 ms | u16 plen | path[plen]
// Ops: 1 INGEST(a=data_size, b=meta_size, name=ingest file)
//      2 GET (pins; pair with RELEASE)   3 RELEASE
//      4 DELETE                          5 CONTAINS (rc = 0/1/2)
//      6 PUT (a=data_size, b=meta_size, name=put-* staging file): the
//        fused graftcopy put — identical admission to INGEST (account,
//        evict, rename-in, pin, journal as an ingest) but for the
//        O_TMPFILE+linkat pipeline whose staging names derive from the
//        object id ("put-<oid hex>"), so the worker needs no
//        name-collision machinery at all. PUT and CONTAINS replies carry
//        the connection's cumulative DROP counters (seen, erased) in
//        their otherwise-unused ds/ms fields.
//      7 DROP: fire-and-forget DELETE — processed and journaled like op 4
//        but answered with NO reply frame; outcomes are reported via the
//        counters on the next PUT/CONTAINS reply.
//      8 SCOPE: drain this process's graftscope flight-recorder rings
//        into the reply's path field (rc = plen = bytes, a whole number
//        of 24-byte records; ds = records dropped so far, ms = recorder
//        enabled flag). Touches no store state — observability only, so
//        a slow scope reader never couples to the object data plane.
//      9 CREATE (a=data_size, b=meta_size): graftshm — allocate a
//        store-owned slab for the object, admit it STAGED (unsealed,
//        invisible to readers and eviction), and pass the slab's fd to
//        the client via SCM_RIGHTS immediately AFTER the reply frame
//        (only when rc == 0). The reply's path field carries the slab
//        path, ms carries a warm-slab-reuse flag. The client maps the
//        fd and serializes in place — no bulk copy phase exists.
//     10 SEAL: graftshm — publish a CREATEd object (staged -> sealed,
//        pinned as the primary copy), journaled as an ingest so the
//        agent's bookkeeping is op-agnostic. Reply carries the drop
//        counters like PUT. A connection that dies between CREATE and
//        SEAL gets its staged objects reclaimed (deleted + journaled)
//        on disconnect — the slab returns to the arena.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include <fcntl.h>
#include <pthread.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include "prof_core.h"
#include "scope_core.h"
#include "shm_core.h"

extern "C" {
// From object_store.cc (same shared library).
int store_ingest_object(void* handle, const char* id, const char* src_path,
                        uint64_t data_size, uint64_t meta_size, int pinned);
int store_get(void* handle, const char* id, char* out_path, int path_cap,
              uint64_t* data_size, uint64_t* meta_size);
int store_release(void* handle, const char* id);
int store_delete(void* handle, const char* id);
int store_contains(void* handle, const char* id);
int store_adopt_staged(void* handle, const char* id, const char* slab_path,
                       uint64_t data_size, uint64_t meta_size);
int store_seal_pin(void* handle, const char* id, uint64_t* total_out);
void store_set_slab_recycler(void* handle,
                             void (*fn)(void*, const char*, uint64_t),
                             void* ctx);
const char* store_dir_ref(void* handle);
uint64_t store_capacity(void* handle);
}

namespace {

constexpr int kIdSize = 20;
constexpr uint8_t kOpIngest = 1, kOpGet = 2, kOpRelease = 3,
                  kOpDelete = 4, kOpContains = 5, kOpPut = 6,
                  kOpDrop = 7, kOpScope = 8, kOpCreate = 9,
                  kOpSeal = 10;

// First 8 oid bytes as a little-endian u64 — enough entropy to match a
// native record back to the Python-side object id during stitching.
uint64_t Oid64(const char* oid) {
  uint64_t v;
  std::memcpy(&v, oid, 8);
  return v;
}

struct Event {       // journal entry: 30 bytes packed on drain
  uint8_t op;        // kOpIngest | kOpDelete | kOpCreate
  uint8_t origin;    // the wire op that caused it (grafttrail provenance:
                     // distinguishes shm seal / copy put / drop / staged
                     // reclaim behind the folded op)
  char oid[kIdSize];
  uint64_t size;
};

struct Server {
  void* store = nullptr;
  void* arena = nullptr;  // graftshm slab arena (owned; see stop())
  std::string dir;
  int listen_fd = -1;
  int notify_r = -1, notify_w = -1;  // pipe: journal nonempty signal
  pthread_t accept_thread;
  std::mutex mu;
  std::vector<Event> journal;
  std::vector<int> conn_fds;             // live connections (under mu)
  std::atomic<int> active_conns{0};      // ConnLoop threads running
  std::atomic<bool> stopping{false};
};

bool ReadFull(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool WriteFull(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

void Journal(Server* s, uint8_t op, uint8_t origin, const char* oid,
             uint64_t size) {
  bool was_empty;
  {
    std::lock_guard<std::mutex> g(s->mu);
    was_empty = s->journal.empty();
    Event e;
    e.op = op;
    e.origin = origin;
    std::memcpy(e.oid, oid, kIdSize);
    e.size = size;
    s->journal.push_back(e);
  }
  if (was_empty) {
    char b = 1;
    (void)!::write(s->notify_w, &b, 1);
  }
}

struct ConnArgs {
  Server* server;
  int fd;
};

void* ConnLoop(void* argp) {
  ConnArgs* args = static_cast<ConnArgs*>(argp);
  Server* s = args->server;
  int fd = args->fd;
  delete args;
  prof_register_thread("sidecar-conn");
  {
    std::lock_guard<std::mutex> g(s->mu);
    s->conn_fds.push_back(fd);
  }
  char oid[kIdSize];
  char name[512];
  char path[4096];
  // Per-connection pin ledger: a client that dies between GET and
  // RELEASE must not leak pins (the reference plasma store releases a
  // disconnected client's pins the same way).
  std::unordered_map<std::string, int> pins;
  // Cumulative fire-and-forget delete outcomes (kOpDrop). DROP writes no
  // reply; these counters ride the otherwise-unused ds/ms fields of the
  // next PUT reply so the client can settle its in-flight drop list with
  // zero extra wakeups.
  uint64_t drops_seen = 0, drops_erased = 0;
  // graftshm staged objects this client CREATEd but has not SEALed: if
  // the client dies mid-put, these are reclaimed on disconnect so no
  // slab leaks behind an invisible staged entry.
  std::unordered_set<std::string> staged;
  for (;;) {
    uint8_t op;
    uint64_t a, b;
    uint16_t nlen;
    if (!ReadFull(fd, &op, 1) || !ReadFull(fd, oid, kIdSize) ||
        !ReadFull(fd, &a, 8) || !ReadFull(fd, &b, 8) ||
        !ReadFull(fd, &nlen, 2)) {
      break;
    }
    if (nlen >= sizeof(name)) break;
    if (nlen && !ReadFull(fd, name, nlen)) break;
    name[nlen] = 0;

    // SCOPE requests are not themselves recorded: a drain loop that
    // produced a fresh record per drain would never run dry.
    uint64_t svc_t0 =
        scope_enabled() && op != kOpScope ? scope_now_ns() : 0;
    if (svc_t0 != 0) {
      uint32_t sz = a + b > 0xFFFFFFFFull ? 0xFFFFFFFFu : (uint32_t)(a + b);
      scope_emit(kScopeScBegin, op, 0, sz, Oid64(oid), svc_t0, 0);
    }
    int32_t rc = -1;
    uint64_t ds = 0, ms = 0;
    uint16_t plen = 0;
    int send_fd = -1;  // slab fd to pass after the reply (CREATE only)
    path[0] = 0;
    switch (op) {
      case kOpIngest:
      case kOpPut: {
        // Same validation as the agent RPC: relative staging-file names
        // only — a worker must not rename arbitrary paths in. INGEST
        // takes the legacy per-worker "ingest-" names; PUT takes the
        // oid-derived "put-" names of the graftcopy pipeline.
        const char* prefix = (op == kOpPut) ? "put-" : "ingest-";
        if (std::strncmp(name, prefix, std::strlen(prefix)) != 0 ||
            std::strchr(name, '/') != nullptr) {
          rc = -4;
          break;
        }
        std::string src = s->dir + "/" + name;
        rc = store_ingest_object(s->store, oid, src.c_str(), a, b,
                                 /*pinned=*/1);
        // Journaled as an ingest either way: the agent's bookkeeping
        // (primary ledger, seal waiters) is op-agnostic.
        if (rc == 0) {
          if (svc_t0 != 0) {
            // The staging file just became the store object (rename-in).
            scope_emit(kScopeScRename, op, 0,
                       a + b > 0xFFFFFFFFull ? 0xFFFFFFFFu
                                             : (uint32_t)(a + b),
                       Oid64(oid), 0, 0);
          }
          Journal(s, kOpIngest, op, oid, a + b);
        }
        if (op == kOpPut) {
          ds = drops_seen;
          ms = drops_erased;
        }
        break;
      }
      case kOpDrop:
        // Fire-and-forget delete: same semantics as DELETE but NO reply
        // frame, so a worker's put/drop loop costs one context-switch
        // cycle per iteration instead of two (a replied delete wakes
        // the client mid-pipeline and preempts the sidecar). Outcomes
        // accumulate into the per-connection counters above.
        drops_seen++;
        if (store_delete(s->store, oid) == 0) drops_erased++;
        Journal(s, kOpDelete, kOpDrop, oid, 0);
        if (svc_t0 != 0) {
          uint64_t t1 = scope_now_ns();
          uint64_t d = t1 - svc_t0;
          scope_emit(kScopeScEnd, op,
                     0, d > 0xFFFFFFFFull ? 0xFFFFFFFFu : (uint32_t)d,
                     Oid64(oid), t1, d);
        }
        continue;
      case kOpGet:
        rc = store_get(s->store, oid, path, sizeof(path), &ds, &ms);
        if (rc == 0) {
          plen = (uint16_t)std::strlen(path);
          pins[std::string(oid, kIdSize)]++;
        }
        break;
      case kOpRelease: {
        rc = store_release(s->store, oid);
        auto it = pins.find(std::string(oid, kIdSize));
        if (it != pins.end() && --it->second <= 0) pins.erase(it);
        break;
      }
      case kOpDelete:
        rc = store_delete(s->store, oid);
        staged.erase(std::string(oid, kIdSize));
        // Journal even when the store never had it (-1): the Python
        // agent may hold spill state for the oid that must drop too.
        Journal(s, kOpDelete, kOpDelete, oid, 0);
        break;
      case kOpCreate: {
        // graftshm: slab allocation + staged admission. -2 maps the
        // arena's clean ENOSPC (and the store's full-after-eviction)
        // onto the same code PUT uses, so the client's fallback logic
        // is shared.
        uint64_t total = a + b;
        int reused = 0;
        int sfd = shm_arena_acquire(s->arena, total, path, sizeof(path),
                                    &reused);
        if (sfd < 0) {
          rc = sfd == -2 ? -2 : -3;
          break;
        }
        rc = store_adopt_staged(s->store, oid, path, a, b);
        if (rc != 0) {
          ::close(sfd);
          shm_arena_recycle(s->arena, path, total);
          path[0] = 0;
          break;
        }
        staged.insert(std::string(oid, kIdSize));
        // grafttrail: a staged shm object now exists (unsealed); the
        // agent's ledger bookkeeping stays seal-driven, but the trail
        // wants creation provenance for conservation audits.
        Journal(s, kOpCreate, kOpCreate, oid, total);
        plen = (uint16_t)std::strlen(path);
        ms = (uint64_t)reused;
        send_fd = sfd;
        break;
      }
      case kOpSeal: {
        uint64_t total = 0;
        rc = store_seal_pin(s->store, oid, &total);
        // Journaled as an ingest: the agent's bookkeeping (primary
        // ledger, seal waiters) is op-agnostic, exactly like PUT.
        if (rc == 0) {
          staged.erase(std::string(oid, kIdSize));
          Journal(s, kOpIngest, kOpSeal, oid, total);
        }
        ds = drops_seen;
        ms = drops_erased;
        break;
      }
      case kOpContains:
        rc = store_contains(s->store, oid);
        // CONTAINS replies carry the drop counters too: the put plane
        // confirms staging-inode reuse with a contains round-trip, and
        // that same reply settles its in-flight drops.
        ds = drops_seen;
        ms = drops_erased;
        break;
      case kOpScope: {
        // Drain the recorder into the path field: a whole number of
        // records, bounded by the u16 plen (path cap 4096, NUL spare).
        int m = scope_drain(path, (int)sizeof(path) - 1);
        if (m < 0) m = 0;
        rc = m;
        plen = (uint16_t)m;
        ds = scope_dropped();
        ms = (uint64_t)scope_enabled();
        break;
      }
      default:
        rc = -5;
    }
    if (svc_t0 != 0) {
      // Span-in-one: size carries the service duration (ns, clipped) so
      // stitching needs no Begin/End pairing across thread rings.
      uint64_t t1 = scope_now_ns();
      uint64_t d = t1 - svc_t0;
      scope_emit(kScopeScEnd, op,
                 0, d > 0xFFFFFFFFull ? 0xFFFFFFFFu : (uint32_t)d,
                 Oid64(oid), t1, d);
    }
    if (!WriteFull(fd, &rc, 4) || !WriteFull(fd, &ds, 8) ||
        !WriteFull(fd, &ms, 8) || !WriteFull(fd, &plen, 2) ||
        (plen && !WriteFull(fd, path, plen))) {
      if (send_fd >= 0) ::close(send_fd);
      break;
    }
    if (send_fd >= 0) {
      // The slab fd rides AFTER the reply frame (SCM_RIGHTS needs its
      // own sendmsg; the client does recv-reply then recv-fd, in
      // order, only when rc == 0). The server's copy closes either
      // way — the client holds the only other reference.
      int ok = shm_send_fd(fd, send_fd);
      ::close(send_fd);
      if (ok != 0) break;
    }
  }
  // Reclaim staged graftshm objects this client never sealed: delete
  // returns the slab to the arena, and the journal tells the agent to
  // drop any bookkeeping it may have for the oid.
  for (const auto& key : staged) {
    store_delete(s->store, key.data());
    Journal(s, kOpDelete, kOpCreate, key.data(), 0);
  }
  // Release any pins this client still held (died mid GET..RELEASE).
  for (const auto& kv : pins) {
    for (int i = 0; i < kv.second; i++) {
      store_release(s->store, kv.first.data());
    }
  }
  {
    std::lock_guard<std::mutex> g(s->mu);
    for (size_t i = 0; i < s->conn_fds.size(); i++) {
      if (s->conn_fds[i] == fd) {
        s->conn_fds.erase(s->conn_fds.begin() + i);
        break;
      }
    }
  }
  ::close(fd);
  // acq_rel: the final fetch_sub publishes this thread's last touches
  // of *s to the acquire loads in store_server_stop, which may delete
  // the Server the moment the count hits zero.
  s->active_conns.fetch_sub(1, std::memory_order_acq_rel);
  return nullptr;
}

void* AcceptLoop(void* argp) {
  Server* s = static_cast<Server*>(argp);
  prof_register_thread("sidecar-accept");
  for (;;) {
    int fd = ::accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      // acquire pairs with stop()'s release store: everything stop()
      // did before raising the flag is visible here.
      if (s->stopping.load(std::memory_order_acquire)) return nullptr;
      continue;
    }
    if (s->stopping.load(std::memory_order_acquire)) {
      ::close(fd);
      return nullptr;
    }
    scope_emit(kScopeScAccept, 0, 0, 0, 0, 0, 0);
    auto* args = new ConnArgs{s, fd};
    s->active_conns.fetch_add(1, std::memory_order_acq_rel);
    pthread_t t;
    if (pthread_create(&t, nullptr, ConnLoop, args) == 0) {
      pthread_detach(t);
    } else {
      s->active_conns.fetch_sub(1, std::memory_order_acq_rel);
      ::close(fd);
      delete args;
    }
  }
}

// Trampoline: the store's EraseObject hands slab-backed paths here
// (under store.mu) and the arena free-lists them under its own mutex.
void ArenaRecycleTramp(void* ctx, const char* path, uint64_t size) {
  shm_arena_recycle(ctx, path, size);
}

}  // namespace

extern "C" {

// Starts the sidecar inside the agent process. Returns the server
// handle (NULL on failure); *notify_fd_out receives the read end of the
// journal-notification pipe (register with the event loop).
void* store_server_start(void* store_handle, const char* sock_path,
                         int* notify_fd_out) {
  auto* s = new Server();
  s->store = store_handle;
  s->dir = store_dir_ref(store_handle);
  // graftshm arena: retain up to a quarter of store capacity in
  // recycled slabs. Warm-slab reuse is the put-bandwidth win; the cap
  // bounds how much tmpfs the free list can hold back from eviction.
  s->arena = shm_arena_create(s->dir.c_str(),
                              store_capacity(store_handle) / 4);
  store_set_slab_recycler(store_handle, ArenaRecycleTramp, s->arena);
  int fds[2];
  if (::pipe(fds) != 0) {
    store_set_slab_recycler(store_handle, nullptr, nullptr);
    shm_arena_destroy(s->arena);
    delete s;
    return nullptr;
  }
  s->notify_r = fds[0];
  s->notify_w = fds[1];
  ::fcntl(s->notify_r, F_SETFL, O_NONBLOCK);
  s->listen_fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  sockaddr_un addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  std::snprintf(addr.sun_path, sizeof(addr.sun_path), "%s", sock_path);
  ::unlink(sock_path);
  if (::bind(s->listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      ::listen(s->listen_fd, 128) != 0) {
    ::close(s->listen_fd);
    ::close(fds[0]);
    ::close(fds[1]);
    store_set_slab_recycler(store_handle, nullptr, nullptr);
    shm_arena_destroy(s->arena);
    delete s;
    return nullptr;
  }
  if (pthread_create(&s->accept_thread, nullptr, AcceptLoop, s) != 0) {
    ::close(s->listen_fd);
    ::close(fds[0]);
    ::close(fds[1]);
    store_set_slab_recycler(store_handle, nullptr, nullptr);
    shm_arena_destroy(s->arena);
    delete s;
    return nullptr;
  }
  *notify_fd_out = s->notify_r;
  return s;
}

// Drain journal events into buf as 30-byte records (u8 op | u8 origin |
// 20B oid | u64 size). Returns bytes written. Also consumes the pipe
// signal.
int store_server_drain(void* handle, char* buf, int cap) {
  auto* s = static_cast<Server*>(handle);
  char scratch[64];
  while (::read(s->notify_r, scratch, sizeof(scratch)) > 0) {
  }  // notify_r is O_NONBLOCK: drains the wake bytes without blocking
  std::lock_guard<std::mutex> g(s->mu);
  int n = 0;
  size_t taken = 0;
  for (const Event& e : s->journal) {
    if (n + 30 > cap) break;
    buf[n] = (char)e.op;
    buf[n + 1] = (char)e.origin;
    std::memcpy(buf + n + 2, e.oid, kIdSize);
    std::memcpy(buf + n + 22, &e.size, 8);
    n += 30;
    taken++;
  }
  s->journal.erase(s->journal.begin(), s->journal.begin() + taken);
  return n;
}

// graftpulse: arena occupancy snapshot — out[0..2] = {free_bytes,
// free_slabs, reuses}. Three arena-mutex reads; called once per pulse
// tick from the node agent.
void store_server_shm_stats(void* handle, uint64_t* out) {
  auto* s = static_cast<Server*>(handle);
  out[0] = shm_arena_free_bytes(s->arena);
  out[1] = shm_arena_free_slabs(s->arena);
  out[2] = shm_arena_reuses(s->arena);
}

void store_server_stop(void* handle) {
  auto* s = static_cast<Server*>(handle);
  s->stopping.store(true, std::memory_order_release);
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  pthread_join(s->accept_thread, nullptr);
  // Kick every live connection out of its blocking read, then wait for
  // the detached handler threads to finish — freeing the Server while a
  // ConnLoop still references it would be a use-after-free.
  {
    std::lock_guard<std::mutex> g(s->mu);
    for (int fd : s->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  // acquire pairs with ConnLoop's final fetch_sub(acq_rel): observing 0
  // means every handler's last touch of *s happened-before the delete.
  for (int spins = 0;
       s->active_conns.load(std::memory_order_acquire) > 0 &&
       spins < 5000;
       spins++) {
    ::usleep(1000);
  }
  ::close(s->notify_r);
  ::close(s->notify_w);
  if (s->active_conns.load(std::memory_order_acquire) == 0) {
    // Unregister before destroying: a store op after stop() must not
    // call into a freed arena. (The store itself outlives the server —
    // the agent destroys it separately.)
    store_set_slab_recycler(s->store, nullptr, nullptr);
    shm_arena_destroy(s->arena);
    delete s;  // else: leak one Server rather than risk a UAF
  }
}

// ---------------------------------------------------------------------
// Blocking client (runs in worker processes; no event loop).
// ---------------------------------------------------------------------

int store_client_connect(const char* sock_path) {
  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_un addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  std::snprintf(addr.sun_path, sizeof(addr.sun_path), "%s", sock_path);
  if (::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

// Send half of a request: frames and writes one op without waiting for
// the reply. The server answers every request in order on the same
// connection, so a caller may pipeline — send a fire-and-forget op
// (delete), do useful work, and collect the reply with
// store_client_recv before the next request. 0 ok, -1 IO error (the
// connection is desynced; caller must reconnect).
int store_client_send(int fd, uint8_t op, const char* oid, uint64_t a,
                      uint64_t b, const char* name) {
  uint16_t nlen = name ? (uint16_t)std::strlen(name) : 0;
  char req[1 + kIdSize + 8 + 8 + 2];
  req[0] = (char)op;
  std::memcpy(req + 1, oid, kIdSize);
  std::memcpy(req + 21, &a, 8);
  std::memcpy(req + 29, &b, 8);
  std::memcpy(req + 37, &nlen, 2);
  if (!WriteFull(fd, req, sizeof(req))) return -1;
  if (nlen && !WriteFull(fd, name, nlen)) return -1;
  return 0;
}

// Receive half: blocks for exactly one reply. 0 ok, -1 IO error.
int store_client_recv(int fd, int32_t* rc_out, uint64_t* ds_out,
                      uint64_t* ms_out, char* path_out, int path_cap) {
  int32_t rc;
  uint64_t ds, ms;
  uint16_t plen;
  if (!ReadFull(fd, &rc, 4) || !ReadFull(fd, &ds, 8) ||
      !ReadFull(fd, &ms, 8) || !ReadFull(fd, &plen, 2)) {
    return -1;
  }
  if (plen >= path_cap) return -1;
  if (plen && !ReadFull(fd, path_out, plen)) return -1;
  path_out[plen] = 0;
  *rc_out = rc;
  *ds_out = ds;
  *ms_out = ms;
  return 0;
}

// Returns 0 on transport success (rc/ds/ms/path filled), -1 on IO error
// (caller should reconnect or fall back to the RPC path).
int store_client_request(int fd, uint8_t op, const char* oid, uint64_t a,
                         uint64_t b, const char* name, int32_t* rc_out,
                         uint64_t* ds_out, uint64_t* ms_out,
                         char* path_out, int path_cap) {
  if (store_client_send(fd, op, oid, a, b, name) != 0) return -1;
  return store_client_recv(fd, rc_out, ds_out, ms_out, path_out,
                           path_cap);
}

// graftshm CREATE round-trip: request a staged slab for the object and
// receive its fd. Returns 0 on transport success (*rc_out is the
// server's status; *slab_fd_out is a valid mapped-writable fd iff
// *rc_out == 0), -1 on IO error — including a failed fd-receive, after
// which the connection is desynced and the caller must reconnect.
int store_client_create(int fd, const char* oid, uint64_t data_size,
                        uint64_t meta_size, int32_t* rc_out,
                        uint64_t* reused_out, char* path_out, int path_cap,
                        int* slab_fd_out) {
  *slab_fd_out = -1;
  if (store_client_send(fd, kOpCreate, oid, data_size, meta_size,
                        nullptr) != 0) {
    return -1;
  }
  uint64_t ds = 0, ms = 0;
  if (store_client_recv(fd, rc_out, &ds, &ms, path_out, path_cap) != 0) {
    return -1;
  }
  *reused_out = ms;
  if (*rc_out != 0) return 0;  // no fd follows a non-zero reply
  int sfd = shm_recv_fd(fd);
  if (sfd < 0) return -1;
  *slab_fd_out = sfd;
  return 0;
}

// graftshm SEAL round-trip: publish a CREATEd object. Semantics of the
// return mirror store_client_request; the reply's ds/ms carry the
// connection's cumulative drop counters (like PUT).
int store_client_seal(int fd, const char* oid, int32_t* rc_out,
                      uint64_t* ds_out, uint64_t* ms_out) {
  char path[8];
  return store_client_request(fd, kOpSeal, oid, 0, 0, nullptr, rc_out,
                              ds_out, ms_out, path, sizeof(path));
}

void store_client_close(int fd) { ::close(fd); }

}  // extern "C"
