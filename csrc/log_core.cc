// graftlog emit path: crash-persistent MAP_SHARED log ring.
//
// Design constraints, in order (inherited from scope_core.cc, with one
// twist — the ring must survive its writer):
//   1. The record must be on the filesystem BEFORE the process can die:
//      the ring is a MAP_SHARED tmpfs file, so every store lands in the
//      page cache immediately; SIGKILL/OOM cannot unwrite it. No
//      fsync — tmpfs pages ARE the storage.
//   2. Losing records under overload is fine; corrupting them is not.
//      One writer per process (threads serialize on a spinlock), head
//      published with a release store, readers lap-check — torn records
//      are discarded by the reader, never surfaced.
//   3. Emitting must never block on I/O, locks held elsewhere, or the
//      reader: an agent tailing the file shares no lock with emit.
//
// No static destructors: globals are PODs/atomics only; the mapping is
// deliberately leaked at exit (the kernel unmaps, the file persists for
// salvage).

#include "log_core.h"

#include <atomic>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <stdlib.h>
#include <strings.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

inline void CpuRelax() {
#if defined(__x86_64__) || defined(__i386__)
  __builtin_ia32_pause();
#elif defined(__aarch64__)
  asm volatile("yield" ::: "memory");
#endif
}

struct SpinLock {
  std::atomic_flag f = ATOMIC_FLAG_INIT;
  void lock() {
    while (f.test_and_set(std::memory_order_acquire)) {
      CpuRelax();
    }
  }
  void unlock() { f.clear(std::memory_order_release); }
};
struct SpinGuard {
  SpinLock& l;
  explicit SpinGuard(SpinLock& lk) : l(lk) { l.lock(); }
  ~SpinGuard() { l.unlock(); }
};

uint64_t WallNs() {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

// File header at offset 0 (fixed offsets — the Python decoder reads
// these with struct, not this header definition).
#pragma pack(push, 1)
struct LogRingHeader {
  uint32_t magic;
  uint32_t version;
  uint32_t record_size;
  uint32_t slots;
  uint64_t pid;
  uint64_t head;     // records ever emitted; __atomic release store
  uint64_t dropped;  // emit-side losses, mirrored for salvage
  uint64_t start_ns;
  char pad[kLogHeaderSize - 48];
};
#pragma pack(pop)
static_assert(sizeof(LogRingHeader) == kLogHeaderSize, "header packing");

LogRingHeader* g_hdr = nullptr;  // published under g_emit_lock
char* g_base = nullptr;          // slot area (g_hdr + 1)
SpinLock g_emit_lock;            // serializes same-process emitters
uint64_t g_tail = 0;             // log_drain cursor, under g_drain_lock
SpinLock g_drain_lock;
std::atomic<uint64_t> g_dropped{0};  // emit-before-open + drain laps

std::atomic<int> g_enabled{-1};  // -1 = resolve from env on first use

int ResolveEnabled() {
  const char* v = getenv("RAY_TPU_GRAFTLOG");
  int on = 1;
  if (v != nullptr &&
      (strcmp(v, "0") == 0 || strcasecmp(v, "false") == 0 ||
       strcasecmp(v, "off") == 0 || strcasecmp(v, "no") == 0)) {
    on = 0;
  }
  int expected = -1;
  g_enabled.compare_exchange_strong(expected, on,
                                    std::memory_order_relaxed,
                                    std::memory_order_relaxed);
  return g_enabled.load(std::memory_order_relaxed);
}

void CopyPadded(char* dst, int cap, const char* src) {
  size_t n = src != nullptr ? strlen(src) : 0;
  if (n > (size_t)cap) n = (size_t)cap;
  if (n > 0) memcpy(dst, src, n);
  if ((int)n < cap) memset(dst + n, 0, (size_t)(cap - n));
}

}  // namespace

extern "C" {

int log_ring_open(const char* dir, uint64_t pid) {
  if (dir == nullptr) return -1;
  char path[512];
  int k = snprintf(path, sizeof(path), "%s/logring-%llu", dir,
                   (unsigned long long)pid);
  if (k <= 0 || (size_t)k >= sizeof(path)) return -1;
  size_t total =
      (size_t)kLogHeaderSize + (size_t)kLogRingSlots * kLogRecordSize;
  int fd = open(path, O_RDWR | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    unlink(path);
    return -1;
  }
  void* map =
      mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);  // the mapping keeps the file's pages reachable
  if (map == MAP_FAILED) {
    unlink(path);
    return -1;
  }
  auto* hdr = (LogRingHeader*)map;
  hdr->magic = (uint32_t)kLogMagic;
  hdr->version = (uint32_t)kLogRingVersion;
  hdr->record_size = (uint32_t)kLogRecordSize;
  hdr->slots = (uint32_t)kLogRingSlots;
  hdr->pid = pid;
  hdr->dropped = 0;
  hdr->start_ns = WallNs();
  __atomic_store_n(&hdr->head, 0, __ATOMIC_RELEASE);
  SpinGuard g(g_emit_lock);
  if (g_hdr != nullptr) {
    // Re-open (tests): drop the old mapping; its file was the caller's
    // to clean up.
    munmap((void*)g_hdr, total);
  }
  g_base = (char*)map + kLogHeaderSize;
  g_hdr = hdr;
  {
    SpinGuard dg(g_drain_lock);
    g_tail = 0;
  }
  return 0;
}

void log_ring_close(void) {
  SpinGuard g(g_emit_lock);
  if (g_hdr == nullptr) return;
  size_t total =
      (size_t)kLogHeaderSize + (size_t)kLogRingSlots * kLogRecordSize;
  munmap((void*)g_hdr, total);
  g_hdr = nullptr;
  g_base = nullptr;
}

uint64_t log_emit(int level, int source, const char* task,
                  const char* actor, const char* msg, int msg_len) {
  if (!log_enabled()) return 0;
  if (msg == nullptr) msg = "";
  if (msg_len < 0) msg_len = (int)strlen(msg);
  SpinGuard g(g_emit_lock);
  if (g_hdr == nullptr) {
    g_dropped.fetch_add(1, std::memory_order_relaxed);
    return 0;
  }
  uint64_t h = __atomic_load_n(&g_hdr->head, __ATOMIC_RELAXED);
  LogWireRec* rec =
      (LogWireRec*)(g_base +
                    (size_t)(h & (kLogRingSlots - 1)) * kLogRecordSize);
  rec->level = (uint8_t)(level < 0 ? 0 : level > 255 ? 255 : level);
  rec->source = (uint8_t)(source & 0xff);
  rec->line_len =
      (uint16_t)(msg_len > 0xffff ? 0xffff : msg_len);
  rec->seq = (uint32_t)(h + 1);
  rec->t_ns = WallNs();
  CopyPadded(rec->task, kLogTaskCap, task);
  CopyPadded(rec->actor, kLogActorCap, actor);
  int n = msg_len > kLogMsgCap ? kLogMsgCap : msg_len;
  if (n > 0) memcpy(rec->msg, msg, (size_t)n);
  if (n < kLogMsgCap) memset(rec->msg + n, 0, (size_t)(kLogMsgCap - n));
  // Publish: the record bytes land before the head moves, so a reader
  // that observes head >= h+1 sees a whole record (or lap-checks it
  // away). MAP_SHARED means these stores are already durable against
  // SIGKILL — the page cache outlives the process.
  __atomic_store_n(&g_hdr->head, h + 1, __ATOMIC_RELEASE);
  g_hdr->dropped = g_dropped.load(std::memory_order_relaxed);
  return h + 1;
}

uint64_t log_emit_batch(int level, int source, const char* task,
                        const char* actor, const char* lines, int len) {
  if (!log_enabled()) return 0;
  if (lines == nullptr || len <= 0) return 0;
  SpinGuard g(g_emit_lock);
  if (g_hdr == nullptr) {
    // Count the would-be records so the loss is visible.
    uint64_t n = 1;
    for (int i = 0; i < len; i++) n += lines[i] == '\n';
    g_dropped.fetch_add(n, std::memory_order_relaxed);
    return 0;
  }
  uint64_t t_ns = WallNs();
  char task_pad[kLogTaskCap], actor_pad[kLogActorCap];
  CopyPadded(task_pad, kLogTaskCap, task);
  CopyPadded(actor_pad, kLogActorCap, actor);
  uint8_t lvl = (uint8_t)(level < 0 ? 0 : level > 255 ? 255 : level);
  uint64_t h = __atomic_load_n(&g_hdr->head, __ATOMIC_RELAXED);
  const char* p = lines;
  const char* end = lines + len;
  while (p < end) {
    const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
    int mlen = (int)((nl != nullptr ? nl : end) - p);
    if (mlen > 0) {
      LogWireRec* rec =
          (LogWireRec*)(g_base +
                        (size_t)(h & (kLogRingSlots - 1)) *
                            kLogRecordSize);
      rec->level = lvl;
      rec->source = (uint8_t)(source & 0xff);
      rec->line_len = (uint16_t)(mlen > 0xffff ? 0xffff : mlen);
      rec->seq = (uint32_t)(h + 1);
      rec->t_ns = t_ns;
      memcpy(rec->task, task_pad, kLogTaskCap);
      memcpy(rec->actor, actor_pad, kLogActorCap);
      int n = mlen > kLogMsgCap ? kLogMsgCap : mlen;
      memcpy(rec->msg, p, (size_t)n);
      if (n < kLogMsgCap)
        memset(rec->msg + n, 0, (size_t)(kLogMsgCap - n));
      h++;
    }
    p = nl != nullptr ? nl + 1 : end;
  }
  uint64_t h0 = __atomic_load_n(&g_hdr->head, __ATOMIC_RELAXED);
  if (h == h0) return 0;  // batch was all empty lines
  // One publish for the whole batch: every record's bytes land before
  // the head moves, so a reader that observes the new head sees whole
  // records — same discipline as the single-record emit.
  __atomic_store_n(&g_hdr->head, h, __ATOMIC_RELEASE);
  g_hdr->dropped = g_dropped.load(std::memory_order_relaxed);
  return h;
}

int log_enabled(void) {
  int e = g_enabled.load(std::memory_order_relaxed);
  return e < 0 ? ResolveEnabled() : e;
}

void log_set_enabled(int on) {
  g_enabled.store(on ? 1 : 0, std::memory_order_relaxed);
}

int log_drain(char* buf, int cap) {
  SpinGuard dg(g_drain_lock);
  if (g_hdr == nullptr) return 0;
  int n = 0;
  uint64_t head = __atomic_load_n(&g_hdr->head, __ATOMIC_ACQUIRE);
  uint64_t t = g_tail;
  if (head - t >= kLogRingSlots) {
    uint64_t safe = head - kLogRingSlots + 1;
    g_dropped.fetch_add(safe - t, std::memory_order_relaxed);
    t = safe;
  }
  while (t < head) {
    if (n + kLogRecordSize > cap) break;
    memcpy(buf + n,
           g_base + (size_t)(t & (kLogRingSlots - 1)) * kLogRecordSize,
           kLogRecordSize);
    // Lap check: if the writer reached t + slots while we copied, the
    // slot may hold a half-written newer record — discard and skip to
    // the new safe window.
    uint64_t h2 = __atomic_load_n(&g_hdr->head, __ATOMIC_ACQUIRE);
    if (h2 - t >= kLogRingSlots) {
      uint64_t safe = h2 - kLogRingSlots + 1;
      g_dropped.fetch_add(safe - t, std::memory_order_relaxed);
      t = safe;
      head = h2;
      continue;
    }
    n += kLogRecordSize;
    t++;
  }
  g_tail = t;
  return n;
}

uint64_t log_emitted(void) {
  SpinGuard g(g_emit_lock);
  if (g_hdr == nullptr) return 0;
  return __atomic_load_n(&g_hdr->head, __ATOMIC_ACQUIRE);
}

uint64_t log_dropped(void) {
  return g_dropped.load(std::memory_order_relaxed);
}

}  // extern "C"
