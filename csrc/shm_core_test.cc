// Native unit tests for the graftshm slab arena and SCM_RIGHTS fd
// passing. Plain asserts, no framework (same convention as the other
// csrc suites); `make test` runs this plus TSAN/ASAN builds — the
// concurrent acquire/recycle storm below is the arena's race test.

#undef NDEBUG
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include "shm_core.h"

namespace {

std::string TempDir(const char* name) {
  std::string dir = std::string("/tmp/raytpu_shm_test_") + name + "_" +
                    std::to_string(::getpid());
  std::string cmd = "rm -rf " + dir + " && mkdir -p " + dir;
  assert(std::system(cmd.c_str()) == 0);
  return dir;
}

bool FileExists(const std::string& path) {
  struct stat st;
  return ::stat(path.c_str(), &st) == 0;
}

void TestAcquireRecycleReuse() {
  std::string dir = TempDir("reuse");
  void* a = shm_arena_create(dir.c_str(), 1 << 20);
  char p1[512], p2[512], p3[512];
  int reused = -1;

  int fd1 = shm_arena_acquire(a, 4096, p1, sizeof p1, &reused);
  assert(fd1 >= 0 && reused == 0 && FileExists(p1));
  // The slab really has its pages: write through a mapping.
  void* m = ::mmap(nullptr, 4096, PROT_READ | PROT_WRITE, MAP_SHARED, fd1, 0);
  assert(m != MAP_FAILED);
  std::memset(m, 'x', 4096);
  ::munmap(m, 4096);
  ::close(fd1);

  // Recycle, then an exact-size acquire reuses the SAME file (warm).
  shm_arena_recycle(a, p1, 4096);
  assert(shm_arena_free_bytes(a) == 4096);
  assert(shm_arena_free_slabs(a) == 1);
  int fd2 = shm_arena_acquire(a, 4096, p2, sizeof p2, &reused);
  assert(fd2 >= 0 && reused == 1);
  assert(std::strcmp(p1, p2) == 0);
  assert(shm_arena_free_bytes(a) == 0);
  assert(shm_arena_reuses(a) == 1);
  ::close(fd2);

  // A different size never matches the bucket: fresh slab.
  shm_arena_recycle(a, p2, 4096);
  int fd3 = shm_arena_acquire(a, 8192, p3, sizeof p3, &reused);
  assert(fd3 >= 0 && reused == 0);
  assert(std::strcmp(p3, p2) != 0);
  ::close(fd3);

  shm_arena_destroy(a);
  // destroy unlinks everything still on the free list.
  assert(!FileExists(p2));
  std::printf("  acquire/recycle/reuse OK\n");
}

void TestStaleFreeListEntry() {
  // Something (a directory sweeper) unlinked a free-listed slab behind
  // the arena's back: acquire must skip the stale entry and hand out a
  // fresh slab instead of failing.
  std::string dir = TempDir("stale");
  void* a = shm_arena_create(dir.c_str(), 1 << 20);
  char p1[512], p2[512];
  int reused = -1;
  int fd1 = shm_arena_acquire(a, 4096, p1, sizeof p1, &reused);
  assert(fd1 >= 0);
  ::close(fd1);
  shm_arena_recycle(a, p1, 4096);
  assert(::unlink(p1) == 0);  // sweeper strikes
  int fd2 = shm_arena_acquire(a, 4096, p2, sizeof p2, &reused);
  assert(fd2 >= 0 && reused == 0);
  assert(std::strcmp(p1, p2) != 0);
  ::close(fd2);
  shm_arena_destroy(a);
  std::printf("  stale free-list entry OK\n");
}

void TestRetentionCap() {
  // Free-bytes beyond the cap are bounded: the first over-cap recycle
  // parks in the single holdover slot (kept warm for an exact-size
  // re-acquire), the next one displaces it — never two slabs past cap.
  std::string dir = TempDir("cap");
  void* a = shm_arena_create(dir.c_str(), 8192);  // cap: two 4 KiB slabs
  char paths[4][512];
  int reused;
  for (int i = 0; i < 4; i++) {
    int fd = shm_arena_acquire(a, 4096, paths[i], sizeof paths[i], &reused);
    assert(fd >= 0);
    ::close(fd);
  }
  shm_arena_recycle(a, paths[0], 4096);
  shm_arena_recycle(a, paths[1], 4096);
  assert(shm_arena_free_bytes(a) == 8192);
  shm_arena_recycle(a, paths[2], 4096);  // over cap -> holdover slot
  assert(shm_arena_free_bytes(a) == 8192);  // holdover is off-books
  assert(FileExists(paths[2]));
  shm_arena_recycle(a, paths[3], 4096);  // displaces the holdover
  assert(!FileExists(paths[2]));
  assert(FileExists(paths[0]) && FileExists(paths[1]) &&
         FileExists(paths[3]));
  // The holdover serves exact-size acquires warm, like a bucket entry:
  // pop the two bucketed slabs, then the holdover must come back reused.
  char q[512];
  for (int i = 0; i < 3; i++) {
    reused = -1;
    int fd = shm_arena_acquire(a, 4096, q, sizeof q, &reused);
    assert(fd >= 0 && reused == 1);
    ::close(fd);
  }
  assert(std::strcmp(q, paths[3]) == 0);  // holdover drained last
  reused = -1;
  int fd = shm_arena_acquire(a, 4096, q, sizeof q, &reused);
  assert(fd >= 0 && reused == 0);  // everything drained: fresh slab
  ::close(fd);
  shm_arena_destroy(a);
  std::printf("  retention cap OK\n");
}

void TestEnospcIsClean() {
  // posix_fallocate of an absurd size must come back as the clean -2
  // (no fd leaked, no file left behind), never a sparse file that would
  // SIGBUS the mapped client later.
  std::string dir = TempDir("enospc");
  void* a = shm_arena_create(dir.c_str(), 1 << 20);
  char p[512];
  int reused;
  int rc = shm_arena_acquire(a, 1ull << 50, p, sizeof p, &reused);
  assert(rc == -2);
  // Directory holds no leftover slab.
  std::string probe = dir + "/shmslab-1";
  assert(!FileExists(probe));
  // The arena still works for sane sizes afterwards.
  int fd = shm_arena_acquire(a, 4096, p, sizeof p, &reused);
  assert(fd >= 0);
  ::close(fd);
  shm_arena_destroy(a);
  std::printf("  ENOSPC clean OK\n");
}

void TestFdPassing() {
  // SCM_RIGHTS round-trip over a socketpair: the received fd reads the
  // same inode the sender allocated.
  int sv[2];
  assert(::socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0);
  std::string dir = TempDir("fdpass");
  void* a = shm_arena_create(dir.c_str(), 1 << 20);
  char p[512];
  int reused;
  int slab_fd = shm_arena_acquire(a, 4096, p, sizeof p, &reused);
  assert(slab_fd >= 0);
  assert(::pwrite(slab_fd, "fd-pass-payload", 15, 0) == 15);

  std::thread sender([&] {
    assert(shm_send_fd(sv[0], slab_fd) == 0);
  });
  int got = shm_recv_fd(sv[1]);
  sender.join();
  assert(got >= 0 && got != slab_fd);
  char buf[16] = {0};
  assert(::pread(got, buf, 15, 0) == 15);
  assert(std::memcmp(buf, "fd-pass-payload", 15) == 0);
  // Same inode, two descriptors.
  struct stat st1, st2;
  assert(::fstat(slab_fd, &st1) == 0 && ::fstat(got, &st2) == 0);
  assert(st1.st_ino == st2.st_ino);
  ::close(got);
  ::close(slab_fd);
  ::close(sv[0]);
  ::close(sv[1]);
  shm_arena_destroy(a);
  std::printf("  fd passing OK\n");
}

void TestRecvOnClosedPeer() {
  // Peer death mid-handshake: recv must fail cleanly, not hang or
  // fabricate an fd.
  int sv[2];
  assert(::socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0);
  ::close(sv[0]);
  assert(shm_recv_fd(sv[1]) == -1);
  ::close(sv[1]);
  std::printf("  recv-on-closed-peer OK\n");
}

void TestConcurrentAcquireRecycle() {
  // The TSAN target: several threads hammering acquire/recycle on the
  // same sizes. Every acquire must yield a usable fd; accounting must
  // come back consistent once everything is recycled.
  std::string dir = TempDir("storm");
  void* a = shm_arena_create(dir.c_str(), 1 << 22);
  auto worker = [&](int t) {
    char p[512];
    int reused;
    uint64_t size = 4096 * (1 + (t % 2));  // two bucket sizes
    for (int i = 0; i < 200; i++) {
      int fd = shm_arena_acquire(a, size, p, sizeof p, &reused);
      assert(fd >= 0);
      assert(::pwrite(fd, &t, sizeof t, 0) == (ssize_t)sizeof t);
      ::close(fd);
      shm_arena_recycle(a, p, size);
    }
  };
  std::vector<std::thread> ts;
  for (int t = 0; t < 4; t++) ts.emplace_back(worker, t);
  for (auto& th : ts) th.join();
  // All slabs are back on the free list; none leaked.
  assert(shm_arena_free_slabs(a) >= 2);
  assert(shm_arena_free_bytes(a) <= (uint64_t)(1 << 22));
  shm_arena_destroy(a);
  std::printf("  concurrent acquire/recycle OK\n");
}

}  // namespace

int main() {
  TestAcquireRecycleReuse();
  TestStaleFreeListEntry();
  TestRetentionCap();
  TestEnospcIsClean();
  TestFdPassing();
  TestRecvOnClosedPeer();
  TestConcurrentAcquireRecycle();
  std::printf("shm_core_test: ALL OK\n");
  return 0;
}
