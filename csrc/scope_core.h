// graftscope: lock-free flight recorder for the native planes.
//
// Shared contract between the recorder (scope_core.cc), the instrumented
// planes (rpc_core.cc, copy_core.cc, store_server.cc) and the Python
// decoder (ray_tpu/core/_native/graftscope.py). The wire record layout
// and the kind table below are lint-checked against the Python constants
// (tools/lint/wire_schema.py pass 3e) — keep both sides in sync.
//
// Wire record (little-endian, fixed width):
//   u8 kind | u8 op | u16 chan | u32 size | u64 seq_or_oid | u64 t_ns
//
// Span-in-one kinds carry their interval inside one record (no pairing
// needed across thread rings):
//   RpcFlush   : seq_or_oid = start_ns, t_ns = end_ns, size = bytes
//   CopyScatter: seq_or_oid = start_ns, t_ns = end_ns, size = bytes
//   ScEnd      : seq_or_oid = oid64,    t_ns = end_ns, size = dur_ns
// Point kinds timestamp a single instant (t_ns), with seq_or_oid
// carrying the frame seq (Rpc*) or the first 8 oid bytes (Sc*).

#ifndef RAY_TPU_SCOPE_CORE_H_
#define RAY_TPU_SCOPE_CORE_H_

#include <cstdint>

#pragma pack(push, 1)
struct ScopeWireRec {  // 24 bytes on the wire, little-endian
  uint8_t kind;
  uint8_t op;
  uint16_t chan;
  uint32_t size;
  uint64_t seq_or_oid;
  uint64_t t_ns;
};
#pragma pack(pop)

constexpr int kScopeRecordSize = 24;
static_assert(sizeof(ScopeWireRec) == kScopeRecordSize, "record packing");

// Record kinds. Mirrored by KIND_* in graftscope.py (lint pass 3e).
[[maybe_unused]] constexpr uint8_t kScopeRpcSend = 1, kScopeRpcRecv = 2,
                                   kScopeRpcFlush = 3, kScopeRpcWake = 4,
                                   kScopeCopyScatter = 5, kScopeCopyLink = 6,
                                   kScopeScAccept = 7, kScopeScBegin = 8,
                                   kScopeScEnd = 9, kScopeScRename = 10;
[[maybe_unused]] constexpr int kScopeKindCount = 11;  // 1 + highest kind

// Per-kind log2 latency histograms (graftpulse). Bucket b counts emits
// whose dur_ns landed in [2^(kScopeHistShift+b), 2^(kScopeHistShift+b+1)),
// with both tails clamped: bucket 0 also absorbs anything below
// 2^(kScopeHistShift+1) ns and the last bucket absorbs everything above.
// Mirrored by PULSE_HIST_* in graftpulse.py (lint pass 3f).
[[maybe_unused]] constexpr int kScopeHistBuckets = 16;
[[maybe_unused]] constexpr int kScopeHistShift = 10;  // bucket 0 ~= 1us

// graftpulse wire record: the fixed-size header of one node pulse,
// assembled by the node agent each tick and decoded by the controller
// (ray_tpu/core/_native/graftpulse.py). The header is followed by
// kind_count * (3 + kScopeHistBuckets) little-endian u64s: per kind the
// {calls, bytes, ns} counter deltas then the histogram bucket deltas.
// Lint pass 3f keeps both sides in sync.
#pragma pack(push, 1)
struct PulseWireRec {  // 104 bytes on the wire, little-endian
  uint32_t magic;         // 'PLSE' = 0x45534c50
  uint16_t version;
  uint16_t kind_count;    // scope kinds in the trailing payload
  uint64_t seq;           // per-node pulse sequence number
  uint64_t t_mono_ns;     // scope_now_ns() at assembly
  uint64_t t_wall_ns;     // wall clock at assembly
  uint64_t store_used;
  uint64_t store_capacity;
  uint32_t store_objects;
  uint32_t shm_free_chunks;  // graftshm free-list depth
  uint64_t shm_arena_bytes;  // graftshm arena occupancy
  uint32_t num_workers;
  uint32_t queue_depth;      // leases queued + running across workers
  uint64_t rss_bytes;        // summed worker RSS
  uint64_t scope_dropped;
  uint64_t events_dropped;
  uint32_t prof_oncpu_permille;  // graftprof: worker on-CPU share, 0..1000
  uint32_t prof_gil_permille;    // graftprof: GIL-wait share, 0..1000
};
#pragma pack(pop)

// v2 appended the two graftprof permille gauges (was 96 bytes at v1).
// Widening this struct without bumping kPulseVersion is a lint error
// (pass 3f keeps a version -> size registry on both sides).
constexpr int kPulseRecordSize = 104;
static_assert(sizeof(PulseWireRec) == kPulseRecordSize, "pulse packing");
[[maybe_unused]] constexpr uint32_t kPulseMagic = 0x45534c50;
[[maybe_unused]] constexpr uint16_t kPulseVersion = 2;
// Version -> header size, one row per wire revision ever shipped.
// Append-only; the current version's row must equal kPulseRecordSize.
// Mirrored by PULSE_VERSION_SIZES in graftpulse.py (lint pass 3f).
[[maybe_unused]] constexpr int kPulseVersionSizes[][2] = {
    {1, 96},   // v1: through events_dropped
    {2, 104},  // v2: + graftprof on-CPU / GIL permille gauges
};

extern "C" {

// Hot-path emit: appends one record to the calling thread's ring and
// bumps the per-kind counter block (calls += 1, bytes += size,
// ns += dur_ns). t_ns == 0 means "stamp with scope_now_ns() here".
// No-op (one relaxed load) while the recorder is disabled.
void scope_emit(uint8_t kind, uint8_t op, uint16_t chan, uint32_t size,
                uint64_t seq_or_oid, uint64_t t_ns, uint64_t dur_ns);

// 1 while recording. Default comes from RAY_TPU_GRAFTSCOPE (unset/1 =
// on, "0"/"false"/"off"/"no" = off), resolved once on first use.
int scope_enabled(void);
void scope_set_enabled(int on);

// CLOCK_MONOTONIC in ns — system-wide on Linux, so records from every
// process on a host share one clock domain.
uint64_t scope_now_ns(void);

// Drain every thread ring into buf as kScopeRecordSize-byte records.
// Returns bytes written (a multiple of the record size). Safe against
// concurrent writers and concurrent drainers (drain holds an internal
// mutex; writers never block).
int scope_drain(char* buf, int cap);

// Copy the cumulative counter block: out[3k..3k+2] = {calls, bytes, ns}
// for kind k. Writes min(max_kinds, kScopeKindCount) kinds; returns the
// number written.
int scope_counters(uint64_t* out, int max_kinds);

// Copy the cumulative log2 latency histograms: out[16k..16k+15] = the
// kScopeHistBuckets bucket counts for kind k. Writes
// min(max_kinds, kScopeKindCount) kinds; returns the number written.
int scope_histograms(uint64_t* out, int max_kinds);

// Records lost to ring wraparound or slot exhaustion since process
// start.
uint64_t scope_dropped(void);

}  // extern "C"

#endif  // RAY_TPU_SCOPE_CORE_H_
