// graftscope recorder: per-thread lock-free ring buffers + cumulative
// per-kind counters for the native planes (SURVEY §5 — the reference
// splits the same way: a lock-cheap C++ stats layer in src/ray/stats/
// feeding a per-node exporter; here the rings feed the node agent's
// metrics tick and the stitched timeline).
//
// Design constraints, in order:
//   1. The write path must cost nanoseconds and never block — it sits
//      inside rpc_core_send (20k calls/s) and the sidecar service loop.
//      Each thread owns one ring (single writer); a record is three
//      relaxed u64 stores plus one release store of the head. No CAS,
//      no lock, no allocation.
//   2. Losing records under overload is fine; corrupting them is not.
//      The drainer detects writer lap-over by re-reading the head after
//      copying a record and discards anything the writer may have been
//      overwriting (counted in scope_dropped()).
//   3. Draining is cold (metrics tick, tests, OP_SCOPE) — it takes a
//      mutex against other drainers, never against writers.
//
// Ring slots are leased per thread and recycled on thread exit via a
// thread_local destructor, so long-lived processes with churning
// sidecar connection threads don't exhaust the table.

#include "scope_core.h"

#include <atomic>
#include <cstring>
#include <ctime>

#include <stdlib.h>
#include <strings.h>

namespace {

constexpr int kRingSlots = 64;       // max concurrently recording threads
constexpr uint64_t kRingCap = 2048;  // records per ring (power of two)

// One record = 3 words: w0 packs kind|op|chan|size, w1 = seq_or_oid,
// w2 = t_ns. Stored as atomics so a concurrent drainer reading a slot
// mid-overwrite is a benign (detected) race, not UB — the lap check
// below discards the torn copy.
struct ScopeRing {
  std::atomic<uint64_t> head{0};  // next absolute record index
  uint64_t tail = 0;              // drainer cursor (under g_drain_mu)
  std::atomic<uint64_t> w[kRingCap * 3];
};

// All recorder globals are PODs or atomics with trivial destructors:
// detached sidecar threads may run their thread_local SlotLease
// destructor after main() returns, so nothing here may be torn down by
// a static destructor (a std::vector free list here is a TSAN-visible
// shutdown race). Cold-path mutual exclusion uses atomic_flag
// spinlocks for the same reason.
// Hint the core that we are spinning: keeps an SMT sibling (often the
// flag holder) from being starved and cuts the spin's power draw.
inline void CpuRelax() {
#if defined(__x86_64__) || defined(__i386__)
  __builtin_ia32_pause();
#elif defined(__aarch64__)
  asm volatile("yield" ::: "memory");
#endif
}

struct SpinLock {
  std::atomic_flag f = ATOMIC_FLAG_INIT;
  void lock() {
    while (f.test_and_set(std::memory_order_acquire)) {
      CpuRelax();
    }
  }
  void unlock() { f.clear(std::memory_order_release); }
};
struct SpinGuard {
  SpinLock& l;
  explicit SpinGuard(SpinLock& lk) : l(lk) { l.lock(); }
  ~SpinGuard() { l.unlock(); }
};

ScopeRing g_rings[kRingSlots];
std::atomic<int> g_high_water{0};  // slots ever handed out
SpinLock g_slot_lock;              // slot lease/recycle (thread birth/death)
int g_free_slots[kRingSlots];      // stack of recycled slots
int g_free_count = 0;              // both under g_slot_lock
SpinLock g_drain_lock;             // serializes drainers
std::atomic<uint64_t> g_dropped{0};
std::atomic<uint64_t> g_counters[kScopeKindCount][3];  // calls, bytes, ns
std::atomic<uint64_t> g_hist[kScopeKindCount][kScopeHistBuckets];

// Log2 bucket of a duration: 0 for anything under 2^(shift+1) ns, then
// one bucket per doubling, clamped into the last bucket. Branch-free
// except the two clamps; one clz on the hot path.
inline int HistBucket(uint64_t dur_ns) {
  uint64_t v = dur_ns >> kScopeHistShift;
  if (v < 2) return 0;
  int b = 63 - __builtin_clzll(v);
  return b < kScopeHistBuckets ? b : kScopeHistBuckets - 1;
}

std::atomic<int> g_enabled{-1};  // -1 = resolve from env on first use

int ResolveEnabled() {
  const char* v = getenv("RAY_TPU_GRAFTSCOPE");
  int on = 1;
  if (v != nullptr &&
      (strcmp(v, "0") == 0 || strcasecmp(v, "false") == 0 ||
       strcasecmp(v, "off") == 0 || strcasecmp(v, "no") == 0)) {
    on = 0;
  }
  // Pure flag, no payload to publish: relaxed on both outcomes.
  int expected = -1;
  g_enabled.compare_exchange_strong(expected, on,
                                    std::memory_order_relaxed,
                                    std::memory_order_relaxed);
  return g_enabled.load(std::memory_order_relaxed);
}

// Recycle the slot when the thread exits so its ring (and any undrained
// records in it) can serve the next thread.
struct SlotLease {
  int slot = -1;
  ~SlotLease() {
    if (slot >= 0) {
      SpinGuard g(g_slot_lock);
      g_free_slots[g_free_count++] = slot;
    }
  }
};
thread_local SlotLease t_lease;

ScopeRing* CurRing() {
  if (t_lease.slot >= 0) return &g_rings[t_lease.slot];
  SpinGuard g(g_slot_lock);
  int s;
  if (g_free_count > 0) {
    s = g_free_slots[--g_free_count];
  } else {
    s = g_high_water.load(std::memory_order_relaxed);
    if (s >= kRingSlots) return nullptr;  // exhausted: counters only
    g_high_water.store(s + 1, std::memory_order_release);
  }
  t_lease.slot = s;
  return &g_rings[s];
}

}  // namespace

extern "C" {

int scope_enabled(void) {
  int e = g_enabled.load(std::memory_order_relaxed);
  return e < 0 ? ResolveEnabled() : e;
}

void scope_set_enabled(int on) {
  g_enabled.store(on ? 1 : 0, std::memory_order_relaxed);
}

uint64_t scope_now_ns(void) {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

void scope_emit(uint8_t kind, uint8_t op, uint16_t chan, uint32_t size,
                uint64_t seq_or_oid, uint64_t t_ns, uint64_t dur_ns) {
  if (!scope_enabled()) return;
  if (kind >= kScopeKindCount) return;
  g_counters[kind][0].fetch_add(1, std::memory_order_relaxed);
  g_counters[kind][1].fetch_add(size, std::memory_order_relaxed);
  if (dur_ns) {
    g_counters[kind][2].fetch_add(dur_ns, std::memory_order_relaxed);
    g_hist[kind][HistBucket(dur_ns)].fetch_add(1,
                                               std::memory_order_relaxed);
  }
  ScopeRing* r = CurRing();
  if (r == nullptr) {
    g_dropped.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  if (t_ns == 0) t_ns = scope_now_ns();
  uint64_t w0 = (uint64_t)kind | ((uint64_t)op << 8) |
                ((uint64_t)chan << 16) | ((uint64_t)size << 32);
  uint64_t h = r->head.load(std::memory_order_relaxed);
  size_t i = (size_t)(h & (kRingCap - 1)) * 3;
  r->w[i].store(w0, std::memory_order_relaxed);
  r->w[i + 1].store(seq_or_oid, std::memory_order_relaxed);
  r->w[i + 2].store(t_ns, std::memory_order_relaxed);
  r->head.store(h + 1, std::memory_order_release);
}

int scope_drain(char* buf, int cap) {
  SpinGuard dg(g_drain_lock);
  int n = 0;
  int slots = g_high_water.load(std::memory_order_acquire);
  for (int s = 0; s < slots; s++) {
    ScopeRing* r = &g_rings[s];
    uint64_t head = r->head.load(std::memory_order_acquire);
    uint64_t t = r->tail;
    // Only records in (head - cap, head) are guaranteed un-overwritten;
    // the writer may be mid-store into slot (head - cap) right now.
    if (head - t >= kRingCap) {
      uint64_t safe = head - kRingCap + 1;
      g_dropped.fetch_add(safe - t, std::memory_order_relaxed);
      t = safe;
    }
    while (t < head) {
      if (n + kScopeRecordSize > cap) break;
      size_t i = (size_t)(t & (kRingCap - 1)) * 3;
      uint64_t w0 = r->w[i].load(std::memory_order_relaxed);
      uint64_t w1 = r->w[i + 1].load(std::memory_order_relaxed);
      uint64_t w2 = r->w[i + 2].load(std::memory_order_relaxed);
      // Lap check: if the writer reached t + cap while we copied, the
      // slot may hold a half-written newer record — discard and skip to
      // the new safe window.
      uint64_t h2 = r->head.load(std::memory_order_acquire);
      if (h2 - t >= kRingCap) {
        uint64_t safe = h2 - kRingCap + 1;
        g_dropped.fetch_add(safe - t, std::memory_order_relaxed);
        t = safe;
        head = h2;
        continue;
      }
      ScopeWireRec rec;
      rec.kind = (uint8_t)(w0 & 0xff);
      rec.op = (uint8_t)((w0 >> 8) & 0xff);
      rec.chan = (uint16_t)((w0 >> 16) & 0xffff);
      rec.size = (uint32_t)(w0 >> 32);
      rec.seq_or_oid = w1;
      rec.t_ns = w2;
      std::memcpy(buf + n, &rec, kScopeRecordSize);
      n += kScopeRecordSize;
      t++;
    }
    r->tail = t;
    if (n + kScopeRecordSize > cap) break;
  }
  return n;
}

int scope_counters(uint64_t* out, int max_kinds) {
  int k = max_kinds < kScopeKindCount ? max_kinds : kScopeKindCount;
  for (int i = 0; i < k; i++) {
    out[i * 3 + 0] = g_counters[i][0].load(std::memory_order_relaxed);
    out[i * 3 + 1] = g_counters[i][1].load(std::memory_order_relaxed);
    out[i * 3 + 2] = g_counters[i][2].load(std::memory_order_relaxed);
  }
  return k;
}

int scope_histograms(uint64_t* out, int max_kinds) {
  int k = max_kinds < kScopeKindCount ? max_kinds : kScopeKindCount;
  for (int i = 0; i < k; i++) {
    for (int b = 0; b < kScopeHistBuckets; b++) {
      out[i * kScopeHistBuckets + b] =
          g_hist[i][b].load(std::memory_order_relaxed);
    }
  }
  return k;
}

uint64_t scope_dropped(void) {
  return g_dropped.load(std::memory_order_relaxed);
}

}  // extern "C"
