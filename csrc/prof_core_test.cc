// Unit tests for the graftprof sampler (prof_core.cc). Run plain and
// under TSAN/ASAN in CI — the drain-while-sampling test exercises the
// single-writer ring against a concurrent drainer, and the
// registration storm exercises the slot table against the sampler's
// scan.

#include "prof_core.h"

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <string>
#include <thread>
#include <vector>

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,     \
                   #cond);                                             \
      std::abort();                                                    \
    }                                                                  \
  } while (0)

namespace {

struct Rec {
  uint8_t kind, slot;
  uint16_t flags;
  uint32_t val_us;
  uint64_t tick, t_ns;
};

std::vector<Rec> DrainOnce() {
  std::vector<Rec> out;
  std::vector<char> buf(1 << 20);
  int n = prof_drain(buf.data(), (int)buf.size());
  CHECK(n >= 0);
  CHECK(n % kProfRecordSize == 0);
  for (int i = 0; i < n; i += kProfRecordSize) {
    ProfWireRec w;
    std::memcpy(&w, buf.data() + i, kProfRecordSize);
    out.push_back(Rec{w.kind, w.slot, w.flags, w.val_us, w.tick, w.t_ns});
  }
  return out;
}

std::vector<Rec> Drain() {
  std::vector<Rec> out;
  for (;;) {
    auto recs = DrainOnce();
    if (recs.empty()) return out;
    out.insert(out.end(), recs.begin(), recs.end());
  }
}

void SleepMs(int ms) {
  timespec req;
  req.tv_sec = ms / 1000;
  req.tv_nsec = (long)(ms % 1000) * 1000000L;
  nanosleep(&req, nullptr);
}

uint64_t MonoNs() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

// Fake GIL: ensure() burns ~200us before "acquiring" so the probe has
// a contended wait to measure; release() checks the state cookie made
// the round trip.
std::atomic<uint64_t> g_fake_releases{0};

int FakeEnsure() {
  uint64_t t0 = MonoNs();
  while (MonoNs() - t0 < 200 * 1000) {
  }
  return 7;
}

void FakeRelease(int st) {
  if (st == 7) g_fake_releases.fetch_add(1, std::memory_order_relaxed);
}

int TestRegistration() {
  int s0 = prof_register_thread("main");
  CHECK(s0 >= 0);
  // Idempotent for the same thread.
  CHECK(prof_register_thread("main") == s0);
  char name[kProfNameCap];
  CHECK(prof_thread_name(s0, name, sizeof(name)) == 4);
  CHECK(std::string(name) == "main");
  CHECK(prof_thread_count() >= 1);
  CHECK(prof_thread_name(kProfMaxThreads + 1, name, sizeof(name)) == -1);
  return 0;
}

int TestCpuAttribution() {
  prof_set_enabled(1);
  Drain();
  std::atomic<bool> stop{false};
  std::atomic<int> spin_slot{-1}, idle_slot{-1};
  std::thread spinner([&] {
    spin_slot.store(prof_register_thread("spinner"),
                    std::memory_order_release);
    while (!stop.load(std::memory_order_relaxed)) {
    }
  });
  std::thread idler([&] {
    idle_slot.store(prof_register_thread("idler"),
                    std::memory_order_release);
    while (!stop.load(std::memory_order_relaxed)) {
      SleepMs(5);
    }
  });
  while (spin_slot.load(std::memory_order_acquire) < 0 ||
         idle_slot.load(std::memory_order_acquire) < 0) {
    SleepMs(1);
  }
  CHECK(prof_start(200) == 0);
  SleepMs(400);
  int ss = spin_slot.load(std::memory_order_acquire);
  int is = idle_slot.load(std::memory_order_acquire);
  CHECK(ss >= 0 && is >= 0 && ss != is);
  uint64_t cpu[kProfMaxThreads] = {0};
  int k = prof_thread_cpu_ns(cpu, kProfMaxThreads);
  CHECK(k > ss && k > is);
  // The spinner burned a core for ~400ms; the idler slept. Require a
  // 10x separation (generous for a loaded CI host).
  CHECK(cpu[ss] > 50ull * 1000 * 1000);
  CHECK(cpu[ss] > 10 * (cpu[is] + 1));
  // The ring carries per-tick deltas for both slots, tick markers, and
  // monotone tick ordinals.
  auto recs = Drain();
  CHECK(!recs.empty());
  uint64_t last_tick = 0;
  bool saw_spin = false, saw_idle = false, saw_tick = false;
  uint64_t spin_us = 0, idle_us = 0;
  for (const Rec& r : recs) {
    CHECK(r.kind >= 1 && r.kind < kProfKindCount);
    CHECK(r.tick >= last_tick);
    last_tick = r.tick;
    if (r.kind == kProfTick) saw_tick = true;
    if (r.kind == kProfThreadCpu && r.slot == (uint8_t)ss) {
      saw_spin = true;
      spin_us += r.val_us;
    }
    if (r.kind == kProfThreadCpu && r.slot == (uint8_t)is) {
      saw_idle = true;
      idle_us += r.val_us;
    }
  }
  CHECK(saw_tick && saw_spin && saw_idle);
  CHECK(spin_us > 10 * (idle_us + 1));
  CHECK(prof_ticks() > 0);
  stop.store(true);
  spinner.join();
  idler.join();
  return 0;
}

int TestGilProbe() {
  prof_set_enabled(1);
  Drain();
  uint64_t wait0 = prof_gil_wait_ns();
  uint64_t probes0 = prof_gil_probes();
  prof_set_gil_fns((void*)&FakeEnsure, (void*)&FakeRelease);
  SleepMs(300);
  prof_set_gil_fns(nullptr, nullptr);
  uint64_t probes = prof_gil_probes() - probes0;
  uint64_t waited = prof_gil_wait_ns() - wait0;
  CHECK(probes > 0);
  // Every fake acquire burns ~200us.
  CHECK(waited >= probes * 150ull * 1000);
  CHECK(g_fake_releases.load(std::memory_order_relaxed) >= probes);
  bool saw_gil = false;
  for (const Rec& r : Drain()) {
    if (r.kind == kProfGilWait) {
      saw_gil = true;
      CHECK(r.val_us >= 150);
    }
  }
  CHECK(saw_gil);
  return 0;
}

int TestDisable() {
  prof_set_enabled(0);
  CHECK(prof_enabled() == 0);
  Drain();
  uint64_t ticks0 = prof_ticks();
  SleepMs(150);
  CHECK(prof_ticks() == ticks0);
  CHECK(Drain().empty());
  prof_set_enabled(1);
  CHECK(prof_enabled() == 1);
  SleepMs(150);
  CHECK(prof_ticks() > ticks0);
  CHECK(!Drain().empty());
  return 0;
}

int TestDrainWhileSampling() {
  prof_set_enabled(1);
  // Concurrent drainers against the live sampler: every record that
  // survives the lap check must be well-formed with non-decreasing
  // ticks per drainer pass.
  std::atomic<bool> stop{false};
  std::thread churn([&] {
    // Thread churn: registrations racing the sampler's table scan.
    while (!stop.load(std::memory_order_relaxed)) {
      std::thread t([] { prof_register_thread("churn"); });
      t.join();
      SleepMs(2);
    }
  });
  uint64_t deadline = MonoNs() + 500ull * 1000 * 1000;
  while (MonoNs() < deadline) {
    for (const Rec& r : DrainOnce()) {
      CHECK(r.kind >= 1 && r.kind < kProfKindCount);
      CHECK(r.slot < kProfMaxThreads);
    }
  }
  stop.store(true);
  churn.join();
  return 0;
}

int TestWraparound() {
  prof_set_enabled(1);
  // Without a drainer the ring laps: several records per tick at
  // 997 Hz overflow kProfRingCap well inside the window. Losses are
  // accounted when a drain detects the lap (same as the scope rings),
  // so poll via DrainOnce. A registered thread burns CPU throughout
  // the window — an idle process no longer ticks at full rate (the
  // sampler stretches its sleep up to 16x), and ring overflow is an
  // under-load phenomenon anyway.
  Drain();
  uint64_t dropped0 = prof_dropped();
  uint64_t ticks0 = prof_ticks();
  prof_start(997);  // raises the rate of the running sampler
  std::atomic<bool> stop{false};
  std::thread hot([&] {
    prof_register_thread("wrap-hot");
    volatile uint64_t sink = 0;
    while (!stop.load(std::memory_order_relaxed)) sink += 1;
  });
  uint64_t deadline = MonoNs() + 8000ull * 1000 * 1000;
  // Let the sampler produce > 2x the ring capacity worth of ticks
  // (>= 3 records per tick: tick marker + sampler + hot), then drain.
  while (MonoNs() < deadline && prof_ticks() - ticks0 < 2 * kProfRingCap) {
    SleepMs(50);
  }
  stop.store(true);
  hot.join();
  DrainOnce();
  CHECK(prof_dropped() > dropped0);
  // The drain still yields only well-formed records from the fresh
  // window.
  uint64_t last_tick = 0;
  for (const Rec& r : Drain()) {
    CHECK(r.kind >= 1 && r.kind < kProfKindCount);
    CHECK(r.tick >= last_tick);
    last_tick = r.tick;
  }
  prof_start(200);
  return 0;
}

int TestStopStart() {
  prof_stop();
  uint64_t ticks0 = prof_ticks();
  SleepMs(120);
  CHECK(prof_ticks() == ticks0);  // sampler really joined
  CHECK(prof_start(200) == 0);
  SleepMs(120);
  CHECK(prof_ticks() > ticks0);
  return 0;
}

}  // namespace

int main() {
  prof_set_enabled(1);
  int rc = 0;
  rc |= TestRegistration();
  std::printf("prof registration ok\n");
  rc |= TestCpuAttribution();
  std::printf("prof cpu attribution ok\n");
  rc |= TestGilProbe();
  std::printf("prof gil probe ok\n");
  rc |= TestDisable();
  std::printf("prof disable ok\n");
  rc |= TestDrainWhileSampling();
  std::printf("prof drain-while-sampling ok\n");
  rc |= TestWraparound();
  std::printf("prof wraparound ok\n");
  rc |= TestStopStart();
  std::printf("prof stop/start ok\n");
  prof_stop();
  if (rc == 0) std::printf("prof_core_test: ALL OK\n");
  return rc;
}
