// graftshm: slab arena + SCM_RIGHTS fd passing for the shared-memory
// object plane.
//
// The arena hands out tmpfs-backed "shmslab-<seq>" files from the store
// directory. Slab names are stable for the life of the file — a sealed
// object's store path IS its slab path, never renamed — so a client that
// mapped the slab at CREATE time keeps a coherent view through SEAL and
// GET (MAP_SHARED mappings of one inode always see current content).
// Recycled slabs are kept on an exact-size free list so a steady-state
// put workload reuses warm pages instead of faulting fresh ones: on this
// host a cold tmpfs first-touch write runs ~1.3 GiB/s while a warm-slab
// copy runs at the memcpy ceiling (~7.5 GiB/s) — slab reuse is where the
// put-bandwidth win actually comes from.
//
// Allocation uses posix_fallocate so "no space" is a clean -2 at CREATE
// time instead of a SIGBUS in the client when it touches a sparse page;
// the Python side falls back to the graftcopy path whose store admission
// can evict.
//
// Locking: a single arena mutex guards the free list. The store calls
// into the arena from EraseObject (slab recycler callback) while holding
// the store mutex; the arena never calls back into the store, so the
// store.mu -> arena.mu order is acyclic. An over-cap recycle lands in a
// single holdover slot (see Arena::holdover_path) and only the slab it
// displaces is unlinked — a cheap tmpfs unlink, done after the mutex
// drops.

#include "shm_core.h"

#include <errno.h>
#include <fcntl.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Arena {
  std::string dir;
  uint64_t max_free_bytes = 0;
  uint64_t free_bytes = 0;
  uint64_t reuses = 0;
  uint64_t seq = 0;
  std::mutex mu;
  // Exact-size buckets: size -> slab paths available for reuse.
  std::unordered_map<uint64_t, std::vector<std::string>> free_slabs;
  // Single over-cap holdover: the most recently recycled slab that did
  // not fit under the retention cap. A put/free loop on an object
  // bigger than the whole cap (e.g. a 1 GiB array against a 512 MiB
  // cap) would otherwise fault fresh pages every iteration — on this
  // host cold tmpfs first-touch runs ~25x slower than a warm rewrite,
  // so one resident slab beyond the cap buys the entire bandwidth win
  // (graftcopy's scratch-inode trick, arena-side). Bounded to exactly
  // one slab: a new over-cap recycle unlinks the previous holdover.
  std::string holdover_path;
  uint64_t holdover_size = 0;
};

}  // namespace

extern "C" {

void* shm_arena_create(const char* dir, uint64_t max_free_bytes) {
  Arena* a = new Arena();
  a->dir = dir;
  a->max_free_bytes = max_free_bytes;
  return a;
}

void shm_arena_destroy(void* arena) {
  Arena* a = static_cast<Arena*>(arena);
  if (a == nullptr) return;
  {
    std::lock_guard<std::mutex> lock(a->mu);
    for (auto& bucket : a->free_slabs) {
      for (const std::string& path : bucket.second) ::unlink(path.c_str());
    }
    a->free_slabs.clear();
    a->free_bytes = 0;
    if (!a->holdover_path.empty()) ::unlink(a->holdover_path.c_str());
  }
  delete a;
}

int shm_arena_acquire(void* arena, uint64_t size, char* out_path,
                      int path_cap, int* reused_out) {
  Arena* a = static_cast<Arena*>(arena);
  if (reused_out != nullptr) *reused_out = 0;
  // Reuse pass: pop exact-size slabs until one opens. A slab can go
  // stale if something swept the store dir underneath us; treat a
  // failed open as "drop and try the next".
  for (;;) {
    std::string path;
    {
      std::lock_guard<std::mutex> lock(a->mu);
      auto it = a->free_slabs.find(size);
      if (it == a->free_slabs.end() || it->second.empty()) break;
      path = std::move(it->second.back());
      it->second.pop_back();
      if (it->second.empty()) a->free_slabs.erase(it);
      a->free_bytes -= size;
    }
    int fd = ::open(path.c_str(), O_RDWR | O_CLOEXEC);
    if (fd < 0) continue;  // stale entry; already unlinked by a sweeper
    int n = std::snprintf(out_path, (size_t)path_cap, "%s", path.c_str());
    if (n < 0 || n >= path_cap) {
      ::close(fd);
      ::unlink(path.c_str());
      return -3;
    }
    {
      std::lock_guard<std::mutex> lock(a->mu);
      a->reuses += 1;
    }
    if (reused_out != nullptr) *reused_out = 1;
    return fd;
  }
  // Over-cap holdover: same exact-size contract as the buckets, same
  // stale handling (a failed open falls through to a fresh slab).
  {
    std::string path;
    {
      std::lock_guard<std::mutex> lock(a->mu);
      if (a->holdover_size == size && !a->holdover_path.empty()) {
        path = std::move(a->holdover_path);
        a->holdover_path.clear();
        a->holdover_size = 0;
      }
    }
    if (!path.empty()) {
      int fd = ::open(path.c_str(), O_RDWR | O_CLOEXEC);
      if (fd >= 0) {
        int n = std::snprintf(out_path, (size_t)path_cap, "%s", path.c_str());
        if (n < 0 || n >= path_cap) {
          ::close(fd);
          ::unlink(path.c_str());
          return -3;
        }
        {
          std::lock_guard<std::mutex> lock(a->mu);
          a->reuses += 1;
        }
        if (reused_out != nullptr) *reused_out = 1;
        return fd;
      }
    }
  }
  // Fresh slab.
  uint64_t seq;
  {
    std::lock_guard<std::mutex> lock(a->mu);
    seq = ++a->seq;
  }
  char path[512];
  int n = std::snprintf(path, sizeof(path), "%s/shmslab-%llu", a->dir.c_str(),
                        (unsigned long long)seq);
  if (n < 0 || n >= (int)sizeof(path) || n >= path_cap) return -3;
  int fd = ::open(path, O_CREAT | O_EXCL | O_RDWR | O_CLOEXEC, 0600);
  if (fd < 0) return -3;
  // posix_fallocate (not ftruncate): reserve the pages now so a full
  // tmpfs is a clean error here, not a SIGBUS in the mapped client.
  int rc = ::posix_fallocate(fd, 0, (off_t)size);
  if (rc != 0) {
    ::close(fd);
    ::unlink(path);
    // EFBIG joins ENOSPC/EDQUOT: all mean "this allocation cannot be
    // satisfied" and the caller should take the fallback path.
    return (rc == ENOSPC || rc == EDQUOT || rc == EFBIG) ? -2 : -3;
  }
  std::memcpy(out_path, path, (size_t)n + 1);
  return fd;
}

void shm_arena_recycle(void* arena, const char* path, uint64_t size) {
  Arena* a = static_cast<Arena*>(arena);
  std::string evict;
  {
    std::lock_guard<std::mutex> lock(a->mu);
    if (a->free_bytes + size <= a->max_free_bytes) {
      a->free_slabs[size].push_back(std::string(path));
      a->free_bytes += size;
      return;
    }
    evict = std::move(a->holdover_path);
    a->holdover_path = path;
    a->holdover_size = size;
  }
  if (!evict.empty()) ::unlink(evict.c_str());
}

uint64_t shm_arena_free_bytes(void* arena) {
  Arena* a = static_cast<Arena*>(arena);
  std::lock_guard<std::mutex> lock(a->mu);
  return a->free_bytes;
}

uint64_t shm_arena_free_slabs(void* arena) {
  Arena* a = static_cast<Arena*>(arena);
  std::lock_guard<std::mutex> lock(a->mu);
  uint64_t n = 0;
  for (auto& bucket : a->free_slabs) n += bucket.second.size();
  return n;
}

uint64_t shm_arena_reuses(void* arena) {
  Arena* a = static_cast<Arena*>(arena);
  std::lock_guard<std::mutex> lock(a->mu);
  return a->reuses;
}

int shm_send_fd(int sock_fd, int fd) {
  char payload = 'F';
  struct iovec iov;
  iov.iov_base = &payload;
  iov.iov_len = 1;
  char cbuf[CMSG_SPACE(sizeof(int))];
  std::memset(cbuf, 0, sizeof(cbuf));
  struct msghdr msg;
  std::memset(&msg, 0, sizeof(msg));
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  msg.msg_control = cbuf;
  msg.msg_controllen = sizeof(cbuf);
  struct cmsghdr* cm = CMSG_FIRSTHDR(&msg);
  cm->cmsg_level = SOL_SOCKET;
  cm->cmsg_type = SCM_RIGHTS;
  cm->cmsg_len = CMSG_LEN(sizeof(int));
  std::memcpy(CMSG_DATA(cm), &fd, sizeof(int));
  for (;;) {
    ssize_t n = ::sendmsg(sock_fd, &msg, MSG_NOSIGNAL);
    if (n < 0 && errno == EINTR) continue;
    return n == 1 ? 0 : -1;
  }
}

int shm_recv_fd(int sock_fd) {
  char payload = 0;
  struct iovec iov;
  iov.iov_base = &payload;
  iov.iov_len = 1;
  char cbuf[CMSG_SPACE(sizeof(int))];
  std::memset(cbuf, 0, sizeof(cbuf));
  struct msghdr msg;
  std::memset(&msg, 0, sizeof(msg));
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  msg.msg_control = cbuf;
  msg.msg_controllen = sizeof(cbuf);
  ssize_t n;
  for (;;) {
    n = ::recvmsg(sock_fd, &msg, MSG_CMSG_CLOEXEC);
    if (n < 0 && errno == EINTR) continue;
    break;
  }
  if (n != 1) return -1;
  for (struct cmsghdr* cm = CMSG_FIRSTHDR(&msg); cm != nullptr;
       cm = CMSG_NXTHDR(&msg, cm)) {
    if (cm->cmsg_level == SOL_SOCKET && cm->cmsg_type == SCM_RIGHTS &&
        cm->cmsg_len >= CMSG_LEN(sizeof(int))) {
      int fd;
      std::memcpy(&fd, CMSG_DATA(cm), sizeof(int));
      if (fd < 0) return -1;
      return fd;
    }
  }
  return -1;
}

}  // extern "C"
