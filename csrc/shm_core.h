// graftshm: slab arena + fd passing for the store-owned shared-memory
// object plane (csrc/shm_core.cc). See shm_core.cc for the design
// notes; store_server.cc drives the arena from its OP_CREATE/OP_SEAL
// handlers, and shm_core_test.cc exercises it standalone.

#ifndef RAY_TPU_SHM_CORE_H_
#define RAY_TPU_SHM_CORE_H_

#include <cstdint>

extern "C" {

// Arena of tmpfs-backed slab files ("shmslab-<seq>") under `dir`.
// `max_free_bytes` caps how many recycled-slab bytes are retained for
// reuse; beyond it, at most ONE further slab (the most recently
// recycled) is parked in a holdover slot and any slab it displaces is
// unlinked — a put/free loop on an object bigger than the whole cap
// still reuses warm pages.
void* shm_arena_create(const char* dir, uint64_t max_free_bytes);
void shm_arena_destroy(void* arena);

// Acquire a slab of exactly `size` bytes. Returns an O_RDWR fd (>= 0)
// and writes the slab path into out_path; *reused_out is 1 when the
// slab came from the free list (its pages are warm — the whole point).
// Negative returns: -2 no space (clean ENOSPC via fallocate — the
// caller falls back to a path whose admission can evict/spill), -3 io
// error.
int shm_arena_acquire(void* arena, uint64_t size, char* out_path,
                      int path_cap, int* reused_out);

// Return a slab to the free list (exact-size bucket); over the
// retained-bytes cap it takes the single holdover slot (displaced
// holdover is unlinked).
void shm_arena_recycle(void* arena, const char* path, uint64_t size);

// Stats (for tests and leak checks).
uint64_t shm_arena_free_bytes(void* arena);
uint64_t shm_arena_free_slabs(void* arena);
uint64_t shm_arena_reuses(void* arena);

// SCM_RIGHTS helpers: pass `fd` over the connected unix socket
// `sock_fd` alongside a 1-byte payload. shm_send_fd returns 0/-1;
// shm_recv_fd returns the received fd (>= 0) or -1.
int shm_send_fd(int sock_fd, int fd);
int shm_recv_fd(int sock_fd);

}  // extern "C"

#endif  // RAY_TPU_SHM_CORE_H_
