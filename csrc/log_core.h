// graftlog: crash-persistent structured log ring for worker processes.
//
// Shared contract between the emit path (log_core.cc) and the Python
// seam (ray_tpu/core/_native/graftlog.py). The wire record layout, the
// source table and the ring geometry below are lint-checked against the
// Python constants (tools/lint/wire_schema.py pass 3h) — keep both
// sides in sync.
//
// Unlike the graftscope/graftprof rings (anonymous process memory,
// gone with the process), the log ring is a MAP_SHARED file
// `logring-<pid>` in the node's tmpfs store directory. A SIGKILL'd or
// OOM-killed worker leaves its last kLogRingSlots records on the
// filesystem; the node agent salvages the tail post-mortem and attaches
// it to the task's grafttrail attempt record — no ptrace, no core dump.
//
// Layout: one 64-byte header page followed by kLogRingSlots fixed-width
// slots. Single writer (the owning process), lock-free: records are
// written into slot (head % slots), then the header's head counter is
// published with a release store. Readers (the agent tailing live, or
// salvage after death) re-read head after copying and discard anything
// the writer may have lapped — same discipline as the scope_core drain.
//
// Wire record (little-endian, fixed width, 256 bytes):
//   u8 level | u8 source | u16 line_len | u32 seq | u64 t_ns
//   | char task[32] | char actor[12] | char msg[196]
// level is the Python logging level (10..50); t_ns is CLOCK_REALTIME
// (wall) so records merge across nodes; task/actor carry the emitting
// thread's graftprof task context (NUL-padded hex); msg holds the first
// kLogMsgCap bytes of the line, line_len the un-truncated length.

#ifndef RAY_TPU_LOG_CORE_H_
#define RAY_TPU_LOG_CORE_H_

#include <cstdint>

#pragma pack(push, 1)
struct LogWireRec {  // 256 bytes on the wire, little-endian
  uint8_t level;
  uint8_t source;
  uint16_t line_len;
  uint32_t seq;
  uint64_t t_ns;
  char task[32];
  char actor[12];
  char msg[196];
};
#pragma pack(pop)

constexpr int kLogRecordSize = 256;
static_assert(sizeof(LogWireRec) == kLogRecordSize, "record packing");

// Record sources. Mirrored by LOG_SRC_* in graftlog.py (lint pass 3h).
[[maybe_unused]] constexpr uint8_t kLogSrcLogger = 0, kLogSrcStdout = 1,
                                   kLogSrcStderr = 2, kLogSrcAgent = 3;
[[maybe_unused]] constexpr int kLogSrcCount = 4;

// Ring geometry. Mirrored by LOG_* in graftlog.py (pass 3h). The file
// is kLogHeaderSize + kLogRingSlots * kLogRecordSize bytes (~1 MiB).
[[maybe_unused]] constexpr int kLogRingSlots = 4096;  // power of two
[[maybe_unused]] constexpr int kLogHeaderSize = 64;
[[maybe_unused]] constexpr int kLogTaskCap = 32;   // full TaskID hex
[[maybe_unused]] constexpr int kLogActorCap = 12;  // ActorID hex prefix
[[maybe_unused]] constexpr int kLogMsgCap = 196;
[[maybe_unused]] constexpr int kLogMagic = 0x474C4F31;     // "GLO1"
[[maybe_unused]] constexpr int kLogRingVersion = 1;

// File header (offsets fixed by the Python decoder):
//   u32 magic | u32 version | u32 record_size | u32 slots
//   | u64 pid | u64 head | u64 dropped | u64 start_ns | pad to 64
// head counts records ever emitted (monotonic, never wraps); dropped
// counts emit-side losses (emit before open / oversized bursts).

extern "C" {

// Create (or truncate) and map `<dir>/logring-<pid>` for this process.
// One ring per process; a second call re-points the writer at the new
// file. Returns 0, or -1 on open/map failure (emit then no-ops).
int log_ring_open(const char* dir, uint64_t pid);

// Unmap the ring (the FILE stays — salvage reads it after death).
void log_ring_close(void);

// Append one record. task/actor are NUL-terminated hex strings (may be
// "" / null); msg_len < 0 means strlen(msg). Truncates msg to
// kLogMsgCap (line_len keeps the true length). Returns the record's
// seq (>= 1), or 0 when disabled or the ring is not open.
uint64_t log_emit(int level, int source, const char* task,
                  const char* actor, const char* msg, int msg_len);

// Append a '\n'-separated batch of lines as consecutive records under
// one lock acquisition, one wall-clock read and one head publish —
// the stdio tee flushes its per-quantum line buffer through this
// instead of paying an FFI call per printed line. All records share
// level/source/task/actor; empty lines are skipped. Returns the seq
// of the last record appended, or 0 when disabled / not open / the
// batch held no non-empty lines.
uint64_t log_emit_batch(int level, int source, const char* task,
                        const char* actor, const char* lines, int len);

// 1 while emitting. Default comes from RAY_TPU_GRAFTLOG (unset/1 = on,
// "0"/"false"/"off"/"no" = off), resolved once on first use.
int log_enabled(void);
void log_set_enabled(int on);

// Drain THIS process's ring from an internal cursor into buf as
// kLogRecordSize-byte records. Returns bytes written (a multiple of
// the record size). Safe against the concurrent writer: lapped slots
// are discarded into log_dropped(). Cross-process tailing and salvage
// decode the file directly in Python — same lap discipline.
int log_drain(char* buf, int cap);

// Records emitted since the ring opened (the header's head counter).
uint64_t log_emitted(void);

// Records lost: emit-side (ring not open while enabled) plus
// drain-side laps.
uint64_t log_dropped(void);

}  // extern "C"

#endif  // RAY_TPU_LOG_CORE_H_
