// graftprof: always-on continuous profiler for worker processes.
//
// Shared contract between the sampler (prof_core.cc), the native planes
// that register their sidecar threads (rpc_core.cc, store_server.cc,
// copy_core.cc, object_store.cc) and the Python seam
// (ray_tpu/core/_native/graftprof.py). The wire record layout, the kind
// table and the ring geometry below are lint-checked against the Python
// constants (tools/lint/wire_schema.py pass 3g) — keep both sides in
// sync.
//
// One native sampler thread ticks at kProfDefaultHz (67 Hz — an
// off-round rate so the tick train can't alias against the 2 s flush
// or the 1 s pulse). Each tick it:
//   * snapshots every registered thread's CLOCK_THREAD_CPUTIME_ID and
//     emits the delta since the previous tick (kProfThreadCpu);
//   * times one GIL acquire from outside the interpreter
//     (kProfGilWait) when the seam handed over PyGILState_Ensure /
//     PyGILState_Release pointers;
//   * stamps a kProfTick marker carrying the measured tick period.
// Records land in a graftscope-style lock-free fixed-record ring the
// Python seam drains on the worker flush tick.
//
// Wire record (little-endian, fixed width):
//   u8 kind | u8 slot | u16 flags | u32 val_us | u64 tick | u64 t_ns
// val_us is kind-specific: cpu-time delta (ThreadCpu), GIL acquire
// latency (GilWait), or the measured tick period (Tick), all in µs.

#ifndef RAY_TPU_PROF_CORE_H_
#define RAY_TPU_PROF_CORE_H_

#include <cstdint>

#pragma pack(push, 1)
struct ProfWireRec {  // 24 bytes on the wire, little-endian
  uint8_t kind;
  uint8_t slot;
  uint16_t flags;
  uint32_t val_us;
  uint64_t tick;
  uint64_t t_ns;
};
#pragma pack(pop)

constexpr int kProfRecordSize = 24;
static_assert(sizeof(ProfWireRec) == kProfRecordSize, "record packing");

// Record kinds. Mirrored by PROF_* in graftprof.py (lint pass 3g).
[[maybe_unused]] constexpr uint8_t kProfTick = 1, kProfThreadCpu = 2,
                                   kProfGilWait = 3;
[[maybe_unused]] constexpr int kProfKindCount = 4;  // 1 + highest kind

// Sampler geometry. Mirrored by PROF_* in graftprof.py (pass 3g).
[[maybe_unused]] constexpr int kProfDefaultHz = 67;
[[maybe_unused]] constexpr int kProfMaxThreads = 64;
[[maybe_unused]] constexpr int kProfRingCap = 4096;  // power of two
[[maybe_unused]] constexpr int kProfNameCap = 32;    // incl. NUL

extern "C" {

// Register the CALLING thread for per-tick CPU-time sampling. Returns
// the slot index (echoed in kProfThreadCpu records), or -1 when the
// table is full or the thread's CPU clock is unavailable. Idempotent
// per thread (the lease is thread_local); slots recycle on thread
// exit.
int prof_register_thread(const char* name);

// Hand over PyGILState_Ensure / PyGILState_Release so the sampler can
// time GIL acquisition from outside the interpreter. Both null
// disables the probe (the C test injects stand-ins here).
void prof_set_gil_fns(void* ensure_fn, void* release_fn);

// Start the sampler thread at `hz` ticks/s (<= 0 = kProfDefaultHz).
// Idempotent; returns 0 when the thread is running. prof_stop() joins
// it — the Python seam calls this from atexit so no GIL probe can run
// during interpreter finalization.
int prof_start(int hz);
void prof_stop(void);

// 1 while sampling. Default comes from RAY_TPU_GRAFTPROF (unset/1 =
// on, "0"/"false"/"off"/"no" = off), resolved once on first use.
// While disabled the sampler thread idles: no clock reads, no GIL
// probes, no records.
int prof_enabled(void);
void prof_set_enabled(int on);

// Drain the sample ring into buf as kProfRecordSize-byte records.
// Returns bytes written (a multiple of the record size). Safe against
// the concurrent sampler writer and concurrent drainers.
int prof_drain(char* buf, int cap);

// Records lost to ring wraparound since process start.
uint64_t prof_dropped(void);

// Sampler ticks since process start.
uint64_t prof_ticks(void);

// Registered-thread table: slots ever handed out (dead slots stay in
// range until recycled).
int prof_thread_count(void);

// Copy per-slot cumulative thread CPU ns: out[s] = total CPU time the
// sampler has observed for slot s. Writes min(max_slots, table size)
// entries; returns the number written. Dead threads keep their last
// total (attribution for exited sidecar threads stays visible).
int prof_thread_cpu_ns(uint64_t* out, int max_slots);

// Copy slot s's registered name into buf (NUL-terminated, truncated to
// kProfNameCap). Returns the name length, or -1 for an unused slot.
int prof_thread_name(int slot, char* buf, int cap);

// Cumulative GIL probe totals since process start.
uint64_t prof_gil_wait_ns(void);
uint64_t prof_gil_probes(void);

}  // extern "C"

#endif  // RAY_TPU_PROF_CORE_H_
