// Shared-memory object store engine for ray_tpu node agents.
//
// TPU-native analogue of the reference's plasma store (reference:
// src/ray/object_manager/plasma/{store.cc,object_store.cc,obj_lifecycle_mgr.cc,
// plasma_allocator.cc,eviction_policy.cc}). Same role — node-local immutable
// shared-memory objects with zero-copy reads, refcount pinning, LRU eviction
// of unpinned sealed objects — but a different shape: instead of one dlmalloc
// arena behind a custom fd-passing socket protocol, every object is its own
// tmpfs-backed file under a session directory that clients mmap directly
// (control traffic rides the agent's RPC; the kernel page cache is the arena).
// This keeps the native engine focused on lifecycle/accounting/eviction and
// makes host<->TPU DMA staging a plain mmap.
//
// Built as libraytpu_store.so, driven in-process by the node agent via ctypes.
//
// Thread-safe: a single mutex guards the index (operations are O(1)-ish and
// the data path never holds it — clients write/read through their own mmaps).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <list>
#include <mutex>
#include <string>
#include <unordered_map>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr int kIdSize = 20;

struct ObjectEntry {
  std::string path;
  uint64_t data_size = 0;
  uint64_t meta_size = 0;
  bool sealed = false;
  bool pinned = false;          // primary copy: never evict
  bool pending_delete = false;  // delete once refcount drops to 0
  int64_t refcount = 0;
  // LRU bookkeeping: valid iff evictable (sealed, refcount==0, !pinned).
  std::list<std::string>::iterator lru_it;
  bool in_lru = false;
};

struct Store {
  std::string dir;
  uint64_t capacity = 0;
  uint64_t used = 0;
  uint64_t num_evictions = 0;
  uint64_t bytes_evicted = 0;
  std::mutex mu;
  std::unordered_map<std::string, ObjectEntry> objects;
  std::list<std::string> lru;  // front = oldest
};

std::string IdKey(const char* id) { return std::string(id, kIdSize); }

std::string HexPath(const Store& s, const std::string& key) {
  static const char* hexd = "0123456789abcdef";
  std::string hex;
  hex.reserve(kIdSize * 2);
  for (unsigned char c : key) {
    hex.push_back(hexd[c >> 4]);
    hex.push_back(hexd[c & 0xf]);
  }
  return s.dir + "/" + hex;
}

void LruPush(Store* s, const std::string& key, ObjectEntry* e) {
  s->lru.push_back(key);
  e->lru_it = std::prev(s->lru.end());
  e->in_lru = true;
}

void LruRemove(Store* s, ObjectEntry* e) {
  if (e->in_lru) {
    s->lru.erase(e->lru_it);
    e->in_lru = false;
  }
}

// Caller holds mu. Removes entry + backing file.
void EraseObject(Store* s, const std::string& key) {
  auto it = s->objects.find(key);
  if (it == s->objects.end()) return;
  LruRemove(s, &it->second);
  s->used -= it->second.data_size + it->second.meta_size;
  ::unlink(it->second.path.c_str());
  s->objects.erase(it);
}

// Caller holds mu. Evict LRU victims until `needed` bytes fit. Returns true
// if enough space was freed.
bool EvictFor(Store* s, uint64_t needed) {
  while (s->used + needed > s->capacity && !s->lru.empty()) {
    std::string victim = s->lru.front();
    auto it = s->objects.find(victim);
    // lru entries are kept consistent; still guard against staleness.
    if (it == s->objects.end()) {
      s->lru.pop_front();
      continue;
    }
    s->num_evictions++;
    s->bytes_evicted += it->second.data_size + it->second.meta_size;
    EraseObject(s, victim);
  }
  return s->used + needed <= s->capacity;
}

}  // namespace

extern "C" {

void* store_create(const char* dir, uint64_t capacity) {
  auto* s = new Store();
  s->dir = dir;
  s->capacity = capacity;
  ::mkdir(dir, 0700);
  return s;
}

void store_destroy(void* handle) {
  auto* s = static_cast<Store*>(handle);
  {
    std::lock_guard<std::mutex> g(s->mu);
    for (auto& kv : s->objects) ::unlink(kv.second.path.c_str());
  }
  ::rmdir(s->dir.c_str());
  delete s;
}

// 0 ok, -1 already exists, -2 out of memory (after eviction), -3 io error.
int store_create_object(void* handle, const char* id, uint64_t data_size,
                        uint64_t meta_size, char* out_path, int path_cap) {
  auto* s = static_cast<Store*>(handle);
  std::string key = IdKey(id);
  uint64_t total = data_size + meta_size;
  std::string path;
  {
    std::lock_guard<std::mutex> g(s->mu);
    if (s->objects.count(key)) return -1;
    if (total > s->capacity) return -2;
    if (!EvictFor(s, total)) return -2;
    path = HexPath(*s, key);
    ObjectEntry e;
    e.path = path;
    e.data_size = data_size;
    e.meta_size = meta_size;
    s->used += total;
    s->objects.emplace(key, std::move(e));
  }
  int fd = ::open(path.c_str(), O_CREAT | O_RDWR | O_EXCL, 0600);
  if (fd < 0) {
    std::lock_guard<std::mutex> g(s->mu);
    EraseObject(s, key);
    return -3;
  }
  if (total > 0 && ::ftruncate(fd, (off_t)total) != 0) {
    ::close(fd);
    std::lock_guard<std::mutex> g(s->mu);
    EraseObject(s, key);
    return -3;
  }
  ::close(fd);
  std::snprintf(out_path, path_cap, "%s", path.c_str());
  return 0;
}

// Ingest a fully-written payload file as a SEALED object in one step
// (worker writes <dir>/ingest-* directly, then one RPC lands here —
// halves the control round-trips of the create+write+seal protocol).
// The rename happens UNDER the mutex, before the entry is published:
// otherwise a concurrent EvictFor could pick the just-inserted entry
// (refcount 0, unpinned) as a victim and erase it before the rename
// lands — the caller would get rc=0 for an object that is gone, with
// the renamed payload stranded untracked in the store dir. A tmpfs
// rename is a metadata-only op, so holding the lock across it is cheap.
// `pinned` != 0 admits the object as a pinned PRIMARY copy atomically,
// so the agent's pin cannot race with eviction either.
// 0 ok, -1 already exists, -2 out of memory (after eviction), -3 io error.
int store_ingest_object(void* handle, const char* id, const char* src_path,
                        uint64_t data_size, uint64_t meta_size, int pinned) {
  auto* s = static_cast<Store*>(handle);
  std::string key = IdKey(id);
  uint64_t total = data_size + meta_size;
  std::lock_guard<std::mutex> g(s->mu);
  if (s->objects.count(key)) return -1;
  if (total > s->capacity) return -2;
  if (!EvictFor(s, total)) return -2;
  std::string path = HexPath(*s, key);
  if (::rename(src_path, path.c_str()) != 0) return -3;
  ObjectEntry e;
  e.path = path;
  e.data_size = data_size;
  e.meta_size = meta_size;
  e.sealed = true;
  e.pinned = pinned != 0;
  s->used += total;
  auto ins = s->objects.emplace(key, std::move(e));
  if (!ins.first->second.pinned) LruPush(s, key, &ins.first->second);
  return 0;
}

// 0 ok, -1 missing.
int store_seal(void* handle, const char* id) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(IdKey(id));
  if (it == s->objects.end()) return -1;
  ObjectEntry& e = it->second;
  e.sealed = true;
  if (e.refcount == 0 && !e.pinned && !e.in_lru) LruPush(s, it->first, &e);
  return 0;
}

// Pins the object (refcount++). 0 ok, -1 missing, -2 unsealed.
int store_get(void* handle, const char* id, char* out_path, int path_cap,
              uint64_t* data_size, uint64_t* meta_size) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(IdKey(id));
  if (it == s->objects.end()) return -1;
  ObjectEntry& e = it->second;
  if (!e.sealed) return -2;
  e.refcount++;
  LruRemove(s, &e);
  std::snprintf(out_path, path_cap, "%s", e.path.c_str());
  *data_size = e.data_size;
  *meta_size = e.meta_size;
  return 0;
}

// 0 ok, -1 missing.
int store_release(void* handle, const char* id) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  std::string key = IdKey(id);
  auto it = s->objects.find(key);
  if (it == s->objects.end()) return -1;
  ObjectEntry& e = it->second;
  if (e.refcount > 0) e.refcount--;
  if (e.refcount == 0) {
    if (e.pending_delete) {
      EraseObject(s, key);
    } else if (e.sealed && !e.pinned && !e.in_lru) {
      LruPush(s, key, &e);
    }
  }
  return 0;
}

// Deletes now if unreferenced, else marks pending-delete. 0 ok, -1 missing.
int store_delete(void* handle, const char* id) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  std::string key = IdKey(id);
  auto it = s->objects.find(key);
  if (it == s->objects.end()) return -1;
  if (it->second.refcount == 0) {
    EraseObject(s, key);
  } else {
    it->second.pending_delete = true;
  }
  return 0;
}

// 1 sealed-present, 0 absent, 2 present-unsealed.
int store_contains(void* handle, const char* id) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(IdKey(id));
  if (it == s->objects.end()) return 0;
  return it->second.sealed ? 1 : 2;
}

// Pin/unpin primary copies (exempt from eviction; spill candidates).
int store_pin(void* handle, const char* id, int pinned) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(IdKey(id));
  if (it == s->objects.end()) return -1;
  ObjectEntry& e = it->second;
  e.pinned = pinned != 0;
  if (e.pinned) {
    LruRemove(s, &e);
  } else if (e.sealed && e.refcount == 0 && !e.in_lru) {
    LruPush(s, it->first, &e);
  }
  return 0;
}

// Borrowed pointer to the store's directory string (valid for the
// store's lifetime) — used by the fast-path sidecar (store_server.cc).
const char* store_dir_ref(void* handle) {
  return static_cast<Store*>(handle)->dir.c_str();
}

uint64_t store_used(void* handle) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  return s->used;
}

uint64_t store_capacity(void* handle) {
  return static_cast<Store*>(handle)->capacity;
}

uint64_t store_num_objects(void* handle) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  return s->objects.size();
}

uint64_t store_num_evictions(void* handle) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  return s->num_evictions;
}

}  // extern "C"
