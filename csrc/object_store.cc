// Shared-memory object store engine for ray_tpu node agents.
//
// TPU-native analogue of the reference's plasma store (reference:
// src/ray/object_manager/plasma/{store.cc,object_store.cc,obj_lifecycle_mgr.cc,
// plasma_allocator.cc,eviction_policy.cc}). Same role — node-local immutable
// shared-memory objects with zero-copy reads, refcount pinning, LRU eviction
// of unpinned sealed objects — but a different shape: instead of one dlmalloc
// arena behind a custom fd-passing socket protocol, every object is its own
// tmpfs-backed file under a session directory that clients mmap directly
// (control traffic rides the agent's RPC; the kernel page cache is the arena).
// This keeps the native engine focused on lifecycle/accounting/eviction and
// makes host<->TPU DMA staging a plain mmap.
//
// Built as libraytpu_store.so, driven in-process by the node agent via ctypes.
//
// Thread-safe: a single mutex guards the index (operations are O(1)-ish and
// the data path never holds it — clients write/read through their own mmaps).

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <list>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include "prof_core.h"

namespace {

constexpr int kIdSize = 20;

struct ObjectEntry {
  std::string path;
  uint64_t data_size = 0;
  uint64_t meta_size = 0;
  bool sealed = false;
  bool pinned = false;          // primary copy: never evict
  bool pending_delete = false;  // delete once refcount drops to 0
  // graftshm: payload lives in an arena slab (stable "shmslab-*" name,
  // never renamed); on erase the file is recycled, not unlinked.
  bool slab_backed = false;
  int64_t refcount = 0;
  // LRU bookkeeping: valid iff evictable (sealed, refcount==0, !pinned).
  std::list<std::string>::iterator lru_it;
  bool in_lru = false;
};

struct Store {
  std::string dir;
  uint64_t capacity = 0;
  uint64_t used = 0;
  uint64_t num_evictions = 0;
  uint64_t bytes_evicted = 0;
  std::mutex mu;
  std::unordered_map<std::string, ObjectEntry> objects;
  std::list<std::string> lru;  // front = oldest
  // Deferred unlink: a GiB-scale tmpfs unlink frees pages synchronously
  // (~50 ms/GiB) and EraseObject runs under mu on the put admission
  // path, so eviction would stall every concurrent store op for that
  // long. Victims are instead renamed (metadata-only) to a trash name
  // and a background reaper unlinks them outside the lock.
  std::vector<std::string> trash;
  uint64_t trash_seq = 0;
  std::condition_variable trash_cv;
  std::thread reaper;
  bool stopping = false;
  // graftshm: where slab-backed payload files go on erase (the arena's
  // free list) instead of unlink. Set under mu via
  // store_set_slab_recycler; the callback only takes the arena mutex,
  // so the store.mu -> arena.mu order is acyclic.
  void (*slab_recycler)(void*, const char*, uint64_t) = nullptr;
  void* slab_recycler_ctx = nullptr;
};

std::string IdKey(const char* id) { return std::string(id, kIdSize); }

std::string HexPath(const Store& s, const std::string& key) {
  static const char* hexd = "0123456789abcdef";
  std::string hex;
  hex.reserve(kIdSize * 2);
  for (unsigned char c : key) {
    hex.push_back(hexd[c >> 4]);
    hex.push_back(hexd[c & 0xf]);
  }
  return s.dir + "/" + hex;
}

void LruPush(Store* s, const std::string& key, ObjectEntry* e) {
  s->lru.push_back(key);
  e->lru_it = std::prev(s->lru.end());
  e->in_lru = true;
}

void LruRemove(Store* s, ObjectEntry* e) {
  if (e->in_lru) {
    s->lru.erase(e->lru_it);
    e->in_lru = false;
  }
}

constexpr size_t kMaxTrashBacklog = 256;

void ReaperLoop(Store* s) {
  prof_register_thread("store-reaper");
  std::unique_lock<std::mutex> lk(s->mu);
  while (!s->stopping) {
    if (s->trash.empty()) {
      s->trash_cv.wait(lk);
      continue;
    }
    std::vector<std::string> batch;
    batch.swap(s->trash);
    lk.unlock();
    for (const std::string& p : batch) ::unlink(p.c_str());
    lk.lock();
  }
}

// Caller holds mu. Removes entry + backing file. With out_unlink set,
// the backing path is handed back for the caller to ::unlink AFTER
// dropping mu: explicit deletes free their pages synchronously (the
// worker blocks on the delete round-trip, so its next put reuses the
// just-freed tmpfs pages — hot-page writes are ~2x faster than cold
// allocation) without extending the critical section. With out_unlink
// null (eviction, whose caller is the admission path and must not
// block), the file is renamed to a trash name and reaped off-thread
// (see Store::trash) unless the backlog is deep or the rename fails,
// in which case it is unlinked inline.
void EraseObject(Store* s, const std::string& key,
                 std::string* out_unlink = nullptr) {
  auto it = s->objects.find(key);
  if (it == s->objects.end()) return;
  LruRemove(s, &it->second);
  s->used -= it->second.data_size + it->second.meta_size;
  if (it->second.slab_backed && s->slab_recycler != nullptr) {
    // graftshm slabs are recycled (warm pages, stable name), never
    // unlinked here. Recycling is a free-list push; the rare over-cap
    // unlink inside the recycler is a cheap tmpfs metadata op, so
    // holding mu across it does not stall the admission path the way
    // a GiB-scale page-freeing unlink would.
    std::string spath = it->second.path;
    uint64_t total = it->second.data_size + it->second.meta_size;
    s->objects.erase(it);
    s->slab_recycler(s->slab_recycler_ctx, spath.c_str(), total);
    if (out_unlink != nullptr) out_unlink->clear();
    return;
  }
  const std::string& path = it->second.path;
  if (out_unlink != nullptr) {
    *out_unlink = path;
    s->objects.erase(it);
    return;
  }
  bool deferred = false;
  if (s->reaper.joinable() && s->trash.size() < kMaxTrashBacklog) {
    std::string tpath = path + ".t" + std::to_string(++s->trash_seq);
    if (::rename(path.c_str(), tpath.c_str()) == 0) {
      s->trash.push_back(std::move(tpath));
      s->trash_cv.notify_one();
      deferred = true;
    }
  }
  if (!deferred) ::unlink(path.c_str());
  s->objects.erase(it);
}

// Caller holds mu. Evict LRU victims until `needed` bytes fit. Returns true
// if enough space was freed.
bool EvictFor(Store* s, uint64_t needed) {
  while (s->used + needed > s->capacity && !s->lru.empty()) {
    std::string victim = s->lru.front();
    auto it = s->objects.find(victim);
    // lru entries are kept consistent; still guard against staleness.
    if (it == s->objects.end()) {
      s->lru.pop_front();
      continue;
    }
    s->num_evictions++;
    s->bytes_evicted += it->second.data_size + it->second.meta_size;
    EraseObject(s, victim);
  }
  return s->used + needed <= s->capacity;
}

}  // namespace

extern "C" {

void* store_create(const char* dir, uint64_t capacity) {
  auto* s = new Store();
  s->dir = dir;
  s->capacity = capacity;
  ::mkdir(dir, 0700);
  s->reaper = std::thread(ReaperLoop, s);
  return s;
}

void store_destroy(void* handle) {
  auto* s = static_cast<Store*>(handle);
  {
    std::lock_guard<std::mutex> g(s->mu);
    s->stopping = true;
  }
  s->trash_cv.notify_all();
  if (s->reaper.joinable()) s->reaper.join();
  {
    std::lock_guard<std::mutex> g(s->mu);
    for (const std::string& p : s->trash) ::unlink(p.c_str());
    for (auto& kv : s->objects) ::unlink(kv.second.path.c_str());
  }
  ::rmdir(s->dir.c_str());
  delete s;
}

// 0 ok, -1 already exists, -2 out of memory (after eviction), -3 io error.
int store_create_object(void* handle, const char* id, uint64_t data_size,
                        uint64_t meta_size, char* out_path, int path_cap) {
  auto* s = static_cast<Store*>(handle);
  std::string key = IdKey(id);
  uint64_t total = data_size + meta_size;
  std::string path;
  {
    std::lock_guard<std::mutex> g(s->mu);
    if (s->objects.count(key)) return -1;
    if (total > s->capacity) return -2;
    if (!EvictFor(s, total)) return -2;
    path = HexPath(*s, key);
    ObjectEntry e;
    e.path = path;
    e.data_size = data_size;
    e.meta_size = meta_size;
    s->used += total;
    s->objects.emplace(key, std::move(e));
  }
  int fd = ::open(path.c_str(), O_CREAT | O_RDWR | O_EXCL, 0600);
  if (fd < 0) {
    std::lock_guard<std::mutex> g(s->mu);
    EraseObject(s, key);
    return -3;
  }
  if (total > 0 && ::ftruncate(fd, (off_t)total) != 0) {
    ::close(fd);
    std::lock_guard<std::mutex> g(s->mu);
    EraseObject(s, key);
    return -3;
  }
  ::close(fd);
  std::snprintf(out_path, path_cap, "%s", path.c_str());
  return 0;
}

// Ingest a fully-written payload file as a SEALED object in one step
// (worker writes <dir>/ingest-* directly, then one RPC lands here —
// halves the control round-trips of the create+write+seal protocol).
// The rename happens UNDER the mutex, before the entry is published:
// otherwise a concurrent EvictFor could pick the just-inserted entry
// (refcount 0, unpinned) as a victim and erase it before the rename
// lands — the caller would get rc=0 for an object that is gone, with
// the renamed payload stranded untracked in the store dir. A tmpfs
// rename is a metadata-only op, so holding the lock across it is cheap.
// `pinned` != 0 admits the object as a pinned PRIMARY copy atomically,
// so the agent's pin cannot race with eviction either.
// 0 ok, -1 already exists, -2 out of memory (after eviction), -3 io error.
int store_ingest_object(void* handle, const char* id, const char* src_path,
                        uint64_t data_size, uint64_t meta_size, int pinned) {
  auto* s = static_cast<Store*>(handle);
  std::string key = IdKey(id);
  uint64_t total = data_size + meta_size;
  std::lock_guard<std::mutex> g(s->mu);
  if (s->objects.count(key)) return -1;
  if (total > s->capacity) return -2;
  if (!EvictFor(s, total)) return -2;
  std::string path = HexPath(*s, key);
  if (::rename(src_path, path.c_str()) != 0) return -3;
  ObjectEntry e;
  e.path = path;
  e.data_size = data_size;
  e.meta_size = meta_size;
  e.sealed = true;
  e.pinned = pinned != 0;
  s->used += total;
  auto ins = s->objects.emplace(key, std::move(e));
  if (!ins.first->second.pinned) LruPush(s, key, &ins.first->second);
  return 0;
}

// 0 ok, -1 missing.
int store_seal(void* handle, const char* id) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(IdKey(id));
  if (it == s->objects.end()) return -1;
  ObjectEntry& e = it->second;
  e.sealed = true;
  if (e.refcount == 0 && !e.pinned && !e.in_lru) LruPush(s, it->first, &e);
  return 0;
}

// Pins the object (refcount++). 0 ok, -1 missing, -2 unsealed.
int store_get(void* handle, const char* id, char* out_path, int path_cap,
              uint64_t* data_size, uint64_t* meta_size) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(IdKey(id));
  if (it == s->objects.end()) return -1;
  ObjectEntry& e = it->second;
  if (!e.sealed) return -2;
  e.refcount++;
  LruRemove(s, &e);
  std::snprintf(out_path, path_cap, "%s", e.path.c_str());
  *data_size = e.data_size;
  *meta_size = e.meta_size;
  return 0;
}

// 0 ok, -1 missing.
int store_release(void* handle, const char* id) {
  auto* s = static_cast<Store*>(handle);
  std::string doomed;
  {
    std::lock_guard<std::mutex> g(s->mu);
    std::string key = IdKey(id);
    auto it = s->objects.find(key);
    if (it == s->objects.end()) return -1;
    ObjectEntry& e = it->second;
    if (e.refcount > 0) e.refcount--;
    if (e.refcount == 0) {
      if (e.pending_delete) {
        EraseObject(s, key, &doomed);
      } else if (e.sealed && !e.pinned && !e.in_lru) {
        LruPush(s, key, &e);
      }
    }
  }
  if (!doomed.empty()) ::unlink(doomed.c_str());
  return 0;
}

// Deletes now if unreferenced, else marks pending-delete. The two
// outcomes are distinct on purpose: 0 means the store's name is gone
// NOW (a worker recycling its staging inode may rewrite the shared
// pages), 1 means readers still hold it and the erase is deferred to
// the last release. -1 missing.
int store_delete(void* handle, const char* id) {
  auto* s = static_cast<Store*>(handle);
  std::string doomed;
  bool erased = false;
  {
    std::lock_guard<std::mutex> g(s->mu);
    std::string key = IdKey(id);
    auto it = s->objects.find(key);
    if (it == s->objects.end()) return -1;
    if (it->second.refcount == 0) {
      // doomed stays empty for slab-backed entries (the slab was
      // recycled, not unlinked) — track the erase separately so the
      // rc still says "gone NOW".
      EraseObject(s, key, &doomed);
      erased = true;
    } else {
      it->second.pending_delete = true;
    }
  }
  if (!erased) return 1;
  if (!doomed.empty()) ::unlink(doomed.c_str());
  return 0;
}

// 1 sealed-present, 0 absent, 2 present-unsealed.
int store_contains(void* handle, const char* id) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(IdKey(id));
  if (it == s->objects.end()) return 0;
  return it->second.sealed ? 1 : 2;
}

// Pin/unpin primary copies (exempt from eviction; spill candidates).
int store_pin(void* handle, const char* id, int pinned) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(IdKey(id));
  if (it == s->objects.end()) return -1;
  ObjectEntry& e = it->second;
  e.pinned = pinned != 0;
  if (e.pinned) {
    LruRemove(s, &e);
  } else if (e.sealed && e.refcount == 0 && !e.in_lru) {
    LruPush(s, it->first, &e);
  }
  return 0;
}

// graftshm: admit a STAGED (unsealed) entry whose payload is a
// store-owned arena slab. No rename — the slab path IS the object path
// for the rest of its life, so the client's CREATE-time mapping stays
// coherent through seal and every later get (same inode). Staged
// entries are invisible to LRU/eviction until sealed, exactly like
// store_create_object's. 0 ok, -1 already exists, -2 out of memory
// (after eviction).
int store_adopt_staged(void* handle, const char* id, const char* slab_path,
                       uint64_t data_size, uint64_t meta_size) {
  auto* s = static_cast<Store*>(handle);
  std::string key = IdKey(id);
  uint64_t total = data_size + meta_size;
  std::lock_guard<std::mutex> g(s->mu);
  if (s->objects.count(key)) return -1;
  if (total > s->capacity) return -2;
  if (!EvictFor(s, total)) return -2;
  ObjectEntry e;
  e.path = slab_path;
  e.data_size = data_size;
  e.meta_size = meta_size;
  e.slab_backed = true;
  s->used += total;
  s->objects.emplace(key, std::move(e));
  return 0;
}

// graftshm: seal a staged entry and pin it as a primary copy in one
// step (mirrors store_ingest_object's pinned admission: the agent's
// ledger pin must not race eviction). *total_out gets data+meta for
// the journal record. 0 ok, -1 missing or already sealed.
int store_seal_pin(void* handle, const char* id, uint64_t* total_out) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->objects.find(IdKey(id));
  if (it == s->objects.end()) return -1;
  ObjectEntry& e = it->second;
  if (e.sealed) return -1;
  e.sealed = true;
  e.pinned = true;
  LruRemove(s, &e);
  if (total_out != nullptr) *total_out = e.data_size + e.meta_size;
  return 0;
}

// graftshm: register/unregister (fn=null) the arena recycler for
// slab-backed erases.
void store_set_slab_recycler(void* handle,
                             void (*fn)(void*, const char*, uint64_t),
                             void* ctx) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  s->slab_recycler = fn;
  s->slab_recycler_ctx = ctx;
}

// Borrowed pointer to the store's directory string (valid for the
// store's lifetime) — used by the fast-path sidecar (store_server.cc).
const char* store_dir_ref(void* handle) {
  return static_cast<Store*>(handle)->dir.c_str();
}

uint64_t store_used(void* handle) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  return s->used;
}

uint64_t store_capacity(void* handle) {
  return static_cast<Store*>(handle)->capacity;
}

uint64_t store_num_objects(void* handle) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  return s->objects.size();
}

uint64_t store_num_evictions(void* handle) {
  auto* s = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> g(s->mu);
  return s->num_evictions;
}

}  // extern "C"
