// graftprof sampler: one native thread per process snapshots
// per-registered-thread CPU time and GIL acquire latency into a
// lock-free fixed-record ring (SURVEY §5.1 — the reference profiles
// out-of-process and on demand via py-spy attach + reporter-agent
// flame graphs; an in-process always-on sampler sees every window and
// can carry task attribution).
//
// Design constraints, in order (inherited from scope_core.cc):
//   1. The sampled threads pay nothing: the sampler reads their CPU
//      clocks from outside (CLOCK_THREAD_CPUTIME_ID via the clockid
//      handed over at registration); no signals, no interpreter
//      interruption, no per-call instrumentation.
//   2. Losing records under overload is fine; corrupting them is not.
//      Single-writer ring (only the sampler emits) with the same
//      lap-detecting drain as the graftscope rings.
//   3. The GIL probe must never touch the interpreter during
//      finalization: the Python seam joins the sampler (prof_stop)
//      from atexit before teardown, and the probe only runs between
//      prof_start and prof_stop.
//
// No static destructors: globals are PODs/atomics only, cold-path
// mutual exclusion is atomic_flag spinlocks (registration happens at
// thread birth; detached sidecar threads may die after main()).

#include "prof_core.h"

#include <atomic>
#include <cstring>
#include <ctime>

#include <pthread.h>
#include <stdlib.h>
#include <strings.h>

namespace {

inline void CpuRelax() {
#if defined(__x86_64__) || defined(__i386__)
  __builtin_ia32_pause();
#elif defined(__aarch64__)
  asm volatile("yield" ::: "memory");
#endif
}

struct SpinLock {
  std::atomic_flag f = ATOMIC_FLAG_INIT;
  void lock() {
    while (f.test_and_set(std::memory_order_acquire)) {
      CpuRelax();
    }
  }
  void unlock() { f.clear(std::memory_order_release); }
};
struct SpinGuard {
  SpinLock& l;
  explicit SpinGuard(SpinLock& lk) : l(lk) { l.lock(); }
  ~SpinGuard() { l.unlock(); }
};

uint64_t NowNs() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

// --- registered-thread table ----------------------------------------------

// Slot states: free -> active -> dead. Dead slots keep their name and
// cumulative CPU total (an exited sidecar thread stays attributed in
// `prof top`); they are reused only when the table would otherwise
// overflow.
constexpr int kSlotFree = 0, kSlotActive = 1, kSlotDead = 2;

struct ProfThread {
  std::atomic<int> state{kSlotFree};
  clockid_t clk{};                     // sampler-only after registration
  char name[kProfNameCap] = {0};       // written under g_table_lock
  uint64_t last_cpu_ns = 0;            // sampler-only
  std::atomic<uint64_t> cum_cpu_ns{0};
};

ProfThread g_threads[kProfMaxThreads];
std::atomic<int> g_high_water{0};  // slots ever handed out
SpinLock g_table_lock;

// Mark the slot dead (not free) when its thread exits: the sampler
// stops reading a clockid that no longer exists, but the cumulative
// total stays visible.
struct ProfLease {
  int slot = -1;
  ~ProfLease() {
    if (slot >= 0) {
      g_threads[slot].state.store(kSlotDead, std::memory_order_release);
    }
  }
};
thread_local ProfLease t_prof_lease;

// --- sample ring (single writer: the sampler thread) ----------------------

std::atomic<uint64_t> g_head{0};
uint64_t g_tail = 0;  // drainer cursor, under g_drain_lock
std::atomic<uint64_t> g_ring[kProfRingCap * 3];
SpinLock g_drain_lock;
std::atomic<uint64_t> g_dropped{0};
std::atomic<uint64_t> g_ticks{0};

void EmitRec(uint8_t kind, uint8_t slot, uint16_t flags, uint32_t val_us,
             uint64_t tick, uint64_t t_ns) {
  uint64_t w0 = (uint64_t)kind | ((uint64_t)slot << 8) |
                ((uint64_t)flags << 16) | ((uint64_t)val_us << 32);
  uint64_t h = g_head.load(std::memory_order_relaxed);
  size_t i = (size_t)(h & (kProfRingCap - 1)) * 3;
  g_ring[i].store(w0, std::memory_order_relaxed);
  g_ring[i + 1].store(tick, std::memory_order_relaxed);
  g_ring[i + 2].store(t_ns, std::memory_order_relaxed);
  g_head.store(h + 1, std::memory_order_release);
}

// --- enabled flag ---------------------------------------------------------

std::atomic<int> g_enabled{-1};  // -1 = resolve from env on first use

int ResolveEnabled() {
  const char* v = getenv("RAY_TPU_GRAFTPROF");
  int on = 1;
  if (v != nullptr &&
      (strcmp(v, "0") == 0 || strcasecmp(v, "false") == 0 ||
       strcasecmp(v, "off") == 0 || strcasecmp(v, "no") == 0)) {
    on = 0;
  }
  // Pure flag, no payload to publish: relaxed on both outcomes.
  int expected = -1;
  g_enabled.compare_exchange_strong(expected, on,
                                    std::memory_order_relaxed,
                                    std::memory_order_relaxed);
  return g_enabled.load(std::memory_order_relaxed);
}

// --- GIL probe ------------------------------------------------------------

typedef int (*GilEnsureFn)(void);
typedef void (*GilReleaseFn)(int);

std::atomic<void*> g_gil_ensure{nullptr};
std::atomic<void*> g_gil_release{nullptr};
std::atomic<uint64_t> g_gil_wait_ns{0};
std::atomic<uint64_t> g_gil_probes{0};

// One GIL probe every this-many ticks (~8 Hz at the default 67 Hz).
constexpr uint64_t kGilProbeStride = 8;

// --- sampler thread -------------------------------------------------------

std::atomic<int> g_run{0};
std::atomic<int> g_hz{kProfDefaultHz};
pthread_t g_sampler{};
int g_sampler_started = 0;  // under g_start_lock
SpinLock g_start_lock;

// Returns true when any registered thread burned CPU this tick — the
// idle-backoff signal for the sampler loop.
bool SampleTick(uint64_t tick, uint64_t now_ns, uint64_t period_ns) {
  EmitRec(kProfTick, 0, 0,
          (uint32_t)(period_ns / 1000 > 0xFFFFFFFFull
                         ? 0xFFFFFFFFull
                         : period_ns / 1000),
          tick, now_ns);
  bool active = false;
  int slots = g_high_water.load(std::memory_order_acquire);
  for (int s = 0; s < slots; s++) {
    ProfThread* t = &g_threads[s];
    if (t->state.load(std::memory_order_acquire) != kSlotActive) continue;
    timespec ts;
    if (clock_gettime(t->clk, &ts) != 0) {
      // The thread exited without running its lease destructor (e.g.
      // pthread_exit from foreign code): freeze its totals.
      t->state.store(kSlotDead, std::memory_order_release);
      continue;
    }
    uint64_t cpu =
        (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
    uint64_t d = cpu > t->last_cpu_ns ? cpu - t->last_cpu_ns : 0;
    t->last_cpu_ns = cpu;
    t->cum_cpu_ns.fetch_add(d, std::memory_order_relaxed);
    // A delta under ~100us over a whole period is scheduler noise (the
    // sampler's own bookkeeping shows up here), not workload.
    if (d > 100000ull) active = true;
    uint64_t d_us = d / 1000;
    EmitRec(kProfThreadCpu, (uint8_t)s, 0,
            (uint32_t)(d_us > 0xFFFFFFFFull ? 0xFFFFFFFFull : d_us),
            tick, now_ns);
  }
  GilEnsureFn ensure =
      (GilEnsureFn)g_gil_ensure.load(std::memory_order_acquire);
  GilReleaseFn release =
      (GilReleaseFn)g_gil_release.load(std::memory_order_acquire);
  // Probe the GIL on a stride, not every tick: each probe forces a GIL
  // handoff in the host process, and at full tick rate across every
  // worker on a small host that tax is measurable. A long hold is still
  // measured end-to-end — the probe blocks inside ensure() for the
  // remainder of whatever hold it lands in.
  if (ensure != nullptr && release != nullptr &&
      tick % kGilProbeStride == 0) {
    uint64_t t0 = NowNs();
    int st = ensure();
    uint64_t dt = NowNs() - t0;
    release(st);
    g_gil_wait_ns.fetch_add(dt, std::memory_order_relaxed);
    g_gil_probes.fetch_add(1, std::memory_order_relaxed);
    uint64_t w_us = dt / 1000;
    EmitRec(kProfGilWait, 0, 0,
            (uint32_t)(w_us > 0xFFFFFFFFull ? 0xFFFFFFFFull : w_us),
            tick, NowNs());
  }
  return active;
}

// Idle ticks stretch the sleep exponentially (1, 2, 4, 8, 16 periods);
// one active tick snaps back to full rate. On a core-starved host the
// wakeups themselves are the profiler's cost — a parked worker at the
// default 67 Hz was paying 75 context switches a second (67 ticks +
// 8 GIL probes) to observe nothing. The CPU-delta totals stay exact
// across stretched sleeps (they are cumulative clocks, not samples),
// only the reporting granularity coarsens while idle.
constexpr uint64_t kIdleStretchMax = 16;

void* SamplerLoop(void*) {
  prof_register_thread("graftprof-sampler");
  uint64_t last_ns = NowNs();
  uint64_t idle = 0;
  while (g_run.load(std::memory_order_acquire)) {
    int hz = g_hz.load(std::memory_order_relaxed);
    if (hz <= 0) hz = kProfDefaultHz;
    uint64_t period_ns = 1000000000ull / (uint64_t)hz;
    uint64_t stretch = idle < 4 ? (1ull << idle) : kIdleStretchMax;
    uint64_t sleep_ns = period_ns * stretch;
    timespec req;
    req.tv_sec = (time_t)(sleep_ns / 1000000000ull);
    req.tv_nsec = (long)(sleep_ns % 1000000000ull);
    nanosleep(&req, nullptr);
    if (!g_run.load(std::memory_order_acquire)) break;
    if (prof_enabled()) {
      uint64_t now = NowNs();
      uint64_t tick =
          g_ticks.fetch_add(1, std::memory_order_relaxed) + 1;
      bool active =
          SampleTick(tick, now, now > last_ns ? now - last_ns : period_ns);
      last_ns = now;
      idle = active ? 0 : idle + 1;
    } else {
      last_ns = NowNs();  // keep the next period honest after re-enable
      idle = idle + 1;    // disabled is as idle as it gets
    }
  }
  return nullptr;
}

}  // namespace

extern "C" {

int prof_register_thread(const char* name) {
  if (t_prof_lease.slot >= 0) return t_prof_lease.slot;
  clockid_t clk;
  if (pthread_getcpuclockid(pthread_self(), &clk) != 0) return -1;
  timespec ts;
  uint64_t cpu0 = 0;
  if (clock_gettime(clk, &ts) == 0) {
    cpu0 = (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
  }
  SpinGuard g(g_table_lock);
  int s = -1;
  int hw = g_high_water.load(std::memory_order_relaxed);
  if (hw < kProfMaxThreads) {
    s = hw;
  } else {
    // Full table: reuse a dead slot (its frozen total is forfeited to
    // keep live threads observable).
    for (int i = 0; i < kProfMaxThreads; i++) {
      if (g_threads[i].state.load(std::memory_order_relaxed)
          == kSlotDead) {
        s = i;
        break;
      }
    }
    if (s < 0) return -1;
  }
  ProfThread* t = &g_threads[s];
  t->clk = clk;
  t->last_cpu_ns = cpu0;
  t->cum_cpu_ns.store(0, std::memory_order_relaxed);
  size_t n = name != nullptr ? strlen(name) : 0;
  if (n >= kProfNameCap) n = kProfNameCap - 1;
  if (n > 0) memcpy(t->name, name, n);
  t->name[n] = '\0';
  // Publish the slot's clk/name/counters before the sampler can see
  // state == active.
  t->state.store(kSlotActive, std::memory_order_release);
  if (s == hw) {
    g_high_water.store(hw + 1, std::memory_order_release);
  }
  t_prof_lease.slot = s;
  return s;
}

void prof_set_gil_fns(void* ensure_fn, void* release_fn) {
  // Publish the pair; the sampler re-reads both with acquire each tick
  // and only probes when both are non-null.
  g_gil_ensure.store(ensure_fn, std::memory_order_release);
  g_gil_release.store(release_fn, std::memory_order_release);
}

int prof_start(int hz) {
  SpinGuard g(g_start_lock);
  g_hz.store(hz > 0 ? hz : kProfDefaultHz, std::memory_order_relaxed);
  if (g_sampler_started) return 0;
  g_run.store(1, std::memory_order_release);
  if (pthread_create(&g_sampler, nullptr, SamplerLoop, nullptr) != 0) {
    g_run.store(0, std::memory_order_release);
    return -1;
  }
  g_sampler_started = 1;
  return 0;
}

void prof_stop(void) {
  SpinGuard g(g_start_lock);
  if (!g_sampler_started) return;
  g_run.store(0, std::memory_order_release);
  pthread_join(g_sampler, nullptr);
  g_sampler_started = 0;
}

int prof_enabled(void) {
  int e = g_enabled.load(std::memory_order_relaxed);
  return e < 0 ? ResolveEnabled() : e;
}

void prof_set_enabled(int on) {
  g_enabled.store(on ? 1 : 0, std::memory_order_relaxed);
}

int prof_drain(char* buf, int cap) {
  SpinGuard dg(g_drain_lock);
  int n = 0;
  uint64_t head = g_head.load(std::memory_order_acquire);
  uint64_t t = g_tail;
  if (head - t >= kProfRingCap) {
    uint64_t safe = head - kProfRingCap + 1;
    g_dropped.fetch_add(safe - t, std::memory_order_relaxed);
    t = safe;
  }
  while (t < head) {
    if (n + kProfRecordSize > cap) break;
    size_t i = (size_t)(t & (kProfRingCap - 1)) * 3;
    uint64_t w0 = g_ring[i].load(std::memory_order_relaxed);
    uint64_t w1 = g_ring[i + 1].load(std::memory_order_relaxed);
    uint64_t w2 = g_ring[i + 2].load(std::memory_order_relaxed);
    // Lap check: if the sampler reached t + cap while we copied, the
    // slot may hold a half-written newer record — discard and skip to
    // the new safe window.
    uint64_t h2 = g_head.load(std::memory_order_acquire);
    if (h2 - t >= kProfRingCap) {
      uint64_t safe = h2 - kProfRingCap + 1;
      g_dropped.fetch_add(safe - t, std::memory_order_relaxed);
      t = safe;
      head = h2;
      continue;
    }
    ProfWireRec rec;
    rec.kind = (uint8_t)(w0 & 0xff);
    rec.slot = (uint8_t)((w0 >> 8) & 0xff);
    rec.flags = (uint16_t)((w0 >> 16) & 0xffff);
    rec.val_us = (uint32_t)(w0 >> 32);
    rec.tick = w1;
    rec.t_ns = w2;
    std::memcpy(buf + n, &rec, kProfRecordSize);
    n += kProfRecordSize;
    t++;
  }
  g_tail = t;
  return n;
}

uint64_t prof_dropped(void) {
  return g_dropped.load(std::memory_order_relaxed);
}

uint64_t prof_ticks(void) {
  return g_ticks.load(std::memory_order_relaxed);
}

int prof_thread_count(void) {
  return g_high_water.load(std::memory_order_acquire);
}

int prof_thread_cpu_ns(uint64_t* out, int max_slots) {
  int hw = g_high_water.load(std::memory_order_acquire);
  int k = max_slots < hw ? max_slots : hw;
  for (int s = 0; s < k; s++) {
    out[s] = g_threads[s].cum_cpu_ns.load(std::memory_order_relaxed);
  }
  return k;
}

int prof_thread_name(int slot, char* buf, int cap) {
  if (slot < 0 || slot >= g_high_water.load(std::memory_order_acquire)) {
    return -1;
  }
  if (g_threads[slot].state.load(std::memory_order_acquire)
      == kSlotFree) {
    return -1;
  }
  SpinGuard g(g_table_lock);  // names are written under the table lock
  int n = (int)strlen(g_threads[slot].name);
  if (n >= cap) n = cap - 1;
  if (n > 0) memcpy(buf, g_threads[slot].name, (size_t)n);
  if (cap > 0) buf[n] = '\0';
  return n;
}

uint64_t prof_gil_wait_ns(void) {
  return g_gil_wait_ns.load(std::memory_order_relaxed);
}

uint64_t prof_gil_probes(void) {
  return g_gil_probes.load(std::memory_order_relaxed);
}

}  // extern "C"
