// graftcopy: vectored, GIL-free copy engine for the object-store put
// plane.
//
// The put hot path serializes a value into pickle-5 out-of-band buffer
// segments and lands them in a tmpfs object file. Python can drive that
// with os.pwritev (one syscall, GIL dropped for its duration), but a
// single thread tops out at the per-core copy bandwidth; the reference's
// plasma client hits the same wall and parallelizes its memcpy
// (reference: src/ray/object_manager/plasma/client.cc WriteObject /
// plasma putting via multiple memcpy threads). This engine does the
// same for the file-backed layout: `copy_write_scatter` splits the
// segment list into fixed-size chunks and fans them out over a small
// worker pool, with the CALLING thread participating so a put never
// waits on a parked pool. On 1-core hosts the pool is empty and the
// caller runs the chunks sequentially — same syscall pattern as
// pwritev, no thread ping-pong.
//
// Also exported here: `copy_linkat`, the O_TMPFILE+linkat ingredient of
// the fused put pipeline (CPython's os.link cannot express
// AT_SYMLINK_FOLLOW on a /proc/self/fd source, so the atomic
// link-into-the-store-dir step needs a native helper).
//
// Exposed via libraytpu_store.so next to the store engine; bound in
// ray_tpu/core/object_store.py::_load_lib and wrapped by
// ray_tpu/core/_native/graftcopy.py.

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

#include "prof_core.h"
#include "scope_core.h"

extern "C" {
// One scatter segment: copy `len` bytes from `src` to file offset `off`.
// Mirrored field-for-field by the ctypes CopySeg struct in
// ray_tpu/core/_native/graftcopy.py (lint pass 3d checks the binding
// signatures; keep the layout in sync).
typedef struct {
  const void* src;
  uint64_t len;
  uint64_t off;
} CopySeg;
}

namespace {

// Split unit: big enough that per-chunk overhead (one pwrite, one
// atomic fetch_add) is noise, small enough that a GiB put spreads over
// every worker.
constexpr uint64_t kCopyChunk = 8ull << 20;

int PwriteFull(int fd, const char* p, uint64_t n, uint64_t off) {
  while (n > 0) {
    ssize_t w = ::pwrite(fd, p, n, (off_t)off);
    if (w < 0) {
      if (errno == EINTR) continue;
      return errno ? errno : EIO;
    }
    p += w;
    n -= (uint64_t)w;
    off += (uint64_t)w;
  }
  return 0;
}

struct Job {
  int fd = -1;
  std::vector<CopySeg> chunks;   // pre-split; read-only once published
  std::atomic<size_t> next{0};   // claim cursor
  std::atomic<size_t> done{0};   // completed chunks
  std::atomic<int> err{0};       // first errno observed
};

// Claim-and-copy until the job's chunks are exhausted. Runs on workers
// AND the calling thread; the atomic cursor makes work-stealing free.
void RunChunks(Job* j) {
  for (;;) {
    size_t i = j->next.fetch_add(1, std::memory_order_relaxed);
    if (i >= j->chunks.size()) return;
    const CopySeg& c = j->chunks[i];
    int rc = PwriteFull(j->fd, static_cast<const char*>(c.src), c.len,
                        c.off);
    if (rc != 0) {
      // Relaxed is enough: the err CAS is sequenced before our
      // done.fetch_add(acq_rel) below, and the waiter only reads err
      // after done.load(acquire) observes the final count — the done
      // release sequence carries the err value across.
      int expected = 0;
      j->err.compare_exchange_strong(expected, rc,
                                     std::memory_order_relaxed,
                                     std::memory_order_relaxed);
    }
    j->done.fetch_add(1, std::memory_order_acq_rel);
  }
}

struct Engine {
  std::mutex mu;
  std::condition_variable cv_work;  // workers park here
  std::condition_variable cv_done;  // callers wait for their job
  std::deque<std::shared_ptr<Job>> queue;
  std::vector<std::thread> workers;
  bool stopping = false;
};

void WorkerLoop(Engine* e) {
  prof_register_thread("graftcopy-worker");
  std::unique_lock<std::mutex> lk(e->mu);
  for (;;) {
    while (!e->stopping && e->queue.empty()) e->cv_work.wait(lk);
    if (e->stopping) return;
    // shared_ptr copy keeps the job alive even if the caller returns
    // while this worker is between chunks.
    std::shared_ptr<Job> j = e->queue.front();
    lk.unlock();
    RunChunks(j.get());
    lk.lock();
    // RunChunks only returns once every chunk is claimed, so the job
    // can leave the queue (later workers would find nothing to do).
    if (!e->queue.empty() && e->queue.front() == j) e->queue.pop_front();
    if (j->done.load(std::memory_order_acquire) >= j->chunks.size()) {
      e->cv_done.notify_all();
    }
  }
}

}  // namespace

extern "C" {

// nthreads <= 0: auto-size to hardware cores minus one (the caller
// participates, so a pool of cores-1 saturates the machine without
// oversubscribing). A 1-core host gets an empty pool — every scatter
// runs sequentially on the calling thread, no threads, no locks.
void* copy_engine_create(int nthreads) {
  auto* e = new Engine();
  if (nthreads < 0) nthreads = 0;
  if (nthreads == 0) {
    unsigned hw = std::thread::hardware_concurrency();
    nthreads = hw > 1 ? (int)hw - 1 : 0;
    if (nthreads > 16) nthreads = 16;
  }
  for (int i = 0; i < nthreads; i++) {
    e->workers.emplace_back(WorkerLoop, e);
  }
  return e;
}

void copy_engine_destroy(void* handle) {
  auto* e = static_cast<Engine*>(handle);
  {
    std::lock_guard<std::mutex> g(e->mu);
    e->stopping = true;
  }
  e->cv_work.notify_all();
  for (auto& t : e->workers) t.join();
  delete e;
}

int copy_engine_threads(void* handle) {
  return (int)static_cast<Engine*>(handle)->workers.size();
}

// Copy every segment into fd. Returns 0 on success, -errno on the first
// write error (all claimed chunks still run to completion so no thread
// is left touching caller memory after return).
int copy_write_scatter(void* handle, int fd, const CopySeg* segs,
                       int nsegs) {
  auto* e = static_cast<Engine*>(handle);
  if (nsegs <= 0) return 0;

  uint64_t t0 = scope_enabled() ? scope_now_ns() : 0;
  // graftscope span-in-one on every exit: seq_or_oid = start_ns,
  // t_ns = end_ns, size = bytes (u32-clipped), op = 1 on error.
  auto scoped = [t0](uint64_t total, int rc) {
    if (t0 != 0) {
      uint64_t t1 = scope_now_ns();
      uint32_t sz = total > 0xFFFFFFFFull ? 0xFFFFFFFFu : (uint32_t)total;
      scope_emit(kScopeCopyScatter, rc == 0 ? 0 : 1, 0, sz, t0, t1,
                 t1 - t0);
    }
    return rc;
  };

  // Sequential path: no pool, or too little data to amortize a handoff.
  uint64_t total = 0;
  for (int i = 0; i < nsegs; i++) total += segs[i].len;
  if (e->workers.empty() || total <= kCopyChunk) {
    for (int i = 0; i < nsegs; i++) {
      int rc = PwriteFull(fd, static_cast<const char*>(segs[i].src),
                          segs[i].len, segs[i].off);
      if (rc != 0) return scoped(total, -rc);
    }
    return scoped(total, 0);
  }

  auto job = std::make_shared<Job>();
  job->fd = fd;
  job->chunks.reserve((size_t)(total / kCopyChunk) + (size_t)nsegs);
  for (int i = 0; i < nsegs; i++) {
    const char* p = static_cast<const char*>(segs[i].src);
    uint64_t len = segs[i].len, off = segs[i].off;
    while (len > 0) {
      uint64_t n = len < kCopyChunk ? len : kCopyChunk;
      job->chunks.push_back(CopySeg{p, n, off});
      p += n;
      off += n;
      len -= n;
    }
  }
  {
    std::lock_guard<std::mutex> g(e->mu);
    e->queue.push_back(job);
  }
  e->cv_work.notify_all();
  RunChunks(job.get());  // caller participates
  std::unique_lock<std::mutex> lk(e->mu);
  // Our RunChunks exhausted the claim cursor; drop the job from the
  // queue if no worker got there first.
  for (auto it = e->queue.begin(); it != e->queue.end(); ++it) {
    if (*it == job) {
      e->queue.erase(it);
      break;
    }
  }
  while (job->done.load(std::memory_order_acquire) < job->chunks.size()) {
    e->cv_done.wait(lk);
  }
  // Ordered by the done.load(acquire) above; see RunChunks.
  return scoped(total, -job->err.load(std::memory_order_relaxed));
}

// Atomically link the (possibly anonymous O_TMPFILE) fd's file at dst.
// 0 ok, -errno on failure (-EEXIST: dst already exists).
int copy_linkat(int src_fd, const char* dst) {
  char proc[64];
  std::snprintf(proc, sizeof proc, "/proc/self/fd/%d", src_fd);
  int rc = 0;
  if (::linkat(AT_FDCWD, proc, AT_FDCWD, dst, AT_SYMLINK_FOLLOW) != 0) {
    rc = errno ? -errno : -EIO;
  }
  scope_emit(kScopeCopyLink, rc == 0 ? 0 : 1, 0, 0, 0, 0, 0);
  return rc;
}

}  // extern "C"
