// Unit tests for the graftlog ring (log_core.cc). Run plain and under
// TSAN/ASAN in CI — the drain-while-writing storm exercises the
// single-writer ring against a concurrent reader (the same race the
// node agent's tailer runs live), and the file-decode test pins the
// crash-persistence contract: everything emitted is on the filesystem
// the moment log_emit returns, exactly as the salvage path will find
// it after a SIGKILL.

#include "log_core.h"

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <string>
#include <thread>
#include <vector>

#include <sys/stat.h>
#include <unistd.h>

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,     \
                   #cond);                                             \
      std::abort();                                                    \
    }                                                                  \
  } while (0)

namespace {

char g_dir[256];

std::string RingPath(uint64_t pid) {
  char buf[320];
  std::snprintf(buf, sizeof(buf), "%s/logring-%llu", g_dir,
                (unsigned long long)pid);
  return std::string(buf);
}

std::vector<LogWireRec> DrainOnce() {
  std::vector<LogWireRec> out;
  std::vector<char> buf(1 << 20);
  int n = log_drain(buf.data(), (int)buf.size());
  CHECK(n >= 0);
  CHECK(n % kLogRecordSize == 0);
  for (int i = 0; i < n; i += kLogRecordSize) {
    LogWireRec w;
    std::memcpy(&w, buf.data() + i, kLogRecordSize);
    out.push_back(w);
  }
  return out;
}

std::vector<LogWireRec> Drain() {
  std::vector<LogWireRec> out;
  for (;;) {
    auto recs = DrainOnce();
    if (recs.empty()) return out;
    out.insert(out.end(), recs.begin(), recs.end());
  }
}

std::string Field(const char* p, int cap) {
  int n = 0;
  while (n < cap && p[n] != '\0') n++;
  return std::string(p, (size_t)n);
}

int TestDisabled() {
  log_set_enabled(0);
  CHECK(log_enabled() == 0);
  CHECK(log_emit(20, kLogSrcLogger, "t", "a", "dropped", -1) == 0);
  log_set_enabled(1);
  CHECK(log_enabled() == 1);
  return 0;
}

int TestRoundtrip() {
  CHECK(log_ring_open(g_dir, (uint64_t)getpid()) == 0);
  CHECK(log_emitted() == 0);
  uint64_t s1 = log_emit(20, kLogSrcLogger,
                         "00112233445566778899aabbccddeeff",
                         "a1b2c3d4e5f6", "hello graftlog", -1);
  CHECK(s1 == 1);
  uint64_t s2 = log_emit(40, kLogSrcStderr, "", nullptr, "boom", 4);
  CHECK(s2 == 2);
  // Oversized line: msg truncates, line_len keeps the true length.
  std::string big(kLogMsgCap + 100, 'x');
  uint64_t s3 =
      log_emit(30, kLogSrcStdout, "ff", "ee", big.c_str(), (int)big.size());
  CHECK(s3 == 3);
  CHECK(log_emitted() == 3);
  auto recs = Drain();
  CHECK(recs.size() == 3);
  CHECK(recs[0].level == 20 && recs[0].source == kLogSrcLogger);
  CHECK(recs[0].seq == 1);
  CHECK(Field(recs[0].task, kLogTaskCap) ==
        "00112233445566778899aabbccddeeff");
  CHECK(Field(recs[0].actor, kLogActorCap) == "a1b2c3d4e5f6");
  CHECK(recs[0].line_len == 14);
  CHECK(Field(recs[0].msg, kLogMsgCap) == "hello graftlog");
  CHECK(recs[0].t_ns > 0);
  CHECK(recs[1].level == 40 && recs[1].source == kLogSrcStderr);
  CHECK(Field(recs[1].task, kLogTaskCap).empty());
  CHECK(Field(recs[1].actor, kLogActorCap).empty());
  CHECK(Field(recs[1].msg, kLogMsgCap) == "boom");
  CHECK(recs[2].line_len == (uint16_t)(kLogMsgCap + 100));
  CHECK(Field(recs[2].msg, kLogMsgCap) == std::string(kLogMsgCap, 'x'));
  CHECK(recs[1].t_ns >= recs[0].t_ns && recs[2].t_ns >= recs[1].t_ns);
  CHECK(Drain().empty());
  return 0;
}

int TestFileDecode() {
  // The crash-persistence contract: the moment log_emit returns, the
  // record is decodable from the FILE by another reader — no flush or
  // clean shutdown required. Decode the bytes exactly as the Python
  // salvage path does.
  uint64_t pid = (uint64_t)getpid();
  std::string path = RingPath(pid);
  FILE* f = std::fopen(path.c_str(), "rb");
  CHECK(f != nullptr);
  struct stat st;
  CHECK(stat(path.c_str(), &st) == 0);
  CHECK(st.st_size ==
        (off_t)kLogHeaderSize + (off_t)kLogRingSlots * kLogRecordSize);
  uint32_t u32[4];
  CHECK(std::fread(u32, sizeof(u32), 1, f) == 1);
  CHECK(u32[0] == (uint32_t)kLogMagic);
  CHECK(u32[1] == (uint32_t)kLogRingVersion);
  CHECK(u32[2] == (uint32_t)kLogRecordSize);
  CHECK(u32[3] == (uint32_t)kLogRingSlots);
  uint64_t u64[4];
  CHECK(std::fread(u64, sizeof(u64), 1, f) == 1);
  CHECK(u64[0] == pid);
  uint64_t head = u64[1];
  CHECK(head == log_emitted());
  CHECK(head >= 3);  // TestRoundtrip's records are already on disk
  // Slot (head - 1) holds the newest record.
  uint64_t last = head - 1;
  CHECK(std::fseek(f,
                   (long)(kLogHeaderSize +
                          (last % kLogRingSlots) * kLogRecordSize),
                   SEEK_SET) == 0);
  LogWireRec w;
  CHECK(std::fread(&w, sizeof(w), 1, f) == 1);
  CHECK(w.seq == (uint32_t)head);
  std::fclose(f);
  return 0;
}

int TestEmitBatch() {
  Drain();
  uint64_t base = log_emitted();
  // Mixed batch: empty lines (doubled \n, trailing \n) are skipped,
  // the rest land as consecutive records sharing one timestamp.
  uint64_t h = log_emit_batch(20, kLogSrcStdout, "feed", "beef",
                              "alpha\n\nbravo\ncharlie\n", 21);
  CHECK(h == base + 3);
  auto recs = Drain();
  CHECK(recs.size() == 3);
  CHECK(Field(recs[0].msg, kLogMsgCap) == "alpha");
  CHECK(Field(recs[1].msg, kLogMsgCap) == "bravo");
  CHECK(Field(recs[2].msg, kLogMsgCap) == "charlie");
  CHECK(recs[0].t_ns == recs[2].t_ns);
  CHECK(recs[0].seq == (uint32_t)(base + 1));
  CHECK(recs[2].seq == (uint32_t)(base + 3));
  for (const LogWireRec& r : recs) {
    CHECK(r.level == 20 && r.source == kLogSrcStdout);
    CHECK(Field(r.task, kLogTaskCap) == "feed");
    CHECK(Field(r.actor, kLogActorCap) == "beef");
  }
  // No final newline: the tail still counts as a line.
  CHECK(log_emit_batch(20, kLogSrcStdout, "", "", "tail", 4) ==
        base + 4);
  CHECK(Drain().size() == 1);
  // All-empty batch appends nothing.
  CHECK(log_emit_batch(20, kLogSrcStdout, "", "", "\n\n", 2) == 0);
  CHECK(log_emitted() == base + 4);
  return 0;
}

int TestWraparound() {
  Drain();
  uint64_t dropped0 = log_dropped();
  uint64_t base = log_emitted();
  // Storm well past ring capacity without draining: the reader must
  // land in the fresh window and account the lapped slots as dropped.
  int total = 2 * kLogRingSlots + 37;
  for (int i = 0; i < total; i++) {
    char line[64];
    std::snprintf(line, sizeof(line), "line %d", i);
    CHECK(log_emit(20, kLogSrcStdout, "t", "a", line, -1) ==
          base + (uint64_t)i + 1);
  }
  auto recs = Drain();
  CHECK(log_dropped() - dropped0 >= (uint64_t)(total - kLogRingSlots));
  CHECK(!recs.empty());
  CHECK((int)recs.size() <= kLogRingSlots);
  // Only records from the fresh window survive, in order, ending at
  // the newest.
  uint32_t prev = 0;
  for (const LogWireRec& r : recs) {
    CHECK(r.seq > prev);
    prev = r.seq;
  }
  CHECK(prev == (uint32_t)(base + (uint64_t)total));
  return 0;
}

int TestDrainWhileWriting() {
  Drain();
  // Writer threads storm the ring while the main thread drains — the
  // same shape as the node agent tailing a live worker. Every record
  // that survives the lap check must be well-formed.
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> wrote{0};
  std::vector<std::thread> writers;
  for (int w = 0; w < 3; w++) {
    writers.emplace_back([&, w] {
      int i = 0;
      while (!stop.load(std::memory_order_relaxed)) {
        char line[96];
        std::snprintf(line, sizeof(line), "writer %d line %d", w, i++);
        if (log_emit(20 + 10 * (w % 3), kLogSrcLogger,
                     "00112233445566778899aabbccddeeff", "a1b2c3d4e5f6",
                     line, -1) != 0) {
          wrote.fetch_add(1, std::memory_order_relaxed);
        }
      }
    });
  }
  uint64_t seen = 0;
  timespec t0;
  clock_gettime(CLOCK_MONOTONIC, &t0);
  for (;;) {
    for (const LogWireRec& r : DrainOnce()) {
      CHECK(r.level >= 20 && r.level <= 40);
      CHECK(r.source < kLogSrcCount);
      CHECK(r.seq != 0);
      CHECK(Field(r.task, kLogTaskCap) ==
            "00112233445566778899aabbccddeeff");
      CHECK(std::strncmp(r.msg, "writer ", 7) == 0);
      seen++;
    }
    timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    if ((now.tv_sec - t0.tv_sec) * 1000000000L +
            (now.tv_nsec - t0.tv_nsec) >
        500L * 1000 * 1000) {
      break;
    }
  }
  stop.store(true);
  for (auto& t : writers) t.join();
  Drain();
  CHECK(seen > 0);
  CHECK(wrote.load(std::memory_order_relaxed) >= seen);
  return 0;
}

int TestReopen() {
  // Re-open resets the ring (fresh head) and re-points the writer.
  uint64_t pid = (uint64_t)getpid();
  CHECK(log_ring_open(g_dir, pid) == 0);
  CHECK(log_emitted() == 0);
  CHECK(log_emit(20, kLogSrcAgent, "", "", "after reopen", -1) == 1);
  auto recs = Drain();
  CHECK(recs.size() == 1);
  CHECK(Field(recs[0].msg, kLogMsgCap) == "after reopen");
  // Close unmaps but leaves the file for salvage; emit then drops.
  log_ring_close();
  uint64_t d0 = log_dropped();
  CHECK(log_emit(20, kLogSrcAgent, "", "", "into the void", -1) == 0);
  CHECK(log_dropped() == d0 + 1);
  struct stat st;
  CHECK(stat(RingPath(pid).c_str(), &st) == 0);
  return 0;
}

}  // namespace

int main() {
  std::snprintf(g_dir, sizeof(g_dir), "/tmp/graftlog_test_XXXXXX");
  CHECK(mkdtemp(g_dir) != nullptr);
  log_set_enabled(1);
  int rc = 0;
  rc |= TestDisabled();
  std::printf("log disabled ok\n");
  rc |= TestRoundtrip();
  std::printf("log roundtrip ok\n");
  rc |= TestFileDecode();
  std::printf("log file decode ok\n");
  rc |= TestEmitBatch();
  std::printf("log emit batch ok\n");
  rc |= TestWraparound();
  std::printf("log wraparound ok\n");
  rc |= TestDrainWhileWriting();
  std::printf("log drain-while-writing ok\n");
  rc |= TestReopen();
  std::printf("log reopen ok\n");
  std::string cmd = std::string("rm -rf ") + g_dir;
  if (std::system(cmd.c_str()) != 0) return 1;
  if (rc == 0) std::printf("log_core_test: ALL OK\n");
  return rc;
}
