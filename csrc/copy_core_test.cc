// Native unit tests for the graftcopy engine (copy_core.cc): scatter
// correctness (gaps, ordering, partial chunks), pool parallelism,
// concurrent scatters through one shared engine (the TSAN target —
// workers and callers hand jobs around under the engine mutex), error
// propagation, and the O_TMPFILE+linkat helper. Same plain-assert
// harness as object_store_test.cc; runs under `make test` and the
// TSAN/ASAN targets.

#undef NDEBUG
#include <cassert>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {
typedef struct {
  const void* src;
  uint64_t len;
  uint64_t off;
} CopySeg;
void* copy_engine_create(int nthreads);
void copy_engine_destroy(void* handle);
int copy_engine_threads(void* handle);
int copy_write_scatter(void* handle, int fd, const CopySeg* segs,
                       int nsegs);
int copy_linkat(int src_fd, const char* dst);
}

namespace {

std::string TempDir(const char* name) {
  std::string dir = std::string("/tmp/raytpu_copy_test_") + name + "_" +
                    std::to_string(::getpid());
  std::string cmd = "rm -rf " + dir + " && mkdir -p " + dir;
  assert(std::system(cmd.c_str()) == 0);
  return dir;
}

std::vector<char> ReadAll(int fd) {
  off_t sz = ::lseek(fd, 0, SEEK_END);
  assert(sz >= 0);
  std::vector<char> out((size_t)sz);
  assert(::pread(fd, out.data(), out.size(), 0) == (ssize_t)out.size());
  return out;
}

void CheckScatter(void* eng, size_t nsegs, size_t seg_len, size_t gap) {
  std::string dir = TempDir("scatter");
  std::string path = dir + "/out";
  int fd = ::open(path.c_str(), O_CREAT | O_RDWR | O_TRUNC, 0600);
  assert(fd >= 0);
  std::vector<std::vector<char>> bufs(nsegs);
  std::vector<CopySeg> segs(nsegs);
  uint64_t off = 0;
  for (size_t i = 0; i < nsegs; i++) {
    bufs[i].assign(seg_len + i, (char)('a' + (i % 26)));
    segs[i] = CopySeg{bufs[i].data(), bufs[i].size(), off};
    off += bufs[i].size() + gap;
  }
  assert(copy_write_scatter(eng, fd, segs.data(), (int)nsegs) == 0);
  std::vector<char> got = ReadAll(fd);
  assert(got.size() == segs.back().off + bufs.back().size());
  for (size_t i = 0; i < nsegs; i++) {
    assert(std::memcmp(got.data() + segs[i].off, bufs[i].data(),
                       bufs[i].size()) == 0);
    if (i + 1 < nsegs) {  // gap bytes read back as zeros (file holes)
      for (uint64_t g = segs[i].off + bufs[i].size();
           g < segs[i + 1].off; g++) {
        assert(got[g] == 0);
      }
    }
  }
  ::close(fd);
  assert(std::system(("rm -rf " + dir).c_str()) == 0);
}

void TestSequentialScatter() {
  void* eng = copy_engine_create(-1);  // clamps to 0 workers
  assert(copy_engine_threads(eng) == 0);
  CheckScatter(eng, 5, 1000, 37);
  copy_engine_destroy(eng);
  std::printf("  sequential scatter OK\n");
}

void TestPooledScatter() {
  void* eng = copy_engine_create(4);
  assert(copy_engine_threads(eng) == 4);
  // > one chunk (8 MiB) total so the pool actually engages; odd sizes
  // exercise the chunk-split remainders.
  CheckScatter(eng, 3, (9 << 20) + 123, 61);
  CheckScatter(eng, 1, (32 << 20) + 1, 0);
  copy_engine_destroy(eng);
  std::printf("  pooled scatter OK\n");
}

void TestConcurrentScatters() {
  // Many caller threads share one engine: jobs queue behind each other
  // and every caller must get exactly its own bytes back.
  void* eng = copy_engine_create(3);
  std::string dir = TempDir("concurrent");
  auto worker = [&](int t) {
    std::string path = dir + "/out" + std::to_string(t);
    int fd = ::open(path.c_str(), O_CREAT | O_RDWR | O_TRUNC, 0600);
    assert(fd >= 0);
    std::vector<char> buf((10 << 20) + t, (char)('A' + t));
    for (int rep = 0; rep < 3; rep++) {
      CopySeg seg{buf.data(), buf.size(), 0};
      assert(copy_write_scatter(eng, fd, &seg, 1) == 0);
    }
    std::vector<char> got = ReadAll(fd);
    assert(got.size() == buf.size());
    assert(std::memcmp(got.data(), buf.data(), buf.size()) == 0);
    ::close(fd);
  };
  std::vector<std::thread> ts;
  for (int t = 0; t < 4; t++) ts.emplace_back(worker, t);
  for (auto& th : ts) th.join();
  copy_engine_destroy(eng);
  assert(std::system(("rm -rf " + dir).c_str()) == 0);
  std::printf("  concurrent scatters OK\n");
}

void TestErrorPropagation() {
  void* eng = copy_engine_create(2);
  std::vector<char> buf(20 << 20, 'x');
  CopySeg seg{buf.data(), buf.size(), 0};
  // Closed fd: every chunk fails; the first errno comes back negated.
  assert(copy_write_scatter(eng, /*fd=*/-1, &seg, 1) == -EBADF);
  // Read-only fd fails too (engine path, multiple chunks).
  int fd = ::open("/dev/null", O_RDONLY);
  assert(fd >= 0);
  assert(copy_write_scatter(eng, fd, &seg, 1) == -EBADF);
  ::close(fd);
  // Empty scatter is a no-op.
  assert(copy_write_scatter(eng, -1, nullptr, 0) == 0);
  copy_engine_destroy(eng);
  std::printf("  error propagation OK\n");
}

void TestLinkat() {
  std::string dir = TempDir("linkat");
  std::string dst = dir + "/linked";
  int fd = ::open(dir.c_str(), O_TMPFILE | O_RDWR, 0600);
  if (fd < 0) {
    // Filesystem without O_TMPFILE: exercise the named-source fallback
    // shape instead (linkat on a regular file is EEXIST-checked too).
    std::string src = dir + "/src";
    fd = ::open(src.c_str(), O_CREAT | O_RDWR, 0600);
    assert(fd >= 0);
  }
  assert(::write(fd, "graftcopy", 9) == 9);
  struct stat st;
  assert(::stat(dst.c_str(), &st) != 0);  // not visible yet
  assert(copy_linkat(fd, dst.c_str()) == 0);
  assert(::stat(dst.c_str(), &st) == 0 && st.st_size == 9);
  char got[16] = {0};
  int rfd = ::open(dst.c_str(), O_RDONLY);
  assert(::read(rfd, got, 9) == 9 && std::memcmp(got, "graftcopy", 9) == 0);
  ::close(rfd);
  // Linking over an existing name must fail cleanly with -EEXIST (the
  // put path maps this to "object already stored").
  assert(copy_linkat(fd, dst.c_str()) == -EEXIST);
  ::close(fd);
  assert(std::system(("rm -rf " + dir).c_str()) == 0);
  std::printf("  linkat OK\n");
}

}  // namespace

int main() {
  TestSequentialScatter();
  TestPooledScatter();
  TestConcurrentScatters();
  TestErrorPropagation();
  TestLinkat();
  std::printf("copy_core_test: ALL OK\n");
  return 0;
}
