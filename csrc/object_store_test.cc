// Native unit tests for the shared-memory object store — the gtest
// analogue of the reference's plasma unit suite (reference:
// src/ray/object_manager/plasma/ tests driven by Bazel). Plain asserts,
// no framework dependency: `make test` builds and runs this against the
// same translation unit the agent loads, so eviction/pin/refcount/
// ingest races are caught at the C++ layer instead of surfacing as
// flaky Python integration tests.

#undef NDEBUG
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <atomic>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {
void* store_create(const char* dir, uint64_t capacity);
void store_destroy(void* handle);
int store_create_object(void* handle, const char* id, uint64_t data_size,
                        uint64_t meta_size, char* out_path, int path_cap);
int store_ingest_object(void* handle, const char* id, const char* src_path,
                        uint64_t data_size, uint64_t meta_size, int pinned);
int store_seal(void* handle, const char* id);
int store_get(void* handle, const char* id, char* out_path, int path_cap,
              uint64_t* data_size, uint64_t* meta_size);
int store_release(void* handle, const char* id);
int store_delete(void* handle, const char* id);
int store_contains(void* handle, const char* id);
int store_pin(void* handle, const char* id, int pinned);
void* store_server_start(void* store_handle, const char* sock_path,
                         int* notify_fd_out);
int store_server_drain(void* handle, char* buf, int cap);
void store_server_stop(void* handle);
int store_client_connect(const char* sock_path);
int store_client_request(int fd, uint8_t op, const char* oid, uint64_t a,
                         uint64_t b, const char* name, int32_t* rc_out,
                         uint64_t* ds_out, uint64_t* ms_out,
                         char* path_out, int path_cap);
int store_client_create(int fd, const char* oid, uint64_t data_size,
                        uint64_t meta_size, int32_t* rc_out,
                        uint64_t* reused_out, char* path_out, int path_cap,
                        int* slab_fd_out);
int store_client_seal(int fd, const char* oid, int32_t* rc_out,
                      uint64_t* ds_out, uint64_t* ms_out);
void store_client_close(int fd);
uint64_t store_used(void* handle);
uint64_t store_capacity(void* handle);
uint64_t store_num_objects(void* handle);
uint64_t store_num_evictions(void* handle);
}

namespace {

std::string MakeId(char tag) { return std::string(20, tag); }

bool FileExists(const std::string& path) {
  struct stat st;
  return ::stat(path.c_str(), &st) == 0;
}

void WriteFile(const std::string& path, const std::string& payload) {
  int fd = ::open(path.c_str(), O_CREAT | O_RDWR | O_TRUNC, 0600);
  assert(fd >= 0);
  assert(::write(fd, payload.data(), payload.size()) ==
         (ssize_t)payload.size());
  ::close(fd);
}

std::string TempDir(const char* name) {
  std::string dir = std::string("/tmp/raytpu_store_test_") + name + "_" +
                    std::to_string(::getpid());
  std::string cmd = "rm -rf " + dir;
  assert(std::system(cmd.c_str()) == 0);
  return dir;
}

void TestCreateSealGetLifecycle() {
  std::string dir = TempDir("lifecycle");
  void* s = store_create(dir.c_str(), 1 << 20);
  char path[4096];
  std::string id = MakeId('a');

  assert(store_create_object(s, id.c_str(), 100, 10, path, sizeof path) == 0);
  assert(FileExists(path));
  assert(store_contains(s, id.c_str()) == 2);  // present-unsealed
  // Unsealed objects are not gettable.
  uint64_t ds = 0, ms = 0;
  assert(store_get(s, id.c_str(), path, sizeof path, &ds, &ms) == -2);
  // Double-create is rejected.
  assert(store_create_object(s, id.c_str(), 1, 0, path, sizeof path) == -1);

  assert(store_seal(s, id.c_str()) == 0);
  assert(store_contains(s, id.c_str()) == 1);
  assert(store_get(s, id.c_str(), path, sizeof path, &ds, &ms) == 0);
  assert(ds == 100 && ms == 10);
  assert(store_used(s) == 110);
  assert(store_num_objects(s) == 1);

  // delete while referenced -> pending until release (rc 1: the name
  // survives, so staging-inode recyclers must not rewrite the pages).
  assert(store_delete(s, id.c_str()) == 1);
  assert(store_contains(s, id.c_str()) == 1);  // still readable
  assert(store_release(s, id.c_str()) == 0);
  assert(store_contains(s, id.c_str()) == 0);
  assert(store_used(s) == 0);
  store_destroy(s);
  std::printf("  lifecycle OK\n");
}

void TestEvictionRespectsPinsAndRefs() {
  std::string dir = TempDir("evict");
  void* s = store_create(dir.c_str(), 300);  // fits two 100-byte objects
  char path[4096];
  uint64_t ds, ms;
  std::string a = MakeId('a'), b = MakeId('b'), c = MakeId('c'),
              d = MakeId('d');
  for (const auto& id : {a, b}) {
    assert(store_create_object(s, id.c_str(), 100, 0, path, sizeof path) ==
           0);
    assert(store_seal(s, id.c_str()) == 0);
  }
  // a is PINNED (primary): eviction must take b, never a.
  assert(store_pin(s, a.c_str(), 1) == 0);
  assert(store_create_object(s, c.c_str(), 150, 0, path, sizeof path) == 0);
  assert(store_contains(s, a.c_str()) == 1);
  assert(store_contains(s, b.c_str()) == 0);  // LRU victim
  assert(store_num_evictions(s) == 1);

  // A REFERENCED object is not evictable: get(c) pins it; creating d
  // (needs eviction of c) must fail with -2, not corrupt c.
  assert(store_seal(s, c.c_str()) == 0);
  assert(store_get(s, c.c_str(), path, sizeof path, &ds, &ms) == 0);
  assert(store_create_object(s, d.c_str(), 200, 0, path, sizeof path) == -2);
  assert(store_contains(s, c.c_str()) == 1);
  // Released -> evictable -> d fits.
  assert(store_release(s, c.c_str()) == 0);
  assert(store_create_object(s, d.c_str(), 200, 0, path, sizeof path) == 0);
  assert(store_contains(s, c.c_str()) == 0);
  // Larger than capacity is rejected outright.
  std::string e = MakeId('e');
  assert(store_create_object(s, e.c_str(), 1000, 0, path, sizeof path) ==
         -2);
  store_destroy(s);
  std::printf("  eviction/pin/ref OK\n");
}

void TestIngestAdoptsSealed() {
  std::string dir = TempDir("ingest");
  void* s = store_create(dir.c_str(), 1024);
  std::string src = dir + "/ingest-test-1";
  WriteFile(src, "hello-ingest");
  std::string id = MakeId('i');
  assert(store_ingest_object(s, id.c_str(), src.c_str(), 12, 0, 0) == 0);
  assert(!FileExists(src));  // renamed in, not copied
  assert(store_contains(s, id.c_str()) == 1);  // sealed on arrival
  char path[4096];
  uint64_t ds, ms;
  assert(store_get(s, id.c_str(), path, sizeof path, &ds, &ms) == 0);
  assert(ds == 12);
  char buf[16] = {0};
  int fd = ::open(path, O_RDONLY);
  assert(::read(fd, buf, 12) == 12);
  ::close(fd);
  assert(std::memcmp(buf, "hello-ingest", 12) == 0);
  // Duplicate ingest is rejected; over-capacity ingest leaves src alone.
  WriteFile(src, "x");
  assert(store_ingest_object(s, id.c_str(), src.c_str(), 1, 0, 0) == -1);
  std::string big = MakeId('j');
  assert(store_ingest_object(s, big.c_str(), src.c_str(), 4096, 0, 0) == -2);
  assert(FileExists(src));  // caller's cleanup problem, not clobbered
  store_destroy(s);
  std::printf("  ingest OK\n");
}

void TestIngestPinnedSurvivesPressure() {
  // A pinned ingest is admitted atomically as a primary copy: capacity
  // pressure right after admission must evict OTHER unpinned objects,
  // never the fresh ingest (the r4 advisor race: sealed+unpinned entry
  // published before the rename could be evicted mid-ingest).
  std::string dir = TempDir("ingest-pin");
  void* s = store_create(dir.c_str(), 300);
  std::string src = dir + "/ingest-p-1";
  WriteFile(src, std::string(200, 'p'));
  std::string id = MakeId('p');
  assert(store_ingest_object(s, id.c_str(), src.c_str(), 200, 0, 1) == 0);
  // Filling the remaining 100 bytes forces eviction; the pinned ingest
  // must not be a victim, so a 200-byte create cannot fit.
  char path[4096];
  std::string q = MakeId('q');
  assert(store_create_object(s, q.c_str(), 200, 0, path, sizeof path) == -2);
  assert(store_contains(s, id.c_str()) == 1);
  // Unpinned ingest IS evictable under pressure.
  std::string src2 = dir + "/ingest-p-2";
  WriteFile(src2, std::string(50, 'u'));
  std::string u = MakeId('u');
  assert(store_ingest_object(s, u.c_str(), src2.c_str(), 50, 0, 0) == 0);
  assert(store_create_object(s, q.c_str(), 100, 0, path, sizeof path) == 0);
  assert(store_contains(s, u.c_str()) == 0);  // evicted
  assert(store_contains(s, id.c_str()) == 1);  // pinned survives
  store_destroy(s);
  std::printf("  ingest-pinned OK\n");
}

void TestConcurrentIngestEvict() {
  // Hammer ingest (pinned) + delete from several threads against a small
  // capacity: every rc=0 ingest must leave a readable object (the race
  // fixed in r5: rename outside the mutex let EvictFor erase the entry
  // first, acknowledging a put for a vanished object).
  std::string dir = TempDir("ingest-race");
  void* s = store_create(dir.c_str(), 1 << 16);
  std::atomic<int> bad{0};
  auto worker = [&](int t) {
    char path[4096];
    uint64_t ds, ms;
    for (int i = 0; i < 100; i++) {
      std::string src = dir + "/ingest-t" + std::to_string(t) + "-" +
                        std::to_string(i);
      WriteFile(src, std::string(512, (char)('a' + t)));
      std::string id(20, (char)('a' + t));
      id[19] = (char)('0' + (i % 10));
      store_delete(s, id.c_str());
      if (store_ingest_object(s, id.c_str(), src.c_str(), 512, 0, 1) == 0) {
        if (store_get(s, id.c_str(), path, sizeof path, &ds, &ms) != 0 ||
            !FileExists(path)) {
          bad.fetch_add(1);
        } else {
          store_release(s, id.c_str());
        }
        store_pin(s, id.c_str(), 0);
      } else {
        ::unlink(src.c_str());
      }
    }
  };
  std::vector<std::thread> ts;
  for (int t = 0; t < 4; t++) ts.emplace_back(worker, t);
  for (auto& th : ts) th.join();
  assert(bad.load() == 0);
  store_destroy(s);
  std::printf("  ingest-concurrent OK\n");
}

void TestConcurrentCreateRelease() {
  // Hammer the index from multiple threads: the single mutex must keep
  // accounting exact (used() returns to 0; no crashes/races).
  std::string dir = TempDir("threads");
  void* s = store_create(dir.c_str(), 1 << 22);
  auto worker = [&](int t) {
    char path[4096];
    uint64_t ds, ms;
    for (int i = 0; i < 200; i++) {
      std::string id(20, (char)('A' + t));
      id[19] = (char)('0' + (i % 10));
      if (store_create_object(s, id.c_str(), 64, 0, path, sizeof path) == 0) {
        store_seal(s, id.c_str());
      }
      if (store_get(s, id.c_str(), path, sizeof path, &ds, &ms) == 0) {
        store_release(s, id.c_str());
      }
      store_delete(s, id.c_str());
    }
  };
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; t++) threads.emplace_back(worker, t);
  for (auto& th : threads) th.join();
  assert(store_used(s) == 0);
  assert(store_num_objects(s) == 0);
  store_destroy(s);
  std::printf("  concurrent create/release OK\n");
}

}  // namespace


void TestSidecarProtocol() {
  // Fast-path sidecar: ingest/get/release/delete over the unix socket,
  // with journal events draining to the (Python-side) agent.
  std::string dir = TempDir("sidecar");
  void* s = store_create(dir.c_str(), 1 << 16);
  std::string sock = dir + ".sock";
  int notify_fd = -1;
  void* srv = store_server_start(s, sock.c_str(), &notify_fd);
  assert(srv != nullptr && notify_fd >= 0);
  int fd = store_client_connect(sock.c_str());
  assert(fd >= 0);

  std::string src = dir + "/ingest-c-1";
  WriteFile(src, "sidecar-payload!");
  std::string id = MakeId('s');
  int32_t rc; uint64_t ds, ms; char path[4096];
  // INGEST
  assert(store_client_request(fd, 1, id.c_str(), 16, 0, "ingest-c-1",
                              &rc, &ds, &ms, path, sizeof path) == 0);
  assert(rc == 0);
  // Path traversal refused.
  assert(store_client_request(fd, 1, id.c_str(), 1, 0, "../evil",
                              &rc, &ds, &ms, path, sizeof path) == 0);
  assert(rc == -4);
  // GET pins and returns the mapped path.
  assert(store_client_request(fd, 2, id.c_str(), 0, 0, nullptr,
                              &rc, &ds, &ms, path, sizeof path) == 0);
  assert(rc == 0 && ds == 16 && FileExists(path));
  // RELEASE + DELETE
  assert(store_client_request(fd, 3, id.c_str(), 0, 0, nullptr,
                              &rc, &ds, &ms, path, sizeof path) == 0);
  assert(rc == 0);
  assert(store_client_request(fd, 4, id.c_str(), 0, 0, nullptr,
                              &rc, &ds, &ms, path, sizeof path) == 0);
  assert(rc == 0);
  // CONTAINS -> absent now.
  assert(store_client_request(fd, 5, id.c_str(), 0, 0, nullptr,
                              &rc, &ds, &ms, path, sizeof path) == 0);
  assert(rc == 0);
  // Journal carries the ingest (op 1, size 16) then the delete (op 4);
  // each record leads with the wire op it originated from.
  char pokebyte;
  assert(::read(notify_fd, &pokebyte, 1) >= 0 || true);
  char buf[30 * 8];
  int n = store_server_drain(srv, buf, sizeof buf);
  assert(n == 30 * 2);
  assert(buf[0] == 1 && buf[1] == 1 &&
         std::memcmp(buf + 2, id.data(), 20) == 0);
  uint64_t jsize;
  std::memcpy(&jsize, buf + 22, 8);
  assert(jsize == 16);
  assert(buf[30] == 4 && buf[31] == 4);
  store_client_close(fd);
  store_server_stop(srv);
  store_destroy(s);
  std::printf("  sidecar OK\n");
}

void TestShmCreateSealWire() {
  // graftshm over the sidecar socket: CREATE passes a slab fd the
  // client serializes into; SEAL publishes it; GET returns the SAME
  // slab path (no rename, no copy); erase recycles the slab so the
  // next same-size CREATE reports a warm reuse.
  std::string dir = TempDir("shm-wire");
  void* s = store_create(dir.c_str(), 1 << 16);
  std::string sock = dir + ".sock";
  int notify_fd = -1;
  void* srv = store_server_start(s, sock.c_str(), &notify_fd);
  assert(srv != nullptr);
  int fd = store_client_connect(sock.c_str());
  assert(fd >= 0);

  std::string id = MakeId('m');
  int32_t rc;
  uint64_t reused = 99, ds, ms;
  char spath[4096], path[4096];
  int slab_fd = -1;
  assert(store_client_create(fd, id.c_str(), 4096, 64, &rc, &reused,
                             spath, sizeof spath, &slab_fd) == 0);
  assert(rc == 0 && reused == 0 && slab_fd >= 0);
  assert(std::strstr(spath, "shmslab-") != nullptr);
  // Staged: visible to contains as unsealed, not gettable.
  assert(store_contains(s, id.c_str()) == 2);
  assert(store_get(s, id.c_str(), path, sizeof path, &ds, &ms) == -2);
  // Serialize "in place" through the mapping.
  void* m = ::mmap(nullptr, 4096 + 64, PROT_READ | PROT_WRITE, MAP_SHARED,
                   slab_fd, 0);
  assert(m != MAP_FAILED);
  std::memset(m, 'z', 4096 + 64);
  std::memcpy(m, "shm-inplace!", 12);
  ::munmap(m, 4096 + 64);
  ::close(slab_fd);
  // SEAL publishes; GET hands back the very same slab path.
  assert(store_client_seal(fd, id.c_str(), &rc, &ds, &ms) == 0);
  assert(rc == 0);
  assert(store_client_seal(fd, id.c_str(), &rc, &ds, &ms) == 0);
  assert(rc == -1);  // double-seal rejected
  assert(store_client_request(fd, 2, id.c_str(), 0, 0, nullptr, &rc, &ds,
                              &ms, path, sizeof path) == 0);
  assert(rc == 0 && ds == 4096 && ms == 64);
  assert(std::strcmp(path, spath) == 0);
  char buf[12];
  int rfd = ::open(path, O_RDONLY);
  assert(rfd >= 0);
  assert(::read(rfd, buf, 12) == 12);
  ::close(rfd);
  assert(std::memcmp(buf, "shm-inplace!", 12) == 0);
  // CREATE journals its own record (op 9, origin 9), then the seal is
  // journaled as an ingest (op 1) whose origin byte marks the shm plane.
  char jbuf[30 * 4];
  int n = store_server_drain(srv, jbuf, sizeof jbuf);
  assert(n == 30 * 2);
  assert(jbuf[0] == 9 && jbuf[1] == 9 &&
         std::memcmp(jbuf + 2, id.data(), 20) == 0);
  assert(jbuf[30] == 1 && jbuf[31] == 10 &&
         std::memcmp(jbuf + 32, id.data(), 20) == 0);
  uint64_t jsize;
  std::memcpy(&jsize, jbuf + 52, 8);
  assert(jsize == 4096 + 64);
  // Release + delete: the slab goes back to the arena, so the next
  // same-size CREATE is a warm reuse of the SAME file.
  assert(store_client_request(fd, 3, id.c_str(), 0, 0, nullptr, &rc, &ds,
                              &ms, path, sizeof path) == 0);
  assert(store_client_request(fd, 4, id.c_str(), 0, 0, nullptr, &rc, &ds,
                              &ms, path, sizeof path) == 0);
  assert(rc == 0);
  std::string id2 = MakeId('n');
  assert(store_client_create(fd, id2.c_str(), 4096, 64, &rc, &reused,
                             path, sizeof path, &slab_fd) == 0);
  assert(rc == 0 && reused == 1 && slab_fd >= 0);
  assert(std::strcmp(path, spath) == 0);
  ::close(slab_fd);

  // Over-capacity CREATE: clean -2, no fd follows, slab recycled.
  std::string big = MakeId('o');
  int big_fd = -1;
  assert(store_client_create(fd, big.c_str(), 1 << 20, 0, &rc, &reused,
                             path, sizeof path, &big_fd) == 0);
  assert(rc == -2 && big_fd == -1);

  // Client death between CREATE and SEAL: a second connection stages an
  // object and dies; the sidecar reclaims it (store entry gone, delete
  // journaled) so the slab cannot leak behind an invisible entry.
  int fd2 = store_client_connect(sock.c_str());
  assert(fd2 >= 0);
  std::string dead = MakeId('d');
  int dead_fd = -1;
  assert(store_client_create(fd2, dead.c_str(), 2048, 0, &rc, &reused,
                             path, sizeof path, &dead_fd) == 0);
  assert(rc == 0 && dead_fd >= 0);
  ::close(dead_fd);
  store_client_close(fd2);  // dies before SEAL
  for (int i = 0; i < 5000 && store_contains(s, dead.c_str()) != 0; i++) {
    ::usleep(1000);
  }
  assert(store_contains(s, dead.c_str()) == 0);

  store_client_close(fd);
  store_server_stop(srv);
  store_destroy(s);
  std::printf("  shm create/seal wire OK\n");
}

int main() {
  TestCreateSealGetLifecycle();
  TestEvictionRespectsPinsAndRefs();
  TestIngestAdoptsSealed();
  TestIngestPinnedSurvivesPressure();
  TestConcurrentIngestEvict();
  TestConcurrentCreateRelease();
  TestSidecarProtocol();
  TestShmCreateSealWire();
  std::printf("object_store_test: ALL OK\n");
  return 0;
}
