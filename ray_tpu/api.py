"""Public API: init/shutdown, @remote, get/put/wait, actors, placement groups.

Analogue of the reference's python surface (reference:
python/ray/_private/worker.py ray.init:1422/get:2847/put:2986/wait:3057,
python/ray/remote_function.py RemoteFunction._remote:314, python/ray/actor.py
ActorClass._remote:792, python/ray/util/placement_group.py).
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ray_tpu.core.common import Address
from ray_tpu.core.core_worker import CoreWorker
from ray_tpu.core.ids import ActorID, PlacementGroupID
from ray_tpu.core.node import LocalNode
from ray_tpu.core.ref import ActorHandle, ObjectRef, get_core_worker
from ray_tpu.utils import get_logger

logger = get_logger("api")

_global_node: Optional[LocalNode] = None
_core_worker: Optional[CoreWorker] = None


def is_initialized() -> bool:
    return _core_worker is not None


def init(address: Optional[str] = None, *,
         resources: Optional[Dict[str, float]] = None,
         agent_address: Optional[str] = None,
         graftprof: Optional[bool] = None) -> Dict[str, Any]:
    """Start a local cluster (head) or connect to an existing controller.

    address: "host:port" of a running controller; None starts controller +
    node agent locally (the reference's `ray.init()` head path).
    graftprof: override the continuous-profiling flag for this process
    and its spawned workers (None = config/env default; the
    RAY_TPU_GRAFTPROF=0 escape hatch reaches the same flag).
    """
    global _global_node, _core_worker
    if _core_worker is not None:
        return {"already_initialized": True}
    if graftprof is not None:
        from ray_tpu.utils.config import GlobalConfig
        GlobalConfig.initialize({"graftprof": bool(graftprof)})
    if address is None:
        # Driver scripts launched by job submission (and the reference's
        # RAY_ADDRESS convention) connect via env.
        import os
        address = os.environ.get("RAY_TPU_ADDRESS") or None
    if address is None:
        _global_node = LocalNode(resources=resources)
        controller_addr = _global_node.controller_addr
        agent_addr = _global_node.agent_addr
    else:
        host, port = address.rsplit(":", 1)
        controller_addr = (host, int(port))
        if agent_address:
            h, p = agent_address.rsplit(":", 1)
            agent_addr = (h, int(p))
        else:
            # Discover an agent on this host via the controller.
            from ray_tpu.core.rpc import SyncRpcClient
            c = SyncRpcClient(controller_addr)
            agent_addr = None
            for n in c.call("get_nodes"):
                if n["state"] == "ALIVE":
                    agent_addr = tuple(n["addr"])
                    break
            c.close()
            if agent_addr is None:
                raise RuntimeError("no alive nodes in cluster")
    _core_worker = CoreWorker(
        "driver", agent_addr, controller_addr,
        _global_node.session_dir if _global_node else "/tmp")
    return {"controller_address": controller_addr,
            "agent_address": agent_addr}


def shutdown() -> None:
    global _global_node, _core_worker
    if _core_worker is not None:
        _core_worker.shutdown()
        _core_worker = None
    from ray_tpu.core import ref as _ref
    _ref._core_worker = None
    if _global_node is not None:
        _global_node.stop()
        _global_node = None


def _cw() -> CoreWorker:
    if _core_worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _core_worker


# ---------------------------------------------------------------------------
# tasks & actors
# ---------------------------------------------------------------------------

class RemoteFunction:
    def __init__(self, func, **default_opts):
        self._func = func
        self._opts = default_opts
        functools.update_wrapper(self, func)

    def remote(self, *args, **kwargs):
        opts = self._opts
        num_returns = opts.get("num_returns", 1)
        refs = _cw().submit_task(
            self._func, args, kwargs,
            num_returns=num_returns,
            resources=_resources_from_opts(opts),
            max_retries=opts.get("max_retries", 0),
            placement_group=_pg_id(opts.get("placement_group")),
            pg_bundle_index=opts.get("placement_group_bundle_index", -1),
            scheduling_strategy=opts.get("scheduling_strategy"),
            label_selector=opts.get("label_selector"),
            name=opts.get("name", ""))
        if num_returns == "streaming":
            return refs  # an ObjectRefGenerator
        return refs[0] if num_returns == 1 else refs

    def options(self, **opts):
        merged = dict(self._opts)
        merged.update(opts)
        return RemoteFunction(self._func, **merged)

    def __call__(self, *a, **kw):
        raise TypeError("Remote functions must be called with .remote()")


class ActorClass:
    def __init__(self, cls, **default_opts):
        self._cls = cls
        self._opts = default_opts

    def remote(self, *args, **kwargs) -> ActorHandle:
        opts = self._opts
        return _cw().create_actor(
            self._cls, args, kwargs,
            name=opts.get("name", ""),
            max_restarts=opts.get("max_restarts", 0),
            max_task_retries=opts.get("max_task_retries", 0),
            max_concurrency=opts.get("max_concurrency", 0),
            # Actors hold 0 CPU at rest by default (reference behavior) so a
            # small node isn't starved of task leases by resident actors.
            resources=_resources_from_opts(opts, default_cpu=0.0),
            placement_group=_pg_id(opts.get("placement_group")),
            pg_bundle_index=opts.get("placement_group_bundle_index", -1),
            runtime_env=opts.get("runtime_env"),
            label_selector=opts.get("label_selector"))

    def options(self, **opts):
        merged = dict(self._opts)
        merged.update(opts)
        return ActorClass(self._cls, **merged)


def _resources_from_opts(opts: dict, default_cpu: float = 1.0
                         ) -> Dict[str, float]:
    res = dict(opts.get("resources") or {})
    res["CPU"] = float(opts.get("num_cpus", res.get("CPU", default_cpu)))
    if "num_tpus" in opts:
        res["TPU"] = float(opts["num_tpus"])
    if "memory" in opts:
        res["memory"] = float(opts["memory"])
    return res


def remote(*args, **opts):
    """@remote decorator for functions and classes (mirrors reference
    python/ray/_private/worker.py:3445)."""

    def wrap(obj):
        if inspect.isclass(obj):
            return ActorClass(obj, **opts)
        return RemoteFunction(obj, **opts)

    if len(args) == 1 and not opts and callable(args[0]):
        return wrap(args[0])
    return wrap


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    cw = _cw()
    if isinstance(refs, ObjectRef):
        return cw.get([refs], timeout)[0]
    return cw.get(list(refs), timeout)


def put(value: Any) -> ObjectRef:
    return _cw().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None) -> Tuple[list, list]:
    return _cw().wait(refs, num_returns, timeout)


def cancel(target, *, force: bool = False) -> None:
    """Cancel a task by ObjectRef or ObjectRefGenerator (mirrors reference
    ray.cancel, python/ray/_private/worker.py:3268). Queued tasks are
    dropped; running tasks get TaskCancelledError raised in their exec
    thread; force=True kills the executing worker."""
    _cw().cancel(target, force)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    cw = _cw()
    cw._run(cw.controller.call("kill_actor", actor.actor_id.binary(),
                               no_restart)).result()
    if no_restart:
        cw.release_actor_arg_refs(actor.actor_id.binary())


def get_actor(name: str) -> ActorHandle:
    cw = _cw()
    info = cw._run(cw.controller.call("get_actor_by_name", name)).result()
    if info is None:
        raise ValueError(f"no actor named {name!r}")
    import cloudpickle
    creation = cloudpickle.loads(info["spec_blob"])
    cls = cloudpickle.loads(creation["cls_blob"])
    method_names = [m for m in dir(cls)
                    if not m.startswith("_") and callable(getattr(cls, m))]
    return ActorHandle(ActorID(info["actor_id"]), info["name"] or "actor",
                       method_names)


# ---------------------------------------------------------------------------
# placement groups
# ---------------------------------------------------------------------------

class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[dict],
                 state: Optional[str] = None):
        self.id = pg_id
        self.bundles = bundles
        # graftsched one-op create replies carry the terminal state, so
        # ready() resolves locally with zero RPCs. Deserialized handles
        # (and legacy creates) fall back to the wait_pg_ready long-poll.
        self._state = state

    def ready(self, timeout: float = 60.0) -> bool:
        if self._state == "CREATED":
            return True
        cw = _cw()
        state = cw._run(cw.controller.call(
            "wait_pg_ready", self.id.binary(), timeout)).result()
        if state == "CREATED":
            self._state = state
        return state == "CREATED"

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles))


def _pg_id(pg) -> Optional[bytes]:
    if pg is None:
        return None
    if isinstance(pg, PlacementGroup):
        return pg.id.binary()
    return pg


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    bundle_label_selector: Optional[List[dict]] = None
                    ) -> PlacementGroup:
    """bundle_label_selector: one node-label selector per bundle
    (reference: label_selector.cc operators — "v", "!v", "in(a,b)",
    "!in(a,b)"); the special value "$same" gangs all such bundles onto
    nodes sharing one value of that label, all-or-nothing (TPU
    slice-atomic reservation)."""
    if bundle_label_selector is not None and \
            len(bundle_label_selector) != len(bundles):
        raise ValueError("bundle_label_selector must have one entry "
                         "per bundle")
    cw = _cw()
    pg_id = PlacementGroupID.random()
    reply = cw._run(cw.controller.call(
        "create_placement_group", pg_id.binary(), bundles,
        strategy, bundle_label_selector)).result()
    state = reply.get("state") if isinstance(reply, dict) else None
    return PlacementGroup(pg_id, bundles, state)


def remove_placement_group(pg: PlacementGroup) -> None:
    cw = _cw()
    cw._run(cw.controller.call(
        "remove_placement_group", pg.id.binary())).result()
    pg._state = None  # ready() consults the controller again


# ---------------------------------------------------------------------------
# cluster state
# ---------------------------------------------------------------------------

def nodes() -> List[dict]:
    cw = _cw()
    return cw._run(cw.controller.call("get_nodes")).result()


def cluster_resources() -> Dict[str, float]:
    cw = _cw()
    return cw._run(cw.controller.call("cluster_resources")).result()["total"]


def available_resources() -> Dict[str, float]:
    cw = _cw()
    return cw._run(cw.controller.call(
        "cluster_resources")).result()["available"]
