"""graftscale harness: ramp simulated nodes against a real controller.

The controller runs as a REAL subprocess (`python -m
ray_tpu.core.controller --port 0`) with its production event loop,
stores and planes; the harness multiplexes ``SimNode`` agents onto one
``SimHost`` in this process and ramps the population level by level.
At each level it holds, then reads the controller's OWN graftmeta
snapshot — per-plane ingest rates, fold-latency p50/p99, event-loop
lag, RSS — and emits one JSONL ``level`` row. After the ramp it emits
graftload-style machine-checked ``verdict`` rows:

  * pulse_fold_p99_bounded   — worst per-level pulse fold p99 < budget
  * loop_lag_bounded         — controller loop-lag p99 < budget
  * rss_per_node_bounded     — controller RSS growth per node < budget
  * rss_growth_sublinear     — marginal RSS per node-SECOND flat across
    levels (isolates cardinality cost from per-node ring fill, which
    grows with time alive, not membership)
  * no_unintended_deaths     — every registered sim node still ALIVE
  * (with kill_nodes > 0) kill_detected / meta_ingest_drop /
    audit_clean_after_kill   — the SIGKILL story, machine-checked

``passed(rows)`` (graftload's gate) decides the exit code; the ``meta``
row records ``max_nodes_sustained`` — the largest level whose fold/lag
bounds held, the headline number of BENCH_SCALE.json.
"""

from __future__ import annotations

import asyncio
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu.core.rpc import RpcClient
from ray_tpu.load.verdict import passed
from ray_tpu.scale.simnode import SimHost, SimNode
from ray_tpu.utils import get_logger
from ray_tpu.utils.aio import spawn

logger = get_logger("graftscale")


@dataclass
class ScaleSpec:
    """One scale run. ``smoke()`` is the CI shape (one small level,
    well under a minute); the default is the bench ramp."""

    levels: Tuple[int, ...] = (64, 128, 192, 256)
    hold_s: float = 8.0
    tick_s: float = 1.0
    seed: int = 20260807
    fold_p99_budget_ms: float = 50.0
    loop_lag_p99_budget_ms: float = 250.0
    rss_per_node_budget_bytes: int = 1_500_000
    kill_nodes: int = 0
    v1_nodes: int = 0  # first N nodes ship v1 pulse frames (skew)
    env: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def smoke(cls) -> "ScaleSpec":
        return cls(levels=(64,), hold_s=10.0)


class ScaleHarness:
    """Async driver — tests compose the phases (start / add_nodes /
    sample / kill_some / stop) directly; ``run_scale`` is the
    all-in-one ramp."""

    def __init__(self, spec: ScaleSpec):
        self.spec = spec
        self.proc: Optional[asyncio.subprocess.Process] = None
        self.ctl: Optional[RpcClient] = None
        self.ctl_addr: Optional[Tuple[str, int]] = None
        self.simhost = SimHost()
        self.killed: List[SimNode] = []
        self._drain_task = None

    @property
    def nodes(self) -> List[SimNode]:
        return self.simhost.nodes

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        env = dict(os.environ)
        for k, v in self.spec.env.items():
            env[f"RAY_TPU_{k.upper()}"] = str(v)
        self.proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "ray_tpu.core.controller",
            "--port", "0", env=env,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.DEVNULL)
        assert self.proc.stdout is not None
        line = await asyncio.wait_for(self.proc.stdout.readline(), 30.0)
        if not line.startswith(b"CONTROLLER_PORT="):
            raise RuntimeError(f"controller did not start: {line!r}")
        port = int(line.split(b"=", 1)[1])
        self.ctl_addr = ("127.0.0.1", port)
        self.ctl = RpcClient(self.ctl_addr, timeout=30.0)
        self._drain_task = spawn(self._drain_stdout())
        await self.simhost.start()
        # Wait for the meta plane's first tick so RSS baselines exist.
        for _ in range(100):
            snap = await self.ctl.call("meta_snapshot", 2)
            if not snap.get("enabled") or snap.get("ticks"):
                break
            await asyncio.sleep(0.2)

    async def _drain_stdout(self) -> None:
        assert self.proc is not None and self.proc.stdout is not None
        try:
            while await self.proc.stdout.readline():
                pass
        except Exception:
            pass

    async def stop(self) -> None:
        try:
            await self.simhost.stop()
        finally:
            if self.ctl is not None:
                await self.ctl.close()
            if self.proc is not None and self.proc.returncode is None:
                self.proc.kill()
                try:
                    await asyncio.wait_for(self.proc.wait(), 10.0)
                except asyncio.TimeoutError:
                    pass

    # -- phases ------------------------------------------------------------

    async def add_nodes(self, upto: int) -> None:
        """Grow the population to ``upto`` sim nodes, staggered so the
        registration burst itself doesn't become the measurement."""
        assert self.ctl_addr is not None and self.simhost.addr
        spec = self.spec
        while len(self.nodes) < upto:
            i = len(self.nodes)
            node = SimNode(
                i, spec.seed, self.ctl_addr, self.simhost.addr,
                tick_s=spec.tick_s,
                wire_version=1 if i < spec.v1_nodes else 2)
            await node.start()
            self.simhost.nodes.append(node)
            if i % 16 == 15:
                await asyncio.sleep(0.05)

    async def sample(self, window_ticks: int) -> dict:
        assert self.ctl is not None
        return await self.ctl.call("meta_snapshot",
                                   max(2, int(window_ticks)))

    async def node_states(self) -> Dict[str, str]:
        assert self.ctl is not None
        return {n["node_id"].hex()[:12]: str(n["state"])
                for n in await self.ctl.call("get_nodes")}

    def node_seconds(self) -> float:
        """Integrated alive-time across the population. Per-node rings
        (pulse history, prof windows, trail/log rows) fill with TIME
        alive, not with membership — so until the caps bite, controller
        RSS is proportional to node-seconds, and node-seconds (not node
        count) is the denominator that isolates cardinality cost from
        ring fill."""
        now = time.monotonic()
        total = 0.0
        for n in self.nodes:
            if n.t_start is not None:
                total += (n.t_end if n.t_end is not None else now) \
                    - n.t_start
        return total

    async def kill_some(self, k: int,
                        timeout_s: float = 30.0) -> List[dict]:
        """Abruptly silence ``k`` live nodes and wait for the
        controller's cadence FSM to declare them DEAD. Returns kill/
        verdict rows; the trail audit must stay clean afterwards."""
        assert self.ctl is not None
        before = await self.sample(max(2, int(self.spec.hold_s)))
        victims = [n for n in self.nodes if not n.killed][-k:]
        t0 = time.monotonic()
        for n in victims:
            n.kill()
        self.killed.extend(victims)
        want = {n.hex12 for n in victims}
        detect_s = None
        while time.monotonic() - t0 < timeout_s:
            states = await self.node_states()
            if all(states.get(h) == "DEAD" for h in want):
                detect_s = time.monotonic() - t0
                break
            await asyncio.sleep(0.5)
        # Post-kill window: only ticks after the deaths, so the meter
        # shows the ingest drop rather than averaging over the kill.
        await asyncio.sleep(3.0)
        after = await self.sample(3)
        audit = await self.ctl.call("trail_audit", None)
        rate = lambda s: (s.get("planes", {}).get("pulse", {})  # noqa: E731
                          .get("records_per_s", 0.0))
        live = len(self.nodes) - len(self.killed)
        expect = rate(before) * (1 - 0.5 * k / max(1, live + k))
        return [
            {"row": "verdict", "check": "kill_detected",
             "ok": detect_s is not None, "killed": k,
             "detect_s": (round(detect_s, 2)
                          if detect_s is not None else None),
             "timeout_s": timeout_s},
            {"row": "verdict", "check": "meta_ingest_drop",
             "ok": rate(after) <= expect or detect_s is None,
             "pulse_rps_before": round(rate(before), 2),
             "pulse_rps_after": round(rate(after), 2),
             "expected_max": round(expect, 2)},
            {"row": "verdict", "check": "audit_clean_after_kill",
             "ok": bool(audit.get("ok")),
             "lost_tasks": len(audit.get("lost_tasks", [])),
             "leaked_objects": len(audit.get("leaked_objects", []))},
        ]


def _level_row(level: int, snap: dict, states: Dict[str, str],
               rss_base: int, node_seconds: float) -> dict:
    planes = snap.get("planes", {})
    pulse = planes.get("pulse", {})
    lag = snap.get("loop_lag", {})
    alive = sum(1 for s in states.values() if s == "ALIVE")
    rss = int(snap.get("rss_bytes") or 0)
    return {
        "row": "level", "nodes": level, "alive": alive,
        "dead": len(states) - alive,
        "node_seconds": round(node_seconds, 1),
        "pulse_fold_p50_us": round(pulse.get("fold_p50_ns", 0) / 1e3, 1),
        "pulse_fold_p99_us": round(pulse.get("fold_p99_ns", 0) / 1e3, 1),
        "pulse_records_per_s": round(pulse.get("records_per_s", 0.0), 1),
        "loop_lag_p50_ms": round(lag.get("p50_ns", 0) / 1e6, 2),
        "loop_lag_p99_ms": round(lag.get("p99_ns", 0) / 1e6, 2),
        "rss_bytes": rss,
        "rss_growth_per_node": (rss - rss_base) // max(1, level),
        "planes": {
            p: {"records_per_s": round(d.get("records_per_s", 0.0), 1),
                "bytes_per_s": round(d.get("bytes_per_s", 0.0), 1),
                "fold_p99_us": round(d.get("fold_p99_ns", 0) / 1e3, 1),
                "drops": d.get("drops", 0)}
            for p, d in planes.items()},
    }


def _verdicts(spec: ScaleSpec, rows: List[dict],
              rss_base: int) -> List[dict]:
    levels = [r for r in rows if r["row"] == "level"]
    worst_fold = max((r["pulse_fold_p99_us"] for r in levels),
                     default=0.0)
    worst_lag = max((r["loop_lag_p99_ms"] for r in levels), default=0.0)
    out = [
        {"row": "verdict", "check": "pulse_fold_p99_bounded",
         "ok": worst_fold < spec.fold_p99_budget_ms * 1000,
         "worst_p99_us": worst_fold,
         "budget_ms": spec.fold_p99_budget_ms},
        {"row": "verdict", "check": "loop_lag_bounded",
         "ok": worst_lag < spec.loop_lag_p99_budget_ms,
         "worst_p99_ms": worst_lag,
         "budget_ms": spec.loop_lag_p99_budget_ms},
    ]
    if levels:
        last = levels[-1]
        per_node = (last["rss_bytes"] - rss_base) / max(1, last["nodes"])
        out.append({"row": "verdict", "check": "rss_per_node_bounded",
                    "ok": per_node < spec.rss_per_node_budget_bytes,
                    "rss_base_bytes": rss_base,
                    "rss_final_bytes": last["rss_bytes"],
                    "per_node_bytes": int(per_node),
                    "budget_bytes": spec.rss_per_node_budget_bytes})
        out.append({"row": "verdict", "check": "no_unintended_deaths",
                    "ok": last["dead"] == 0, "dead": last["dead"],
                    "nodes": last["nodes"]})
    if len(levels) >= 3:
        # Sub-linearity in CARDINALITY, controlling for time: per-node
        # rings fill with seconds alive, so raw per-level RSS deltas
        # grow with wall time even when every store is bounded (levels
        # are sampled sequentially — by level 4 the level-1 nodes have
        # 4x the ring fill). Normalize each level's RSS delta by its
        # node-seconds delta: bytes per node-second is flat for bounded
        # per-node state, and a superlinear cardinality cost (eviction
        # scans, cross-node index churn) still shows as a rising slope.
        slopes = []
        prev_rss, prev_ns = rss_base, 0.0
        for r in levels:
            dns = r["node_seconds"] - prev_ns
            if dns > 0:
                slopes.append((r["rss_bytes"] - prev_rss) / dns)
            prev_rss, prev_ns = r["rss_bytes"], r["node_seconds"]
        ok = len(slopes) < 2 or slopes[-1] <= max(slopes[0] * 2.0,
                                                  16 * 1024)
        out.append({"row": "verdict", "check": "rss_growth_sublinear",
                    "ok": ok,
                    "marginal_bytes_per_node_second":
                        [int(s) for s in slopes]})
    return out


async def _run(spec: ScaleSpec) -> List[dict]:
    h = ScaleHarness(spec)
    rows: List[dict] = []
    await h.start()
    try:
        base = await h.sample(2)
        rss_base = int(base.get("rss_bytes") or 0)
        for level in spec.levels:
            await h.add_nodes(level)
            await asyncio.sleep(spec.hold_s)
            snap = await h.sample(int(spec.hold_s / max(
                0.05, _meta_tick_s(spec))))
            states = await h.node_states()
            rows.append(_level_row(level, snap, states, rss_base,
                                   h.node_seconds()))
        rows.extend(_verdicts(spec, rows, rss_base))
        if spec.kill_nodes > 0:
            rows.extend(await h.kill_some(spec.kill_nodes))
        # Per-plane ingest-ceiling rows at the max level: what each
        # plane was actually sustaining, from the plane's own meter.
        final = [r for r in rows if r["row"] == "level"][-1]
        for p, d in final["planes"].items():
            rows.append({"row": "plane", "plane": p, "nodes":
                         final["nodes"], **d})
        level_ok = [r["nodes"] for r in rows if r["row"] == "level"
                    and r["pulse_fold_p99_us"]
                    < spec.fold_p99_budget_ms * 1000
                    and r["loop_lag_p99_ms"]
                    < spec.loop_lag_p99_budget_ms]
        rows.append({"row": "meta", "seed": spec.seed,
                     "levels": list(spec.levels),
                     "tick_s": spec.tick_s, "hold_s": spec.hold_s,
                     "v1_nodes": spec.v1_nodes,
                     "kill_nodes": spec.kill_nodes,
                     "max_nodes_sustained": max(level_ok, default=0),
                     "host_cores": os.cpu_count(),
                     "passed": passed(rows)})
    finally:
        await h.stop()
    return rows


def _meta_tick_s(spec: ScaleSpec) -> float:
    try:
        return max(0.05, float(spec.env.get("meta_tick_ms", 1000))
                   / 1000.0)
    except (TypeError, ValueError):
        return 1.0


def run_scale(spec: Optional[ScaleSpec] = None) -> List[dict]:
    """Run the full ramp; returns the JSONL row list (see module
    docstring). ``passed(rows)`` gates the caller's exit code."""
    return asyncio.run(_run(spec or ScaleSpec()))
