"""Simulated node agents: the multiplexing layer of graftscale.

One ``SimNode`` is the control-plane ghost of a node agent: it holds a
real ``RpcClient`` connection to the controller, registers with a real
node id, heartbeats, and ships one wire-true graftpulse frame plus
trail/log/prof batches per tick — all synthesized from a seeded
deterministic workload model instead of real workers. Hundreds of them
share one asyncio loop and one ``SimHost`` RpcServer that answers the
few agent-side RPCs the controller initiates (``trail_residents`` for
the conservation audit, ``reconcile_bundles``), so from the
controller's side the cluster is indistinguishable from N live agents
— every ingest path, fold, cadence FSM and store sees production
traffic shapes at populations no real deployment of this repo has.

Determinism: every stochastic choice draws from
``random.Random(seed * 1000003 + index)``, so a (seed, index) pair
replays the same pulse kinds, task lifecycles and log cadence run
after run — a failing scale level is re-runnable.

Kill semantics: ``kill()`` silences the node mid-flight (open tasks
stay open, live objects stay "resident" only in the ledger) — the
controller must detect the pulse silence, fold node-death provenance
into the trail, and keep the audit clean. ``stop()`` is the graceful
path: open work is finished in a final batch first.
"""

from __future__ import annotations

import asyncio
import hashlib
import random
import struct
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu.core._native import graftpulse, graftscope
from ray_tpu.core.ids import NodeID
from ray_tpu.core.rpc import RpcClient, RpcServer
from ray_tpu.utils import get_logger
from ray_tpu.utils.aio import spawn

logger = get_logger("graftscale")

# The op mix one sim node reports per pulse: a plausible small slice of
# the real kind table (client-side send/flush + sidecar service ops).
_PULSE_KINDS = ("rpc_send", "rpc_recv", "rpc_flush", "sc_begin", "sc_end")

_TASK_NAMES = ("sim_ingest", "sim_transform", "sim_reduce")


class SimNode:
    """One multiplexed node agent (see module docstring)."""

    def __init__(self, index: int, seed: int,
                 controller_addr: Tuple[str, int],
                 sim_addr: Tuple[str, int],
                 tick_s: float = 1.0,
                 wire_version: int = graftpulse.PULSE_VERSION):
        self.index = index
        self.rng = random.Random(seed * 1000003 + index)
        # NOT NodeID.random(): that id's first 8 bytes are a per-
        # PROCESS prefix, so every sim node in one host process would
        # share the hex12 prefix the controller keys its per-node
        # plane state on — N nodes would collapse into one series.
        # A (seed, index) digest is unique AND replayable.
        self.node_id = NodeID(hashlib.blake2b(
            b"graftscale:%d:%d" % (seed, index),
            digest_size=NodeID.SIZE).digest())
        self.hex12 = self.node_id.binary().hex()[:12]
        self.controller_addr = controller_addr
        self.sim_addr = sim_addr
        self.tick_s = tick_s
        self.wire_version = wire_version
        self.client = RpcClient(controller_addr, max_retries=2,
                                timeout=15.0)
        # workload-model state
        self._seq = 0
        self._tick = 0
        self._task_seq = 0
        self._obj_seq = 0
        self._log_seq = 0
        # task_id -> finish-at tick (tasks held open across ticks)
        self._open_tasks: Dict[str, int] = {}
        self._live_oids: List[str] = []
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        self.killed = False
        self.registered = False
        # Lifetime bounds (monotonic): the harness integrates these
        # into node-seconds, the denominator of the RSS-growth verdict.
        self.t_start: Optional[float] = None
        self.t_end: Optional[float] = None
        # per-plane sent counters, for the harness's own bookkeeping
        self.sent = {"pulse": 0, "trail": 0, "log": 0, "prof": 0}

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        await self.client.call(
            "register_node", self.node_id.binary(), self.sim_addr,
            {"CPU": 4.0, "memory": float(2 << 30)},
            {"sim": "1", "sim_index": str(self.index)})
        self.registered = True
        self.t_start = time.monotonic()
        self._task = spawn(self._loop())

    async def stop(self) -> None:
        """Graceful: finish open work in one last batch, then go quiet."""
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        if not self.killed:
            try:
                await self.client.call("report_trail_batch",
                                       self.node_id.binary(),
                                       self._drain_events(), [])
            except Exception:
                pass
        await self.client.close()

    def kill(self) -> None:
        """SIGKILL analogue: stop mid-flight, leaving open tasks and
        "resident" objects for the controller's node-death fold."""
        self.killed = True
        self._stopped = True
        self.t_end = time.monotonic()
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def _drain_events(self) -> list:
        ts = time.time()
        out = [(tid, 0, "FINISHED", ts, {"node": self.hex12})
               for tid in self._open_tasks]
        self._open_tasks.clear()
        return out

    # -- tick loop ---------------------------------------------------------

    async def _loop(self) -> None:
        loop = asyncio.get_running_loop()
        # Staggered phase: N nodes must not fire in lockstep — the real
        # fleet never does, and the herd would measure the harness.
        start = loop.time() + self.rng.random() * self.tick_s
        k = 0
        hb_every = max(1, int(round(2.0 / self.tick_s)))
        while not self._stopped:
            k += 1
            delay = start + k * self.tick_s - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            try:
                await self._tick_once(hb_every)
            except asyncio.CancelledError:
                raise
            except Exception:
                # Transport hiccup: drop this tick, keep the cadence.
                continue

    async def _tick_once(self, hb_every: int) -> None:
        self._tick += 1
        nid = self.node_id.binary()
        if self._tick % hb_every == 0:
            ok = await self.client.call(
                "heartbeat", nid,
                {"CPU": round(self.rng.uniform(0.0, 4.0), 2)})
            if ok == "unknown":
                await self.client.call(
                    "register_node", nid, self.sim_addr,
                    {"CPU": 4.0, "memory": float(2 << 30)},
                    {"sim": "1", "sim_index": str(self.index)})
            elif ok is False:
                self._stopped = True
                return
        await self.client.call("report_pulse", nid, self._make_pulse())
        self.sent["pulse"] += 1
        tasks, objects = self._make_trail()
        if tasks or objects:
            await self.client.call("report_trail_batch", nid, tasks,
                                   objects)
            self.sent["trail"] += len(tasks) + len(objects)
        logs = self._make_logs()
        if logs:
            await self.client.call("report_log_batch", nid, logs)
            self.sent["log"] += len(logs)
        if self._tick % 2 == 0:
            await self.client.call("report_prof_batch", nid,
                                   [self._make_prof()])
            self.sent["prof"] += 1

    # -- workload models ---------------------------------------------------

    def _make_pulse(self) -> bytes:
        rng = self.rng
        self._seq += 1
        kinds = {}
        for name in _PULSE_KINDS:
            calls = rng.randint(40, 400)
            hist = [0] * graftpulse.PULSE_HIST_BUCKETS
            left = calls
            # Latency mass in buckets 2..6 (~4µs..128µs), the shape the
            # real native planes report on loopback.
            for b in (2, 3, 4, 5, 6):
                n = rng.randint(0, left)
                hist[b] += n
                left -= n
            hist[3] += left
            ns = sum(int(n * 1.5 * (1 << (graftpulse.PULSE_HIST_SHIFT
                                          + b)))
                     for b, n in enumerate(hist))
            kinds[name] = (calls, calls * rng.randint(128, 2048), ns,
                           tuple(hist))
        p = graftpulse.Pulse(
            seq=self._seq,
            t_mono_ns=time.monotonic_ns(),
            t_wall_ns=time.time_ns(),
            store_used=rng.randint(1, 64) << 20,
            store_capacity=1 << 30,
            store_objects=rng.randint(4, 256),
            shm_free_chunks=rng.randint(16, 1024),
            shm_arena_bytes=256 << 20,
            num_workers=4,
            queue_depth=rng.randint(0, 8),
            rss_bytes=(300 << 20) + (self.index << 16),
            scope_dropped=0,
            events_dropped=0,
            prof_oncpu_permille=rng.randint(50, 400),
            prof_gil_permille=rng.randint(10, 120),
            kinds=kinds)
        if self.wire_version == 1:
            return self._encode_v1(p)
        return graftpulse.encode(p)

    @staticmethod
    def _encode_v1(p) -> bytes:
        """A v1 agent's frame: the v2 header minus the trailing prof
        gauges. Exercises the controller's version-skew degrade path."""
        head = graftpulse._V1_RECORD.pack(
            graftpulse.PULSE_MAGIC, 1, graftscope.KIND_COUNT,
            p.seq, p.t_mono_ns, p.t_wall_ns, p.store_used,
            p.store_capacity, p.store_objects, p.shm_free_chunks,
            p.shm_arena_bytes, p.num_workers, p.queue_depth,
            p.rss_bytes, p.scope_dropped, p.events_dropped)
        words: List[int] = []
        for kind in range(graftscope.KIND_COUNT):
            row = p.kinds.get(graftscope.KIND_NAMES.get(kind, ""))
            if row is None:
                words.extend([0] * (3 + graftpulse.PULSE_HIST_BUCKETS))
            else:
                calls, nbytes, ns, hist = row
                words.extend((calls, nbytes, ns))
                words.extend(hist[:graftpulse.PULSE_HIST_BUCKETS])
        return head + struct.pack("<%dQ" % len(words), *words)

    def _make_trail(self) -> Tuple[list, list]:
        rng = self.rng
        ts = time.time()
        tasks: list = []
        # Finish tasks held open from earlier ticks that are now due.
        for tid in [t for t, due in self._open_tasks.items()
                    if due <= self._tick]:
            del self._open_tasks[tid]
            tasks.append((tid, 0, "FINISHED", ts, {"node": self.hex12}))
        for _ in range(rng.randint(1, 4)):
            self._task_seq += 1
            tid = "sim%05x%08x" % (self.index, self._task_seq)
            info = {"name": rng.choice(_TASK_NAMES), "node": self.hex12,
                    "worker": 4000 + self.index}
            tasks.append((tid, 0, "SUBMITTED", ts, info))
            tasks.append((tid, 0, "RUNNING", ts, {"node": self.hex12}))
            if rng.random() < 0.85:
                tasks.append((tid, 0, "FINISHED", ts,
                              {"node": self.hex12}))
            else:
                self._open_tasks[tid] = self._tick + rng.randint(1, 3)
        objects: list = []
        for _ in range(rng.randint(0, 2)):
            self._obj_seq += 1
            oid = "simo%05x%08x" % (self.index, self._obj_seq)
            objects.append((oid, "sealed", ts,
                            {"size": rng.randint(1 << 10, 1 << 20),
                             "plane": "shm", "node": self.hex12}))
            if rng.random() < 0.8:
                objects.append((oid, "freed", ts,
                                {"reason": "out_of_scope"}))
            else:
                self._live_oids.append(oid)
        while len(self._live_oids) > 4:
            objects.append((self._live_oids.pop(0), "freed", ts,
                            {"reason": "lru"}))
        return tasks, objects

    def _make_logs(self) -> list:
        rng = self.rng
        out = []
        for _ in range(rng.randint(1, 3)):
            self._log_seq += 1
            r = rng.random()
            level = 40 if r < 0.02 else 30 if r < 0.08 else 20
            msg = "sim node %d tick %d seq %d" % (
                self.index, self._tick, self._log_seq)
            out.append({"pid": 4000 + self.index, "level": level,
                        "source": 0, "seq": self._log_seq,
                        "t_ns": time.time_ns(), "task": "", "actor": "",
                        "msg": msg, "line_len": len(msg)})
        return out

    def _make_prof(self) -> dict:
        rng = self.rng
        frames = ["<module>", "sim_outer", "sim_inner",
                  rng.choice(_TASK_NAMES)]
        n = rng.randint(10, 60)
        return {"pid": 4000 + self.index, "hz": 29, "frames": frames,
                "stacks": [("", "", frames[3], [0, 1, 2, 3], n)],
                "tasks": [("", "", frames[3], n,
                           n * 1_000_000_000 // 29 // 2,
                           n * 1_000_000_000 // 29 // 8)],
                "threads": [("MainThread",
                             n * 1_000_000_000 // 29 // 2)]}


class SimHost:
    """One RpcServer fronting every sim node on this host.

    All sim nodes register the same (host, port): the controller dials
    one socket per NodeEntry but every agent-side RPC lands here. The
    audit's ``trail_residents`` answers with the UNION of all live sim
    nodes' resident oids — the controller can't tell sim nodes apart by
    address, and a superset keeps the leak check sound (an oid the
    ledger thinks is live IS claimed by its home node's host)."""

    def __init__(self, host: str = "127.0.0.1"):
        self.host = host
        self.server = RpcServer("simhost")
        self.nodes: List[SimNode] = []
        self.addr: Optional[Tuple[str, int]] = None

    async def start(self) -> Tuple[str, int]:
        async def trail_residents() -> list:
            out = []
            for n in self.nodes:
                if not n.killed:
                    out.extend(n._live_oids)
            return out

        async def _noop(*a, **kw) -> None:
            return None

        self.server.register("trail_residents", trail_residents)
        for m in ("reconcile_bundles", "kill_actor_worker",
                  "commit_bundle", "return_bundle", "return_bundles",
                  "drain_node"):
            self.server.register(m, _noop)
        port = await self.server.start_tcp(self.host, 0)
        self.addr = (self.host, port)
        return self.addr

    async def stop(self) -> None:
        for n in list(self.nodes):
            try:
                await n.stop()
            except Exception:
                pass
        await self.server.stop()
