"""graftscale: the thousand-node scale harness.

Multiplexes hundreds of lightweight simulated node agents onto one
host process — real graftrpc connections, real graftpulse wire frames,
real trail/log/prof batches from seeded deterministic workload models,
no workers — and ramps the population against a real controller
subprocess until a machine-checked limit trips. The controller's own
graftmeta plane is the instrument: per-plane ingest rates, fold-latency
percentiles, event-loop lag and RSS all come from the system under
test metering itself (``meta_snapshot``), not from an external probe.

``harness.run_scale(ScaleSpec(...))`` emits graftload-style JSONL rows
(level rows + verdict rows + a meta row); ``bench_scale.py`` at the
repo root wraps it as `make bench-scale` -> BENCH_SCALE.json.
"""

from ray_tpu.scale.harness import ScaleSpec, run_scale  # noqa: F401
from ray_tpu.scale.simnode import SimHost, SimNode  # noqa: F401
