"""ray_tpu — a TPU-native distributed AI framework.

A ground-up rebuild of the reference framework's capabilities (distributed
task/actor/object runtime + Data/Train/Tune/Serve/RLlib) designed for
JAX/XLA/Pallas/pjit over TPU ICI/DCN. See SURVEY.md for the blueprint.
"""

from ray_tpu.version import __version__

_API_NAMES = (
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "placement_group",
    "remove_placement_group", "PlacementGroup", "nodes", "cluster_resources",
    "available_resources", "ObjectRef", "ActorHandle", "ObjectRefGenerator",
)


def __getattr__(name):
    # Lazy: importing ray_tpu stays light; the runtime loads on first API use.
    if name in _API_NAMES:
        if name in ("ObjectRef", "ActorHandle", "ObjectRefGenerator"):
            from ray_tpu.core import ref as _ref
            return getattr(_ref, name)
        from ray_tpu import api as _api
        return getattr(_api, name)
    raise AttributeError(name)


__all__ = ["__version__", *_API_NAMES]
