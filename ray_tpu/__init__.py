"""ray_tpu — a TPU-native distributed AI framework.

A ground-up rebuild of the reference framework's capabilities (distributed
task/actor/object runtime + Data/Train/Tune/Serve/RLlib) designed for
JAX/XLA/Pallas/pjit over TPU ICI/DCN. See SURVEY.md for the blueprint.
"""

from ray_tpu.version import __version__

__all__ = ["__version__"]
