"""ActorPool — load-balance tasks over a fixed set of actors.

Analogue of the reference's ActorPool (reference:
python/ray/util/actor_pool.py — submit(fn, value) round-robins onto free
actors; get_next/get_next_unordered collect; map/map_unordered stream).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._inflight_by_ref = {}
        self._ref_by_seq = {}
        self._submit_seq = 0
        self._consume_seq = 0
        self._backlog: List[tuple] = []

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef; queues when all actors busy."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._inflight_by_ref[ref] = (self._submit_seq, actor)
            self._ref_by_seq[self._submit_seq] = ref
            self._submit_seq += 1
        else:
            self._backlog.append((fn, value))

    def _return_actor(self, actor) -> None:
        self._idle.append(actor)
        if self._backlog:
            self.submit(*self._backlog.pop(0))

    def has_next(self) -> bool:
        return bool(self._ref_by_seq)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in SUBMISSION order. On timeout the task stays
        pending and its actor stays busy (popping before the result is
        ready would lose the result and double-book the actor)."""
        if not self.has_next():
            raise StopIteration("no pending results")
        ref = self._ref_by_seq[self._consume_seq]
        if timeout is not None:
            ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=timeout)
            if not ready:
                raise TimeoutError("next result not ready within timeout")
        self._ref_by_seq.pop(self._consume_seq)
        self._consume_seq += 1
        _, actor = self._inflight_by_ref.pop(ref)
        try:
            return ray_tpu.get(ref)
        finally:
            self._return_actor(actor)

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next COMPLETED result, any order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._inflight_by_ref),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        ref = ready[0]
        index, actor = self._inflight_by_ref.pop(ref)
        self._ref_by_seq.pop(index)
        try:
            return ray_tpu.get(ref)
        finally:
            self._return_actor(actor)

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def push(self, actor: Any) -> None:
        """Add an idle actor to the pool."""
        self._return_actor(actor)
