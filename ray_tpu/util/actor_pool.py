"""ActorPool — load-balance tasks over a fixed set of actors.

Analogue of the reference's ActorPool (reference:
python/ray/util/actor_pool.py — submit(fn, value) round-robins onto free
actors; get_next/get_next_unordered collect; map/map_unordered stream).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List[tuple] = []

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef; queues when all actors busy."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def _return_actor(self, actor) -> None:
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in SUBMISSION order. On timeout the task stays
        pending and its actor stays busy (popping before the result is
        ready would lose the result and double-book the actor)."""
        if not self.has_next():
            raise StopIteration("no pending results")
        ref = self._index_to_future[self._next_return_index]
        if timeout is not None:
            ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=timeout)
            if not ready:
                raise TimeoutError("next result not ready within timeout")
        self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        _, actor = self._future_to_actor.pop(ref)
        try:
            return ray_tpu.get(ref)
        finally:
            self._return_actor(actor)

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next COMPLETED result, any order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        ref = ready[0]
        index, actor = self._future_to_actor.pop(ref)
        self._index_to_future.pop(index)
        try:
            return ray_tpu.get(ref)
        finally:
            self._return_actor(actor)

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def push(self, actor: Any) -> None:
        """Add an idle actor to the pool."""
        self._return_actor(actor)
