"""Public utilities over the actor runtime.

Analogue of the reference's ray.util helpers (reference:
python/ray/util/actor_pool.py ActorPool, python/ray/util/queue.py Queue —
an actor-backed distributed queue).
"""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue

__all__ = ["ActorPool", "Empty", "Full", "Queue"]
