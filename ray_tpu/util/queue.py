"""Queue — a distributed FIFO backed by an async actor.

Analogue of the reference's queue (reference: python/ray/util/queue.py —
an asyncio.Queue inside a dedicated actor; producers/consumers block
server-side, so gets long-poll instead of spinning).
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self._q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)

    async def put(self, item: Any, timeout: Optional[float] = None) -> bool:
        try:
            if timeout is None:
                await self._q.put(item)
            else:
                await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        try:
            if timeout is None:
                return (True, await self._q.get())
            return (True, await asyncio.wait_for(self._q.get(), timeout))
        except asyncio.TimeoutError:
            return (False, None)

    async def put_nowait_batch(self, items: List[Any]) -> int:
        n = 0
        for it in items:
            if self._q.full():
                break
            self._q.put_nowait(it)
            n += 1
        return n

    async def qsize(self) -> int:
        return self._q.qsize()

    async def empty(self) -> bool:
        return self._q.empty()

    async def full(self) -> bool:
        return self._q.full()


class Queue:
    def __init__(self, maxsize: int = 0):
        self._actor = ray_tpu.remote(_QueueActor).remote(maxsize)

    def put(self, item: Any, timeout: Optional[float] = None) -> None:
        ok = ray_tpu.get(self._actor.put.remote(item, timeout),
                         timeout=None if timeout is None else timeout + 30)
        if not ok:
            raise Full("queue full")

    def get(self, timeout: Optional[float] = None) -> Any:
        ok, item = ray_tpu.get(
            self._actor.get.remote(timeout),
            timeout=None if timeout is None else timeout + 30)
        if not ok:
            raise Empty("queue empty")
        return item

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote(), timeout=30)

    def empty(self) -> bool:
        return ray_tpu.get(self._actor.empty.remote(), timeout=30)

    def full(self) -> bool:
        return ray_tpu.get(self._actor.full.remote(), timeout=30)

    def shutdown(self) -> None:
        try:
            ray_tpu.kill(self._actor)
        except Exception:
            pass
