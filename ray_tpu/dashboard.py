"""Dashboard-lite: an HTTP view over the state API + metrics.

Analogue of the reference's dashboard head (reference: python/ray/
dashboard/ — aiohttp head serving /api/... + Prometheus metrics; the
React client is out of scope). Endpoints:

    GET /                -> minimal HTML overview
    GET /api/summary     -> cluster summary JSON
    GET /api/nodes|actors|tasks|workers|jobs
    GET /metrics         -> Prometheus text exposition

Run via `python -m ray_tpu.cli dashboard --address H:P [--port 8265]`
or `start_dashboard(...)` in a driver.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>body{font-family:monospace;margin:2em}pre{background:#f4f4f4;
padding:1em}</style></head>
<body><h2>ray_tpu cluster</h2>
<pre id="summary">loading...</pre>
<h3>endpoints</h3>
<ul><li><a href="/api/summary">/api/summary</a></li>
<li><a href="/api/nodes">/api/nodes</a></li>
<li><a href="/api/actors">/api/actors</a></li>
<li><a href="/api/tasks">/api/tasks</a></li>
<li><a href="/api/workers">/api/workers</a></li>
<li><a href="/api/jobs">/api/jobs</a></li>
<li><a href="/metrics">/metrics</a></li></ul>
<script>fetch('/api/summary').then(r=>r.json()).then(d=>
document.getElementById('summary').textContent=
JSON.stringify(d,null,2));</script>
</body></html>"""


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet
        pass

    def _send(self, status: int, body: bytes,
              ctype: str = "application/json") -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (stdlib naming)
        from ray_tpu import state
        try:
            if self.path == "/" or self.path == "/index.html":
                self._send(200, _PAGE.encode(), "text/html")
                return
            if self.path == "/metrics":
                self._send(200, state.metrics_text().encode(),
                           "text/plain; version=0.0.4")
                return
            routes = {
                "/api/summary": state.cluster_summary,
                "/api/nodes": state.list_nodes,
                "/api/actors": state.list_actors,
                "/api/tasks": state.list_tasks,
                "/api/workers": state.list_workers,
            }
            if self.path == "/api/jobs":
                from ray_tpu import job_submission
                self._send(200, json.dumps(job_submission.list_jobs(),
                                           default=str).encode())
                return
            fn = routes.get(self.path)
            if fn is None:
                self._send(404, b'{"error": "not found"}')
                return
            self._send(200, json.dumps(fn(), default=str).encode())
        except Exception as e:  # pragma: no cover - defensive
            self._send(500, json.dumps({"error": repr(e)}).encode())


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="dashboard")
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._thread.join(timeout=5)


def start_dashboard(host: str = "127.0.0.1",
                    port: int = 8265) -> Dashboard:
    """Serve the dashboard over the CURRENT driver connection
    (ray_tpu.init must have been called)."""
    return Dashboard(host, port)
