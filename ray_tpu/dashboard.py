"""Dashboard-lite: an HTTP view over the state API + metrics.

Analogue of the reference's dashboard head (reference: python/ray/
dashboard/ — aiohttp head serving /api/... + Prometheus metrics; the
React client is out of scope). Endpoints:

    GET /                -> minimal HTML overview
    GET /api/summary     -> cluster summary JSON
    GET /api/nodes|actors|tasks|workers|jobs|task_events
    GET /api/state/tasks?state=FAILED&node=ID&name=f&limit=N
                         -> grafttrail task records (indexed filters)
    GET /api/state/objects?node=ID&plane=shm&live=1
                         -> object provenance records
    GET /api/state/summary -> per-function task rollup
    GET /api/state/audit   -> conservation audit report
    GET /api/timeline    -> Chrome-trace JSON incl. graftscope native spans
    GET /api/native      -> native hot-path latency rollup (graftscope)
    GET /api/cluster?window=N
                         -> graftpulse SLO view (per-op p50/p99 over the
                            last N pulses per node, per-node occupancy +
                            pulse health, resident totals; a running
                            graftload soak's live status rides along)
    GET /api/logs?task=&actor=&node=&level=30&tail=N&after_id=&stats=1
                         -> graftlog cluster log records (crash-
                            persistent rings; salvaged tails included)
    GET /api/prof?view=top|flame|collapsed|stats&task=&actor=&node=
                 &seconds=&limit=
                         -> graftprof continuous-profiling queries
    GET /api/meta?window=N
                         -> graftmeta self-telemetry (per-plane ingest
                            rates + fold p50/p99 over the last N meta
                            ticks, controller loop lag + RSS, store
                            occupancy)
    GET /flame           -> self-contained flamegraph view over /api/prof
    GET /metrics         -> Prometheus text exposition
    GET /metrics/cluster -> federated exposition + raytpu_cluster_*
                            pulse aggregates

Run via `python -m ray_tpu.cli dashboard --address H:P [--port 8265]`
or `start_dashboard(...)` in a driver.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title><meta charset="utf-8">
<style>
 body{font-family:system-ui,sans-serif;margin:1.5em;color:#1a1a1a}
 h2{margin:.2em 0}h3{margin:1.2em 0 .4em;border-bottom:1px solid #ddd}
 table{border-collapse:collapse;width:100%;font-size:13px}
 th,td{text-align:left;padding:3px 10px;border-bottom:1px solid #eee;
       font-family:ui-monospace,monospace;white-space:nowrap}
 th{background:#fafafa;position:sticky;top:0}
 .bar{display:inline-block;height:9px;background:#4a7;border-radius:2px;
      vertical-align:middle;margin-right:4px}
 .barbg{display:inline-block;width:90px;height:9px;background:#eee;
        border-radius:2px;vertical-align:middle;margin-right:6px}
 .dead{color:#c33}.alive{color:#2a7}.muted{color:#888}
 #ts{font-size:12px;color:#888}
 a{color:#36c;text-decoration:none}
</style></head><body>
<h2>ray_tpu cluster <span id="ts"></span></h2>
<div id="summary" class="muted">loading…</div>
<h3>Nodes</h3><table id="nodes"></table>
<h3>Actors</h3><table id="actors"></table>
<h3>Workers</h3><table id="workers"></table>
<h3>Task summary</h3><table id="tasks"></table>
<h3>Native hot paths (graftscope)</h3><table id="native"></table>
<h3>Cluster telemetry (graftpulse)</h3>
<div id="pulse" class="muted"></div>
<div id="soak" class="muted"></div><table id="cluster"></table>
<h3>Jobs</h3><table id="jobs"></table>
<p class="muted">raw: <a href="/api/summary">summary</a> ·
<a href="/api/nodes">nodes</a> · <a href="/api/actors">actors</a> ·
<a href="/api/tasks">tasks</a> · <a href="/api/workers">workers</a> ·
<a href="/api/jobs">jobs</a> · <a href="/api/native">native</a> ·
<a href="/api/cluster">cluster</a> · <a href="/api/meta">meta</a> ·
<a href="/api/prof?view=top">prof</a> · <a href="/flame">flame</a> ·
<a href="/api/logs?tail=100">logs</a> ·
<a href="/api/timeline">timeline</a> · <a href="/metrics">metrics</a> ·
<a href="/metrics/cluster">metrics/cluster</a></p>
<script>
const fmt = v => typeof v === "number" && !Number.isInteger(v)
    ? v.toFixed(2) : v;
function table(id, rows, cols, render) {
  const el = document.getElementById(id);
  if (!rows || !rows.length) { el.innerHTML =
      "<tr><td class=muted>(none)</td></tr>"; return; }
  let h = "<tr>" + cols.map(c => `<th>${c}</th>`).join("") + "</tr>";
  for (const r of rows) h += "<tr>" +
      cols.map(c => `<td>${render(r, c)}</td>`).join("") + "</tr>";
  el.innerHTML = h;
}
function usage(total, avail) {
  const out = [];
  for (const k of Object.keys(total || {})) {
    const t = total[k], a = (avail || {})[k] ?? t, used = t - a;
    const pct = t > 0 ? Math.round(100 * used / t) : 0;
    out.push(`${k} <span class=barbg><span class=bar style="width:${
        Math.round(pct * 0.9)}px"></span></span>${fmt(used)}/${fmt(t)}`);
  }
  return out.join(" &nbsp; ");
}
async function tick() {
  try {
    const [s, nodes, actors, tasks, workers, jobs, native, cluster] =
      await Promise.all(
      ["summary","nodes","actors","tasks","workers","jobs","native",
       "cluster"].map(
        p => fetch("/api/" + p).then(r => r.json())));
    document.getElementById("summary").textContent =
      `nodes ${s.nodes_alive}/${s.nodes_total} · actors ${s.actors} · ` +
      `resources ` + JSON.stringify(s.resources_available);
    table("nodes", nodes, ["node_id","state","addr","usage","labels"],
      (n, c) => c === "usage"
        ? usage(n.resources_total, n.resources_available)
        : c === "state" ? `<span class=${
            n.state === "ALIVE" ? "alive" : "dead"}>${n.state}</span>`
        : c === "labels" ? JSON.stringify(n.labels)
        : JSON.stringify(n[c]).replaceAll('"', ""));
    table("actors", actors,
      ["actor_id","name","state","node_id","restarts"],
      (a, c) => c === "state" ? `<span class=${
          a.state === "ALIVE" ? "alive" : "dead"}>${a.state}</span>`
        : a[c] ?? "");
    const byState = {};
    for (const t of tasks) byState[t.state] =
        (byState[t.state] || 0) + 1;
    table("tasks", Object.entries(byState).map(
        ([state, count]) => ({state, count})),
      ["state","count"], (t, c) => t[c]);
    table("workers", workers, Object.keys(workers[0] || {}),
      (w, c) => fmt(w[c]));
    table("native", native, ["name","count","mean_us","max_us"],
      (r, c) => fmt(r[c]));
    const tot = cluster.totals || {};
    document.getElementById("pulse").textContent =
      `objects ${tot.store_objects ?? 0} · queue ${
       tot.queue_depth ?? 0} · workers ${tot.num_workers ?? 0} · ` +
      `store ${fmt((tot.store_used ?? 0) / 1048576)}MiB · ` +
      `window ${fmt(cluster.window_s ?? 0)}s`;
    const soak = cluster.soak, soakEl = document.getElementById("soak");
    if (soak) {
      const wl = Object.entries(soak.workloads || {}).map(([k, v]) =>
        `${k} ${v.completed}/${v.submitted}` +
        (v.errors ? ` (${v.errors} err)` : "")).join(" · ");
      const chaos = (soak.chaos || []).map(c =>
        `${c.kind}@${c.at_s}s${c.ok ? "" : " FAILED"}`).join(", ");
      soakEl.innerHTML = `<b>soak ${soak.profile}</b> [${soak.phase}] ` +
        `${soak.elapsed_s}/${soak.duration_s}s · ${wl}` +
        (chaos ? ` · chaos: ${chaos}` : "");
    } else soakEl.textContent = "";
    table("cluster",
      Object.entries(cluster.ops || {}).map(([op, v]) => ({op, ...v})),
      ["op","calls","p50_ns","p99_ns","calls_per_s","bytes_per_s"],
      (r, c) => c === "p50_ns" || c === "p99_ns"
        ? fmt(r[c] / 1000) + "us" : fmt(r[c]));
    table("jobs", jobs, ["job_id","status","entrypoint"],
      (j, c) => j[c] ?? "");
    document.getElementById("ts").textContent =
      "refreshed " + new Date().toLocaleTimeString();
  } catch (e) {
    document.getElementById("ts").textContent = "refresh failed: " + e;
  }
}
tick(); setInterval(tick, 2000);
</script></body></html>"""

# Self-contained flamegraph over /api/prof?view=flame — nested-div icicle
# layout from the d3-flamegraph-shaped JSON, zero external assets so it
# renders on an air-gapped cluster.
_FLAME_PAGE = """<!doctype html>
<html><head><title>ray_tpu flamegraph</title><meta charset="utf-8">
<style>
 body{font-family:system-ui,sans-serif;margin:1.2em;color:#1a1a1a}
 #controls{margin-bottom:.8em;font-size:13px}
 #controls input{font-family:ui-monospace,monospace;font-size:12px;
   margin-right:.6em;padding:2px 4px;border:1px solid #ccc;
   border-radius:3px}
 #graph{font-size:11px;font-family:ui-monospace,monospace}
 .fr{box-sizing:border-box;height:17px;overflow:hidden;
   white-space:nowrap;border:1px solid #fff;border-radius:2px;
   padding:1px 3px;cursor:default;position:absolute}
 .fr:hover{border-color:#333}
 #graph{position:relative}
 #detail{margin-top:.6em;font-size:12px;color:#555;
   font-family:ui-monospace,monospace}
 .muted{color:#888}
 a{color:#36c;text-decoration:none}
</style></head><body>
<h2>graftprof flamegraph</h2>
<div id="controls">
 task <input id="task" size=18 placeholder="id prefix or name">
 actor <input id="actor" size=10> node <input id="node" size=10>
 seconds <input id="seconds" size=5>
 <button onclick="draw()">refresh</button>
 <span class=muted>(<a href="/">overview</a> ·
 <a href="/api/prof?view=top">top json</a>)</span>
</div>
<div id="graph"></div><div id="detail" class=muted></div>
<script>
function color(name) {
  let h = 0;
  for (const ch of name) h = (h * 31 + ch.charCodeAt(0)) >>> 0;
  return `hsl(${20 + h % 40},${60 + h % 30}%,${62 + h % 12}%)`;
}
function layout(node, x, w, depth, out, total) {
  out.push({node, x, w, depth});
  let cx = x;
  for (const c of node.children || []) {
    const cw = w * c.value / node.value;
    layout(c, cx, cw, depth + 1, out, total);
    cx += cw;
  }
  return out;
}
async function draw() {
  const q = new URLSearchParams({view: "flame"});
  for (const k of ["task","actor","node","seconds"]) {
    const v = document.getElementById(k).value.trim();
    if (v) q.set(k, v);
  }
  const root = await fetch("/api/prof?" + q).then(r => r.json());
  const g = document.getElementById("graph");
  if (!root.value) {
    g.innerHTML = "<span class=muted>no samples matched</span>";
    return;
  }
  const W = g.clientWidth || 960;
  const rows = layout(root, 0, W, 0, [], root.value);
  const maxd = Math.max(...rows.map(r => r.depth));
  g.style.height = (maxd + 1) * 17 + "px";
  g.innerHTML = "";
  for (const r of rows) {
    if (r.w < 1) continue;
    const d = document.createElement("div");
    d.className = "fr";
    d.style.left = r.x + "px";
    d.style.top = r.depth * 17 + "px";
    d.style.width = Math.max(1, r.w - 1) + "px";
    d.style.background = color(r.node.name);
    d.textContent = r.node.name;
    const pct = (100 * r.node.value / root.value).toFixed(1);
    d.title = `${r.node.name} — ${r.node.value} samples (${pct}%)`;
    d.onmouseenter = () => document.getElementById("detail")
        .textContent = d.title;
    g.appendChild(d);
  }
}
draw();
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet
        pass

    def _send(self, status: int, body: bytes,
              ctype: str = "application/json") -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (stdlib naming)
        from urllib.parse import parse_qs, urlsplit

        from ray_tpu import state
        try:
            parts = urlsplit(self.path)
            path = parts.path
            q = {k: v[0] for k, v in parse_qs(parts.query).items()}
            if path == "/" or path == "/index.html":
                self._send(200, _PAGE.encode(), "text/html")
                return
            if path == "/metrics":
                self._send(200, state.metrics_text().encode(),
                           "text/plain; version=0.0.4")
                return
            if path == "/metrics/cluster":
                self._send(200, state.cluster_metrics_text().encode(),
                           "text/plain; version=0.0.4")
                return
            # grafttrail state API: the ledger-backed views, with query-
            # string filters riding the same index intersections the CLI
            # uses (reference: dashboard /api/v0/tasks etc.).
            if path == "/api/state/tasks" or path == "/api/tasks":
                self._send(200, json.dumps(state.list_tasks(
                    state=q.get("state"), node=q.get("node"),
                    name=q.get("name"), actor=q.get("actor"),
                    limit=int(q.get("limit", 100))),
                    default=str).encode())
                return
            if path == "/api/state/objects":
                live = q.get("live")
                self._send(200, json.dumps(state.list_objects(
                    node=q.get("node"), plane=q.get("plane"),
                    live=(None if live is None else live == "1"),
                    limit=int(q.get("limit", 100))),
                    default=str).encode())
                return
            if path == "/flame":
                self._send(200, _FLAME_PAGE.encode(), "text/html")
                return
            if path == "/api/prof":
                # graftprof: profiles already live on the controller —
                # the query is a pure read, no attach step.
                view = q.get("view", "top")
                filt = dict(task=q.get("task"), actor=q.get("actor"),
                            node=q.get("node"),
                            seconds=(float(q["seconds"])
                                     if q.get("seconds") else None))
                if view == "flame":
                    body = state.prof_flame(**filt)
                elif view == "collapsed":
                    body = state.prof_collapsed(**filt)
                elif view == "stats":
                    body = state.prof_stats()
                else:
                    body = state.prof_top(
                        limit=int(q.get("limit", 30)), **filt)
                self._send(200, json.dumps(body, default=str).encode())
                return
            if path == "/api/logs":
                # graftlog: indexed cluster log records, incl. salvaged
                # final lines of dead workers.  stats=1 -> store stats.
                if q.get("stats") == "1":
                    body = state.log_stats()
                else:
                    body = state.list_logs(
                        task=q.get("task"), actor=q.get("actor"),
                        node=q.get("node"),
                        level=int(q.get("level", 0) or 0),
                        after_id=int(q.get("after_id", 0) or 0),
                        limit=int(q.get("tail", q.get("limit", 100))))
                self._send(200, json.dumps(body, default=str).encode())
                return
            if path == "/api/state/summary":
                self._send(200, json.dumps(state.summary_tasks(),
                                           default=str).encode())
                return
            if path == "/api/state/audit":
                grace = q.get("grace")
                self._send(200, json.dumps(
                    state.audit(float(grace) if grace else None),
                    default=str).encode())
                return
            if path == "/api/meta":
                # graftmeta: the controller's self-telemetry — plane
                # ingest rates, fold-latency percentiles, loop lag,
                # RSS, store occupancy. ?window=N in meta ticks.
                self._send(200, json.dumps(state.meta_snapshot(
                    window=int(q.get("window", 60) or 60)),
                    default=str).encode())
                return
            if path == "/api/cluster":
                # graftpulse SLO view; ?window=N bounds how many recent
                # pulses per node feed the aggregates (verdict engines
                # want "p99 over the last N ticks", not all-time). The
                # soak status blob rides along while a soak runs.
                self._send(200, json.dumps(state.cluster_telemetry(
                    window=int(q.get("window", 30) or 30)),
                    default=str).encode())
                return
            routes = {
                "/api/summary": state.cluster_summary,
                "/api/nodes": state.list_nodes,
                "/api/actors": state.list_actors,
                "/api/task_events": state.list_task_events,
                "/api/workers": state.list_workers,
                "/api/timeline": state.timeline,
                "/api/native": state.native_latency,
            }
            if path == "/api/jobs":
                from ray_tpu import job_submission
                self._send(200, json.dumps(job_submission.list_jobs(),
                                           default=str).encode())
                return
            fn = routes.get(path)
            if fn is None:
                self._send(404, b'{"error": "not found"}')
                return
            self._send(200, json.dumps(fn(), default=str).encode())
        except Exception as e:  # pragma: no cover - defensive
            self._send(500, json.dumps({"error": repr(e)}).encode())


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="dashboard")
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._thread.join(timeout=5)


def start_dashboard(host: str = "127.0.0.1",
                    port: int = 8265) -> Dashboard:
    """Serve the dashboard over the CURRENT driver connection
    (ray_tpu.init must have been called)."""
    return Dashboard(host, port)
