"""Device-mesh construction for ray_tpu.

TPU-first replacement for the reference's process-group world (torch DDP/NCCL
groups created by Ray Train, reference: python/ray/train/torch/config.py and
python/ray/util/collective/collective.py:166). Instead of rank-indexed process
groups, parallelism is expressed as named axes of a `jax.sharding.Mesh`;
XLA/GSPMD inserts the collectives over ICI/DCN.

Axis vocabulary (all six are always present; unused axes have size 1):

  pp   pipeline parallel — p2p activation transfer, lowest bandwidth need,
       outermost (maps to DCN across slices in multi-slice deployments)
  dp   pure data parallel — gradient allreduce per step
  fsdp sharded data parallel (ZeRO-3/GSPMD param sharding) — allgather/reducescatter
  ep   expert parallel — all-to-all dispatch for MoE layers
  sp   sequence/context parallel — ring attention K/V rotation (ppermute)
  tp   tensor parallel — per-layer allreduce, highest bandwidth, innermost so it
       lands on the tightest ICI ring
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_NAMES = ("pp", "dp", "fsdp", "ep", "sp", "tp")

# Axes over which the global batch is split.
BATCH_AXES = ("dp", "fsdp")
# Axes over which model parameters are sharded (fsdp dimension-sharding + tp).
PARAM_AXES = ("fsdp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1
    # Cross-slice (DCN) factors: how much of pp/dp/fsdp spans SLICES
    # rather than ICI (SURVEY §5.8; the scaling-book recipe: only the
    # lowest-bandwidth axes — dp, fsdp-reduce, pp activations — may ride
    # DCN; tp/sp/ep stay strictly intra-slice, enforced by construction
    # since they have no DCN factor). The slice-crossing factor of each
    # axis is OUTERMOST within that axis, so GSPMD's per-axis collectives
    # decompose into intra-slice ICI ops + a small cross-slice phase.
    dcn_pp: int = 1
    dcn_dp: int = 1
    dcn_fsdp: int = 1

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.pp, self.dp, self.fsdp, self.ep, self.sp, self.tp)

    @property
    def num_devices(self) -> int:
        return math.prod(self.shape)

    @property
    def num_slices(self) -> int:
        return self.dcn_pp * self.dcn_dp * self.dcn_fsdp

    @property
    def dcn_shape(self) -> tuple[int, ...]:
        return (self.dcn_pp, self.dcn_dp, self.dcn_fsdp, 1, 1, 1)

    @property
    def ici_shape(self) -> tuple[int, ...]:
        """Per-slice factor of each axis."""
        out = []
        for name, total, dcn in zip(AXIS_NAMES, self.shape, self.dcn_shape):
            if total % dcn:
                raise ValueError(
                    f"axis {name}={total} not divisible by its DCN factor "
                    f"{dcn} (the slice-crossing factor must divide the "
                    f"axis)")
            out.append(total // dcn)
        return tuple(out)

    def with_axes(self, **kw) -> "MeshConfig":
        return dataclasses.replace(self, **kw)

    @staticmethod
    def for_devices(n: int) -> "MeshConfig":
        """Reasonable default factorization: all-FSDP (ZeRO-style) over n chips."""
        return MeshConfig(fsdp=n)


def _slice_groups(devices: list, num_slices: int,
                  per: Optional[int] = None) -> list:
    """Partition devices into per-slice groups. Real multi-slice TPUs
    expose `device.slice_index`; virtual/CPU meshes fall back to
    contiguous equal chunks (the driver's 2-virtual-slice dry run).

    `per` (group size) defaults to len(devices)//num_slices; pass it
    explicitly when `devices` is a superset to draw from (so a mesh
    needing 6 of each 8-device physical slice isn't rejected by a
    pre-truncated list)."""
    if per is None:
        if len(devices) % num_slices:
            raise ValueError(f"{len(devices)} devices do not split into "
                             f"{num_slices} equal slices")
        per = len(devices) // num_slices
    if per < 1 or len(devices) < num_slices * per:
        raise ValueError(f"need {num_slices} slices of {per} devices, "
                         f"have {len(devices)} devices")
    by_slice: dict = {}
    n_with = sum(1 for d in devices
                 if getattr(d, "slice_index", None) is not None)
    if n_with and n_with != len(devices):
        raise ValueError(
            f"mixed device list: {n_with}/{len(devices)} devices report a "
            f"slice_index — cannot infer slice topology")
    if n_with:
        for d in devices:
            by_slice.setdefault(d.slice_index, []).append(d)
    if by_slice:
        # Real slice topology present: no group may STRADDLE a physical
        # slice boundary — a straddling "ICI" submesh is a topology lie.
        # Subdividing is fine: one physical slice with >= k*per devices
        # yields k virtual slices (this is how the driver's
        # jax.distributed multi-process CPU dryrun presents itself —
        # every device reports slice_index=0). Two separate concerns:
        #  SELECT round-robin across physical slices (depth-first would
        #  pack every virtual slice into the lowest-indexed physical
        #  slice and leave the others' devices out of the mesh);
        #  ORDER the selection physical-slice-major, so the OUTERMOST
        #  nontrivial DCN axis (np.unravel_index varies the last
        #  coordinate fastest) is the one that truly crosses physical
        #  slices — matching the axis doc above: pp outermost on DCN.
        per_slice_groups = []  # [(phys_key, [groups...])] in index order
        for k in sorted(by_slice):
            ds = by_slice[k]
            per_slice_groups.append(
                (k, [ds[i * per:(i + 1) * per]
                     for i in range(len(ds) // per)]))
        selected: list = []  # (phys_order, depth, group)
        depth = 0
        while len(selected) < num_slices:
            layer = [(order, depth, gs[depth])
                     for order, (_, gs) in enumerate(per_slice_groups)
                     if depth < len(gs)]
            if not layer:
                raise ValueError(
                    f"cannot form {num_slices} slices of {per} devices "
                    f"from physical slices "
                    f"{ {k: len(v) for k, v in by_slice.items()} } "
                    f"without straddling a slice boundary — pick DCN "
                    f"factors matching the real slice topology")
            selected.extend(layer)
            depth += 1
        selected = selected[:num_slices]
        selected.sort(key=lambda t: (t[0], t[1]))
        return [g for _, _, g in selected]
    # No slice identity (CPU / virtual mesh): contiguous equal chunks.
    return [devices[i * per:(i + 1) * per] for i in range(num_slices)]


def _merge_hybrid(groups: list, config: "MeshConfig") -> Mesh:
    """Compose per-slice ICI submeshes into the hybrid mesh: each axis's
    slice-crossing (DCN) factor is OUTERMOST within the axis — the layout
    mesh_utils.create_hybrid_device_mesh produces, built manually so
    virtual CPU slices work identically for the multi-chip dry run."""
    ici_shape = config.ici_shape
    dcn_shape = config.dcn_shape
    slice_arrays = []
    for g in groups:
        try:
            a = mesh_utils.create_device_mesh(
                ici_shape, devices=g, allow_split_physical_axes=True)
        except Exception:
            a = np.array(g).reshape(ici_shape)
        slice_arrays.append(a)
    arr = np.empty(dcn_shape + ici_shape, dtype=object)
    for si, sa in enumerate(slice_arrays):
        arr[np.unravel_index(si, dcn_shape)] = sa
    # Interleave (dcn_0, ici_0, dcn_1, ici_1, ...) then merge each pair:
    # axis k of the final mesh = dcn_k (outer) x ici_k (inner).
    k = len(AXIS_NAMES)
    arr = arr.transpose([ax for i in range(k) for ax in (i, k + i)])
    return Mesh(arr.reshape(config.shape), AXIS_NAMES)


def _select_single_slice(devices: list, n: int) -> list:
    """Pick n devices for a single-slice (all-ICI) mesh. When the devices
    carry real slice topology, prefer a single physical slice — a
    truncation that straddles slices would label DCN hops as ICI. If no
    one slice holds n devices, the mesh genuinely spans slices: warn
    (collectives on every axis will ride DCN; set dcn_* factors to split
    the low-bandwidth axes deliberately) and fall back to the first n."""
    if getattr(devices[0], "slice_index", None) is None:
        return devices[:n]
    by_slice: dict = {}
    for d in devices:
        si = getattr(d, "slice_index", None)
        if si is None:
            return devices[:n]  # mixed: no usable topology signal
        by_slice.setdefault(si, []).append(d)
    for k in sorted(by_slice):
        if len(by_slice[k]) >= n:
            return by_slice[k][:n]
    from ray_tpu.utils import get_logger
    get_logger("mesh").warning(
        "single-slice mesh of %d devices spans %d physical slices — every "
        "axis's collectives will cross DCN; set MeshConfig dcn_* factors "
        "to place only low-bandwidth axes (dp/fsdp/pp) across slices",
        n, len(by_slice))
    return devices[:n]


def build_mesh(config: MeshConfig,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    n = config.num_devices
    if n > len(devices):
        raise ValueError(
            f"MeshConfig {config} needs {n} devices but only {len(devices)} available")
    devices = list(devices)
    if config.num_slices == 1:
        devices = _select_single_slice(devices, n)
        try:
            dev_array = mesh_utils.create_device_mesh(
                config.shape, devices=devices, allow_split_physical_axes=True)
        except Exception:
            dev_array = np.array(devices).reshape(config.shape)
        return Mesh(dev_array, AXIS_NAMES)

    # Multi-slice (DCN) mesh. Validate axis/DCN divisibility up front
    # (ici_shape raises the precise error; per = prod(ici_shape) >= 1
    # follows), then group from the FULL device list (not a [:n]
    # truncation) so a mesh needing, say, 6 devices from each of two
    # 8-device physical slices is satisfiable.
    per = math.prod(config.ici_shape)
    groups = _slice_groups(devices, config.num_slices, per=per)
    return _merge_hybrid(groups, config)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    device = device or jax.devices()[0]
    return Mesh(np.array([device]).reshape((1,) * len(AXIS_NAMES)), AXIS_NAMES)
