"""Device-mesh construction for ray_tpu.

TPU-first replacement for the reference's process-group world (torch DDP/NCCL
groups created by Ray Train, reference: python/ray/train/torch/config.py and
python/ray/util/collective/collective.py:166). Instead of rank-indexed process
groups, parallelism is expressed as named axes of a `jax.sharding.Mesh`;
XLA/GSPMD inserts the collectives over ICI/DCN.

Axis vocabulary (all six are always present; unused axes have size 1):

  pp   pipeline parallel — p2p activation transfer, lowest bandwidth need,
       outermost (maps to DCN across slices in multi-slice deployments)
  dp   pure data parallel — gradient allreduce per step
  fsdp sharded data parallel (ZeRO-3/GSPMD param sharding) — allgather/reducescatter
  ep   expert parallel — all-to-all dispatch for MoE layers
  sp   sequence/context parallel — ring attention K/V rotation (ppermute)
  tp   tensor parallel — per-layer allreduce, highest bandwidth, innermost so it
       lands on the tightest ICI ring
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_NAMES = ("pp", "dp", "fsdp", "ep", "sp", "tp")

# Axes over which the global batch is split.
BATCH_AXES = ("dp", "fsdp")
# Axes over which model parameters are sharded (fsdp dimension-sharding + tp).
PARAM_AXES = ("fsdp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.pp, self.dp, self.fsdp, self.ep, self.sp, self.tp)

    @property
    def num_devices(self) -> int:
        return math.prod(self.shape)

    def with_axes(self, **kw) -> "MeshConfig":
        return dataclasses.replace(self, **kw)

    @staticmethod
    def for_devices(n: int) -> "MeshConfig":
        """Reasonable default factorization: all-FSDP (ZeRO-style) over n chips."""
        return MeshConfig(fsdp=n)


def build_mesh(config: MeshConfig, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    n = config.num_devices
    if n > len(devices):
        raise ValueError(
            f"MeshConfig {config} needs {n} devices but only {len(devices)} available")
    devices = list(devices)[:n]
    try:
        dev_array = mesh_utils.create_device_mesh(
            config.shape, devices=devices, allow_split_physical_axes=True)
    except Exception:
        dev_array = np.array(devices).reshape(config.shape)
    return Mesh(dev_array, AXIS_NAMES)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    device = device or jax.devices()[0]
    return Mesh(np.array([device]).reshape((1,) * len(AXIS_NAMES)), AXIS_NAMES)
