"""Logical-axis sharding rules.

Parameters and activations are annotated with *logical* axis names; a rules
table maps logical names onto mesh axes. This is the GSPMD-idiomatic
replacement for the reference's per-strategy runtimes (torch DDP vs FSDP wrap
in reference python/ray/train/torch/train_loop_utils.py:170-181): switching
between DP / ZeRO-3 / TP / EP is a rules-table change, not a different runtime.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxes = Union[None, str, Tuple[str, ...]]

# Default rules, Megatron-style: hidden dims over tp, d_model params over fsdp,
# batch over (dp, fsdp), sequence over sp, experts over ep.
DEFAULT_RULES: Dict[str, MeshAxes] = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "embed": "fsdp",        # d_model dimension of weight matrices
    "vocab": "tp",
    "mlp": "tp",            # ffn hidden dimension
    "heads": "tp",          # attention heads
    "kv_heads": "tp",
    "head_dim": None,
    "qkv": None,
    "expert": "ep",
    "layers": None,         # stacked-layer leading axis (pp handled by shard_map)
    "stage": "pp",
    "act_embed": None,      # activation d_model — replicated within (tp) by default
}


def spec_for(logical_axes: Tuple[Optional[str], ...],
             rules: Optional[Dict[str, MeshAxes]] = None) -> P:
    rules = rules or DEFAULT_RULES
    parts = []
    for name in logical_axes:
        if name is None:
            parts.append(None)
        else:
            parts.append(rules.get(name))
    return P(*parts)


def sharding_for(logical_axes: Tuple[Optional[str], ...], mesh: Mesh,
                 rules: Optional[Dict[str, MeshAxes]] = None) -> NamedSharding:
    return NamedSharding(mesh, spec_for(logical_axes, rules))


def tree_specs(logical_tree: Any, rules: Optional[Dict[str, MeshAxes]] = None) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda axes: spec_for(axes, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x),
    )


def tree_shardings(logical_tree: Any, mesh: Mesh,
                   rules: Optional[Dict[str, MeshAxes]] = None) -> Any:
    return jax.tree.map(
        lambda axes: sharding_for(axes, mesh, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x),
    )


def batch_spec() -> P:
    """[batch, seq, ...] activation spec."""
    return P(("dp", "fsdp"), "sp")


def shard_batch(batch: Any, mesh: Mesh) -> Any:
    sh = NamedSharding(mesh, batch_spec())
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch)
