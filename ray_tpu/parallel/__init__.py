from ray_tpu.parallel.mesh import AXIS_NAMES, MeshConfig, build_mesh, single_device_mesh
from ray_tpu.parallel.sharding import (DEFAULT_RULES, batch_spec, shard_batch,
                                       sharding_for, spec_for, tree_shardings,
                                       tree_specs)
from ray_tpu.parallel.context import ParallelContext
from ray_tpu.parallel.pipeline import gpipe_spmd

__all__ = [
    "AXIS_NAMES", "MeshConfig", "build_mesh", "single_device_mesh",
    "DEFAULT_RULES", "batch_spec", "shard_batch", "sharding_for", "spec_for",
    "tree_shardings", "tree_specs", "ParallelContext", "gpipe_spmd",
]
