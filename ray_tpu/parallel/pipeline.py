"""Pipeline parallelism over the ``pp`` mesh axis.

The reference's substrate for pipeline-style execution is the compiled actor
DAG with NCCL P2P channels (reference: python/ray/dag/compiled_dag_node.py and
python/ray/experimental/channel/torch_tensor_accelerator_channel.py:49). The
TPU-native equivalent is compiled *into* the XLA program: a GPipe microbatch
schedule expressed as a ``lax.scan`` whose per-step stage-to-stage activation
transfer is a ``lax.ppermute`` hop on the ``pp`` axis. Autodiff through the
scan + ppermute yields the reverse pipeline schedule for the backward pass.

Runs inside a shard_map whose manual axes include "pp"; all other mesh axes
(dp/fsdp/tp/sp/ep) stay automatic, so GSPMD still inserts the tensor-parallel
and FSDP collectives inside each stage.

Round-1 schedule is plain GPipe (bubble = (pp-1)/(M+pp-1)); interleaved /
circular schedules are a planned optimization.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp


def _vary(x, axis_name):
    try:
        return jax.lax.pcast(x, (axis_name,), to="varying")
    except AttributeError:  # pragma: no cover - older jax spelling
        return jax.lax.pvary(x, (axis_name,))


def gpipe_spmd(stage_fn: Callable[[Any, jax.Array], "tuple[jax.Array, jax.Array] | jax.Array"],
               stage_params: Any,
               microbatches: jax.Array,
               *,
               axis_name: str = "pp",
               with_aux: bool = False):
    """GPipe forward over the pp axis. Call inside shard_map (manual on pp).

    stage_fn(params_local, x) -> y (or (y, aux_scalar) with with_aux=True)
      with x, y of one microbatch's shape.
    stage_params: pytree whose leaves have a leading stacked-stage axis of
      local size 1 (sharded P("pp") on that axis by the caller's in_specs).
    microbatches: [M, mb, ...] — replicated across pp.
    Returns [M, mb, ...] outputs of the final stage broadcast to all
    stages; with_aux=True also returns the per-stage aux summed over the
    pp axis and averaged over microbatches (warmup/drain steps, whose
    inputs are bubble garbage, are excluded).
    """
    pp = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    params_local = jax.tree.map(lambda p: p[0], stage_params)
    num_mb = microbatches.shape[0]
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    # mb_in: cast to pp-varying; init buffers derive from it (times zero) so
    # they inherit every other manual axis the caller's shard_map has (e.g. sp).
    mb_in = _vary(microbatches, axis_name)
    out0 = mb_in * 0
    state0 = out0[0]
    # Scalar zero derived from out0 so it inherits the manual-axis varying
    # type (same idiom as the model's aux accumulator).
    aux0 = (out0[(0,) * out0.ndim] * 0).astype(jnp.float32)

    def step(carry, t):
        state, outputs, aux_acc = carry
        mb_idx = jnp.clip(t, 0, num_mb - 1)
        x_in = jnp.where(stage == 0,
                         jax.lax.dynamic_index_in_dim(mb_in, mb_idx, 0,
                                                      keepdims=False),
                         state)
        res = stage_fn(params_local, x_in)
        y, aux = res if with_aux else (res, jnp.zeros((), jnp.float32))
        # This stage computes REAL microbatches only for t in
        # [stage, stage + num_mb); outside that window it chews bubble
        # garbage whose aux must not count.
        active = (t >= stage) & (t - stage < num_mb)
        aux_acc = aux_acc + jnp.where(active, aux, 0.0)
        out_idx = t - (pp - 1)
        valid = (stage == pp - 1) & (out_idx >= 0)
        safe_idx = jnp.clip(out_idx, 0, num_mb - 1)
        prev = jax.lax.dynamic_index_in_dim(outputs, safe_idx, 0, keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(valid, y, prev), safe_idx, 0)
        state = jax.lax.ppermute(y, axis_name, perm)
        return (state, outputs, aux_acc), None

    (_, outputs, aux_acc), _ = jax.lax.scan(
        step, (state0, out0, aux0), jnp.arange(num_mb + pp - 1))
    # Broadcast final-stage outputs to every stage (indicator + psum).
    mask = (stage == pp - 1).astype(outputs.dtype)
    out = jax.lax.psum(outputs * mask, axis_name)
    if not with_aux:
        return out
    # Sum stage-local aux across stages; average over microbatches so the
    # scale matches the non-pp full-batch aux.
    aux = jax.lax.psum(aux_acc, axis_name) / num_mb
    return out, aux
