"""ParallelContext: mesh + mesh-config + sharding rules bundle threaded through
model/train code (the TPU-native analogue of the reference Train worker's
process-group context, reference: python/ray/train/torch/config.py)."""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import MeshConfig, build_mesh
from ray_tpu.parallel.sharding import DEFAULT_RULES, MeshAxes, batch_spec


@dataclasses.dataclass
class ParallelContext:
    mesh: Mesh
    config: MeshConfig
    rules: Dict[str, MeshAxes] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_RULES))

    @staticmethod
    def create(config: Optional[MeshConfig] = None, devices=None) -> "ParallelContext":
        if config is None:
            n = len(devices) if devices is not None else len(jax.devices())
            config = MeshConfig.for_devices(n)
        return ParallelContext(build_mesh(config, devices), config)

    @property
    def num_slices(self) -> int:
        """Slices this context's mesh spans (DCN axes; 1 = single slice)."""
        return self.config.num_slices

    @property
    def sp(self) -> int:
        return self.config.sp

    @property
    def pp(self) -> int:
        return self.config.pp

    @property
    def ep(self) -> int:
        return self.config.ep

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, batch_spec())

    def activation_spec(self) -> P:
        return P(*batch_spec(), None)
