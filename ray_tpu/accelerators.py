"""TPU accelerator manager: chip discovery, visibility, slice labels.

Analogue of the reference's TPU accelerator manager (reference:
python/ray/_private/accelerators/tpu.py:199 TPUAcceleratorManager — chip
discovery via TPU_CHIPS_PER_HOST_BOUNDS / /dev devices, TPU_VISIBLE_CHIPS
env for workers, slice-name node label :564, pod-type resources), rebuilt
TPU-first: the node agent calls into this module at startup to advertise
``TPU`` as a first-class scheduler resource plus slice/topology labels, and
at actor spawn to pin specific chips to a worker process.

Design departures from the reference: no GCE metadata server calls (works
in any container), and chip accounting lives in the node agent's resource
vectors rather than a bolted-on custom-resource string.
"""

from __future__ import annotations

import glob
import os
import re
from typing import Dict, List, Optional

from ray_tpu.utils.config import GlobalConfig

# Node label keys (reference: tpu.py RAY_NODE_TPU_SLICE_NAME_KEY etc.)
TPU_SLICE_NAME_LABEL = "ray_tpu.io/tpu-slice-name"
TPU_ACCELERATOR_TYPE_LABEL = "ray_tpu.io/tpu-accelerator-type"
TPU_WORKER_ID_LABEL = "ray_tpu.io/tpu-worker-id"
TPU_TOPOLOGY_LABEL = "ray_tpu.io/tpu-topology"


def _chips_from_bounds(bounds: str) -> Optional[int]:
    """Parse '2,2,1'-style TPU_CHIPS_PER_HOST_BOUNDS into a chip count."""
    try:
        dims = [int(x) for x in bounds.split(",") if x.strip()]
        n = 1
        for d in dims:
            n *= d
        return n if n > 0 else None
    except ValueError:
        return None


def num_tpu_chips() -> int:
    """Detect the number of TPU chips attached to this host.

    Priority: explicit config flag (tests / operator override) >
    TPU_CHIPS_PER_HOST_BOUNDS env (set by the TPU VM runtime) >
    /dev/accel* or /dev/vfio device files > none.
    """
    if GlobalConfig.tpu_chips_per_host > 0:
        return int(GlobalConfig.tpu_chips_per_host)
    bounds = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS", "")
    if bounds:
        n = _chips_from_bounds(bounds)
        if n:
            return n
    accel = glob.glob("/dev/accel*")
    if accel:
        return len(accel)
    vfio = glob.glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio)
    return 0


def visible_chip_ids() -> List[int]:
    """Chip ids this agent may hand to workers (tpu_visible_chips filter)."""
    n = num_tpu_chips()
    spec = GlobalConfig.tpu_visible_chips.strip()
    if spec:
        ids = sorted({int(x) for x in spec.split(",") if x.strip()})
        return [i for i in ids if 0 <= i < n]
    return list(range(n))


def accelerator_type() -> str:
    """e.g. 'v5e-16' — from TPU VM env, else empty."""
    t = os.environ.get("TPU_ACCELERATOR_TYPE", "")
    return t if re.match(r"^v\d", t) else ""


def slice_name() -> str:
    """Multi-host slice identity (gang scheduling key)."""
    return os.environ.get("TPU_NAME", os.environ.get("TPU_WORKER_HOSTNAMES",
                                                     ""))


def tpu_worker_id() -> int:
    try:
        return int(os.environ.get("TPU_WORKER_ID", "0"))
    except ValueError:
        return 0


def node_labels() -> Dict[str, str]:
    labels: Dict[str, str] = {}
    if accelerator_type():
        labels[TPU_ACCELERATOR_TYPE_LABEL] = accelerator_type()
    if slice_name():
        labels[TPU_SLICE_NAME_LABEL] = slice_name()
        labels[TPU_WORKER_ID_LABEL] = str(tpu_worker_id())
    topo = os.environ.get("TPU_TOPOLOGY", "")
    if topo:
        labels[TPU_TOPOLOGY_LABEL] = topo
    return labels


def reserve_tpu_slice(num_hosts: int,
                      resources_per_host: Optional[Dict[str, float]] = None,
                      *, accelerator_type_filter: str = "",
                      strategy: str = "STRICT_SPREAD"):
    """Atomically reserve `num_hosts` worker nodes of ONE TPU slice as a
    placement group (reference: python/ray/_private/accelerators/tpu.py:145
    reserve_tpu_slice + train/v2/.../tpu_reservation_callback.py:9).

    All bundles are constrained to nodes sharing one slice-name label
    ("$same" gang), so the reservation either lands entirely on a single
    slice or stays pending — multi-host gang scheduling can then target
    the PG's bundles one-per-host.
    """
    import ray_tpu

    bundle = dict(resources_per_host or {"TPU": 4.0})
    selector: Dict[str, str] = {TPU_SLICE_NAME_LABEL: "$same"}
    if accelerator_type_filter:
        selector[TPU_ACCELERATOR_TYPE_LABEL] = accelerator_type_filter
    return ray_tpu.placement_group(
        [dict(bundle) for _ in range(num_hosts)], strategy=strategy,
        bundle_label_selector=[dict(selector) for _ in range(num_hosts)])


def worker_env_for_chips(chip_ids: List[int]) -> Dict[str, str]:
    """Env vars that scope a spawned worker process to specific chips
    (reference: tpu.py set_current_process_visible_accelerator_ids →
    TPU_VISIBLE_CHIPS)."""
    ids = ",".join(str(i) for i in chip_ids)
    return {
        "TPU_VISIBLE_CHIPS": ids,
        # One process per assigned chip group; single-host bounds.
        "TPU_CHIPS_PER_PROCESS_BOUNDS": f"1,{len(chip_ids)},1",
        "TPU_PROCESS_BOUNDS": "1,1,1",
    }
