"""Streaming executor: drives an operator-graph topology with per-op
budgets and backpressure.

Analogue of the reference's streaming execution core (reference:
python/ray/data/_internal/execution/streaming_executor.py:61 executor loop,
streaming_executor_state.py build_streaming_topology/select_operator_to_run/
process_completed_tasks, resource_manager.py:40 ResourceManager +
:363 ReservationOpResourceAllocator, backpressure_policy/
concurrency_cap_backpressure_policy.py). Redesigned pull-driven:

  * The CONSUMER drives the loop — each `next()` harvests completions,
    moves blocks downstream, and dispatches new work until an output
    block is available. No executor thread: when the consumer stalls,
    dispatch stops, in-flight generator tasks park on the runtime's
    per-task yield backpressure, and total in-flight memory stays at
    (per-op task budget x per-task window) blocks. A slow consumer
    therefore stalls the producers (the reference needs a thread +
    output-queue cap for the same property; here it falls out of the
    pull design).
  * Operator selection prefers the op CLOSEST TO THE SINK that can run
    (same drain-downstream-first policy as select_operator_to_run:
    finishing blocks frees memory before new blocks are created).
  * The ResourceManager splits a global in-flight task budget equally
    across task-launching ops (reservation), and lends unused slots to
    ops with queued work (the reservation allocator's shared pool).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ray_tpu.data.operators import (AllToAllOperator, ConcatOperator,
                                    PhysicalOperator, SourceOperator)
from ray_tpu.utils import get_logger

logger = get_logger("data.streaming_executor")

# Global in-flight task budget split across task-launching operators
# (reference: ReservationOpResourceAllocator's reservation ratio over the
# cluster resource budget; here BOTH a task-slot budget and a BYTE budget
# apply — slots bound cold-start concurrency, bytes bound steady-state
# memory once block sizes are observed).
DEFAULT_TASK_BUDGET = 8

# Per-edge queue cap: an op stops dispatching when this many of its output
# blocks sit undispatched in the downstream op's input queue (reference:
# OutputQueueSizeBackpressurePolicy).
DEFAULT_EDGE_QUEUE_CAP = 16


def _default_memory_budget() -> int:
    from ray_tpu.utils.config import GlobalConfig
    b = GlobalConfig.data_memory_budget_bytes
    if b > 0:
        return b
    # A quarter of the local object store: leaves room for task args,
    # other datasets, and non-Data objects.
    return max(64 * 1024 * 1024,
               GlobalConfig.object_store_memory_bytes // 4)


class OpState:
    """Executor-side wiring for one operator."""

    def __init__(self, op: PhysicalOperator):
        self.op = op
        # (downstream OpState, branch index for ConcatOperator or None)
        self.downstream: Optional[Tuple["OpState", Optional[int]]] = None
        self.upstreams: List["OpState"] = []
        self.done_notified = False
        # Byte accounting for blocks queued at THIS op's input (sizes
        # parallel the op's input deque for launcher ops; Concat tracks
        # per-branch totals).
        self.in_sizes: deque = deque()
        self.in_bytes = 0
        self.branch_in_bytes: Dict[int, int] = {}
        self.branch_in_sizes: Dict[int, deque] = {}

    @property
    def name(self) -> str:
        return self.op.name


class ResourceManager:
    """Task-slot budgeting + queue backpressure across operators
    (reference: resource_manager.py ReservationOpResourceAllocator +
    backpressure policies). Each task-launching op holds a reserved share
    of the global budget; the remainder is a shared pool any op may
    borrow from. An op's output edge blocks when the downstream input
    queue exceeds the edge cap."""

    def __init__(self, ops: List[OpState], budget: int = DEFAULT_TASK_BUDGET,
                 edge_queue_cap: int = DEFAULT_EDGE_QUEUE_CAP,
                 memory_budget: Optional[int] = None):
        self.budget = max(1, budget)
        self.edge_queue_cap = edge_queue_cap
        self.memory_budget = (memory_budget if memory_budget is not None
                              else _default_memory_budget())
        # Barrier (AllToAll) ops run driver-side outside the slot budget,
        # so they neither reserve nor consume shares.
        self._launchers = [
            s for s in ops
            if not isinstance(s.op, (SourceOperator, ConcatOperator,
                                     AllToAllOperator))]
        n = max(1, len(self._launchers))
        self._reserved = max(1, self.budget // n)
        self._shared_pool = max(0, self.budget - self._reserved * n)
        # Byte budget split the same way: each launcher owns a reserved
        # share; the remainder is a shared pool (reference:
        # resource_manager.py:363 ReservationOpResourceAllocator, whose
        # core abstraction is MEMORY — slot budgets alone cannot prevent
        # OOM when block sizes vary 10x between ops).
        self._mem_reserved = max(1, self.memory_budget // n)
        self._mem_shared = max(0, self.memory_budget
                               - self._mem_reserved * n)
        self.peak_mem_used = 0
        self._sink_bytes_fn = lambda: 0  # wired by the executor

    # Pessimistic per-task output estimate until the op's first task
    # finishes (the reference similarly charges an assumed block size
    # before sizes are observed — a zero cold estimate would let the
    # full slot budget launch before the byte budget could engage).
    COLD_TASK_BYTES = 2 * 1024 * 1024

    @classmethod
    def _est_task_bytes(cls, state: OpState) -> int:
        """Expected output bytes of ONE task of this op, from observed
        blocks (pessimistic constant until the first task finishes)."""
        m = state.op.metrics
        if m.tasks_finished <= 0:
            return cls.COLD_TASK_BYTES
        return m.bytes_out_estimate // m.tasks_finished

    def _mem_used(self, state: OpState) -> int:
        """Bytes attributable to this op: its unconsumed output blocks
        (queued at the downstream input / executor sink) plus the
        expected output of its in-flight tasks."""
        down = state.downstream
        if down is None:
            queued = self._sink_bytes_fn()
        else:
            target, branch = down
            queued = (target.branch_in_bytes.get(branch, 0)
                      if branch is not None else target.in_bytes)
        return queued + state.op.num_active_tasks() \
            * self._est_task_bytes(state)

    def mem_usage(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for s in self._launchers:  # diagnostic view; names may repeat
            out[s.name] = out.get(s.name, 0) + self._mem_used(s)
        return out

    def can_launch(self, state: OpState) -> bool:
        op = state.op
        if isinstance(op, AllToAllOperator):
            return True  # barrier op: runs once, driver-side
        actives = [s.op.num_active_tasks() for s in self._launchers]
        if sum(actives) >= self.budget:
            return False  # absolute cap — borrows never exceed the budget
        if op.num_active_tasks() >= self._reserved:
            shared_used = sum(max(0, a - self._reserved) for a in actives)
            if shared_used >= self._shared_pool:
                return False
        # Byte budget: would this launch push the op past its memory
        # allowance (reserved share, then the shared byte pool)?
        est = self._est_task_bytes(state)
        # Keyed by OpState IDENTITY: op names are not unique (every
        # union branch is "read->map"), and a name collision would let
        # same-named ops alias one ledger entry and overrun the budget.
        used = {id(s): self._mem_used(s) for s in self._launchers}
        total = sum(used.values())
        self.peak_mem_used = max(self.peak_mem_used, total)
        mine = used.get(id(state), 0)
        if mine + est > self._mem_reserved:
            # Progress guarantee: an op with NOTHING in flight and
            # nothing queued may always launch one task, even when a
            # single task's estimate exceeds its whole allowance —
            # otherwise an oversized block (or a budget below the cold
            # estimate) would wedge the pipeline forever.
            if op.num_active_tasks() == 0 and mine == 0:
                return True
            mem_shared_used = sum(max(0, u - self._mem_reserved)
                                  for u in used.values())
            if mem_shared_used + est > self._mem_shared:
                return False
        return True

    def output_blocked(self, state: OpState, sink_queue_len: int) -> bool:
        down = state.downstream
        if down is None:
            # Sink edge: bounded by the executor's output buffer (the
            # pull-driven consumer usually keeps this at ~0).
            return sink_queue_len >= self.edge_queue_cap
        target, branch = down
        if branch is not None and isinstance(target.op, ConcatOperator):
            queued = len(target.op._branch_queues[branch])
        else:
            queued = target.op.num_queued_inputs()
        return queued >= self.edge_queue_cap


class StreamingExecutor:
    """Executes a topology (list of OpStates in topological order, the
    last being the sink) as a pull-driven block-ref iterator."""

    def __init__(self, states: List[OpState],
                 task_budget: int = DEFAULT_TASK_BUDGET,
                 edge_queue_cap: int = DEFAULT_EDGE_QUEUE_CAP,
                 memory_budget: Optional[int] = None):
        self._states = states
        self._sink = states[-1]
        assert self._sink.downstream is None
        self._rm = ResourceManager(states, task_budget, edge_queue_cap,
                                   memory_budget)
        self._out_queue: deque = deque()
        self._out_bytes = 0
        self._out_sizes: deque = deque()
        self._rm._sink_bytes_fn = lambda: self._out_bytes
        self._started = False
        self._shut = False

    # -- public ---------------------------------------------------------
    def run(self) -> Iterator[Any]:
        try:
            while True:
                ref = self._next_output()
                if ref is _DONE:
                    return
                yield ref
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        if self._shut:
            return
        self._shut = True
        for s in self._states:
            try:
                s.op.shutdown()
            except Exception:
                logger.debug("shutdown of %s failed", s.name, exc_info=True)

    def metrics(self) -> Dict[str, Any]:
        return {s.name: s.op.metrics for s in self._states}

    # -- internals ------------------------------------------------------
    def _pop_output(self):
        self._out_bytes -= self._out_sizes.popleft() if self._out_sizes \
            else 0
        return self._out_queue.popleft()

    def _next_output(self):
        if not self._started:
            self._started = True
            for s in self._states:
                s.op.start()
        while True:
            if self._out_queue:
                return self._pop_output()
            progressed = self._step()
            if self._out_queue:
                return self._pop_output()
            if self._all_done():
                return _DONE
            if not progressed:
                self._wait_for_progress()

    def _step(self) -> bool:
        """One scheduling pass: harvest + route + dispatch. Returns True
        if anything moved."""
        progressed = False

        # 1. Harvest completions and route blocks downstream, sink-first
        #    (freeing downstream capacity before upstream produces more).
        for s in reversed(self._states):
            outs = s.op.poll()
            if outs:
                progressed = True
                for ref in outs:
                    self._route(s, ref)
            # Propagate upstream-exhaustion exactly once.
            if s.op.completed() and not s.done_notified:
                s.done_notified = True
                progressed = True
                self._notify_done(s)

        # 2. Dispatch: pick ops that can run, closest-to-sink first.
        for s in reversed(self._states):
            while (s.op.can_dispatch()
                   and self._rm.can_launch(s)
                   and not self._rm.output_blocked(s, len(self._out_queue))):
                before = s.op.num_queued_inputs()
                if not s.op.dispatch():
                    break
                # The op consumed inputs: retire their tracked sizes
                # (launcher ops pop exactly one per dispatch; barrier
                # ops drain in bulk inside poll and resync below).
                consumed = before - s.op.num_queued_inputs()
                for _ in range(consumed):
                    if s.in_sizes:
                        s.in_bytes -= s.in_sizes.popleft()
                progressed = True
        # Non-launcher ops (AllToAll/Concat) consume inputs inside
        # poll(): resync their byte ledgers to the surviving queues.
        for s in self._states:
            if s.in_sizes and isinstance(s.op, AllToAllOperator):
                q = s.op.num_queued_inputs()
                while len(s.in_sizes) > q:
                    s.in_bytes -= s.in_sizes.popleft()
            if s.branch_in_sizes and isinstance(s.op, ConcatOperator):
                for b, sizes in s.branch_in_sizes.items():
                    q = len(s.op._branch_queues[b])
                    while len(sizes) > q:
                        s.branch_in_bytes[b] -= sizes.popleft()
        return progressed

    @staticmethod
    def _size_of(ref: Any) -> int:
        """Byte size of a block ref from the owner's ledger (0 for
        non-ref items such as pickled read callables)."""
        try:
            from ray_tpu.core.ref import ObjectRef, get_core_worker
            if not isinstance(ref, ObjectRef):
                return 0
            e = get_core_worker().objects.get(ref.binary())
            return int(e.size or 0) if e is not None else 0
        except Exception:
            return 0

    def _route(self, s: OpState, ref: Any) -> None:
        size = self._size_of(ref)
        s.op.metrics.bytes_out_estimate += size
        down = s.downstream
        if down is None:
            self._out_queue.append(ref)
            self._out_sizes.append(size)
            self._out_bytes += size
            return
        target, branch = down
        if branch is not None:
            assert isinstance(target.op, ConcatOperator)
            target.op.add_branch_input(branch, ref)
            target.branch_in_bytes[branch] = \
                target.branch_in_bytes.get(branch, 0) + size
            target.branch_in_sizes.setdefault(branch, deque()).append(size)
        else:
            target.op.add_input(ref)
            target.in_sizes.append(size)
            target.in_bytes += size

    def _notify_done(self, s: OpState) -> None:
        down = s.downstream
        if down is None:
            return
        target, branch = down
        if branch is not None:
            assert isinstance(target.op, ConcatOperator)
            target.op.branch_done(branch)
        else:
            # Multi-upstream non-concat target: done only when ALL
            # upstreams are done.
            if all(u.done_notified for u in target.upstreams):
                target.op.all_inputs_done()

    def _all_done(self) -> bool:
        return not self._out_queue \
            and all(s.op.completed() for s in self._states)

    def _wait_for_progress(self, timeout: float = 0.05) -> None:
        """Nothing moved and nothing ready: park on the busiest op."""
        for s in reversed(self._states):
            if s.op.num_active_tasks():
                s.op.wait_any(timeout)
                return
        import time
        time.sleep(0.005)


class _Done:
    pass


_DONE = _Done()


def execute_topology(states: List[OpState],
                     task_budget: int = DEFAULT_TASK_BUDGET,
                     edge_queue_cap: int = DEFAULT_EDGE_QUEUE_CAP
                     ) -> Iterator[Any]:
    ex = StreamingExecutor(states, task_budget, edge_queue_cap)
    return ex.run()
