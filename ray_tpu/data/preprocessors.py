"""Dataset preprocessors: fit statistics once, transform as a streamed
map stage.

Analogue of the reference's preprocessor layer (reference:
python/ray/data/preprocessor.py Preprocessor.fit/transform +
python/ray/data/preprocessors/{scaler.py,encoder.py,concatenator.py,
chain.py}). TPU-first shape: `fit` aggregates per-block partial
statistics THROUGH the streaming executor (map_batches emits one small
stats row per block; the driver reduces them), and `transform` is a
plain map_batches stage, so fitted pipelines compose with sharding and
`iter_jax_batches` like any other dataset op.

    from ray_tpu.data.preprocessors import StandardScaler, Chain
    prep = Chain(StandardScaler(["x"]), Concatenator(["x", "y"], "f"))
    prep.fit(train_ds)
    model_input = prep.transform(eval_ds)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Preprocessor:
    """fit(ds) learns state; transform(ds) applies it lazily."""

    _fitted = False

    # -- subclass hooks -------------------------------------------------
    def _aggregate(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Per-block partial statistics (runs inside a task)."""
        raise NotImplementedError

    def _reduce(self, partials: List[Dict[str, Any]]) -> None:
        """Combine partials into fitted state (runs on the driver)."""
        raise NotImplementedError

    def _transform_batch(self, batch: Dict[str, np.ndarray]
                         ) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    # -- public ---------------------------------------------------------
    def fit(self, ds) -> "Preprocessor":
        agg = self._aggregate

        def per_block(batch):
            return {"__stats__": np.asarray([agg(batch)], dtype=object)}

        partials = [row["__stats__"] for row in
                    ds.map_batches(per_block).take_all()]
        self._reduce(partials)
        self._fitted = True
        return self

    def transform(self, ds):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} is not fitted")
        return ds.map_batches(self._transform_batch)

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform_batch(self, batch: Dict[str, np.ndarray]
                        ) -> Dict[str, np.ndarray]:
        """Apply to ONE in-memory batch (serving-time path; reference:
        Preprocessor.transform_batch)."""
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} is not fitted")
        return self._transform_batch(batch)

    def _needs_fit(self) -> bool:
        return True


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference: preprocessors/scaler.py
    StandardScaler — same one-pass sum/sum-of-squares reduction)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _aggregate(self, batch):
        out = {}
        for c in self.columns:
            v = np.asarray(batch[c], dtype=np.float64)
            out[c] = (v.size, float(v.sum()), float((v * v).sum()))
        return out

    def _reduce(self, partials):
        for c in self.columns:
            n = sum(p[c][0] for p in partials)
            s = sum(p[c][1] for p in partials)
            ss = sum(p[c][2] for p in partials)
            mean = s / max(1, n)
            var = max(0.0, ss / max(1, n) - mean * mean)
            self.stats_[c] = (mean, float(np.sqrt(var)) or 1.0)

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            mean, std = self.stats_[c]
            out[c] = (np.asarray(batch[c], np.float64) - mean) / (std or 1.0)
        return out


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column (reference: scaler.py
    MinMaxScaler)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _aggregate(self, batch):
        return {c: (float(np.min(batch[c])), float(np.max(batch[c])))
                for c in self.columns}

    def _reduce(self, partials):
        for c in self.columns:
            lo = min(p[c][0] for p in partials)
            hi = max(p[c][1] for p in partials)
            self.stats_[c] = (lo, hi)

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            lo, hi = self.stats_[c]
            span = (hi - lo) or 1.0
            out[c] = (np.asarray(batch[c], np.float64) - lo) / span
        return out


class LabelEncoder(Preprocessor):
    """Categorical column -> dense int codes, deterministic (sorted)
    label order (reference: preprocessors/encoder.py LabelEncoder)."""

    def __init__(self, column: str):
        self.column = column
        self.classes_: List[Any] = []
        self._index: Dict[Any, int] = {}

    def _aggregate(self, batch):
        return {"labels": sorted({v if not isinstance(v, np.generic)
                                  else v.item()
                                  for v in np.asarray(batch[self.column])})}

    def _reduce(self, partials):
        seen = set()
        for p in partials:
            seen.update(p["labels"])
        self.classes_ = sorted(seen)
        self._index = {v: i for i, v in enumerate(self.classes_)}

    def _transform_batch(self, batch):
        out = dict(batch)
        idx = self._index
        vals = np.asarray(batch[self.column])
        codes = np.empty(len(vals), np.int64)
        for i, v in enumerate(vals):
            v = v.item() if isinstance(v, np.generic) else v
            code = idx.get(v)
            if code is None:
                raise ValueError(
                    f"LabelEncoder({self.column!r}): value {v!r} was not "
                    f"seen during fit (known: {self.classes_[:10]}...)")
            codes[i] = code
        out[self.column] = codes
        return out


class Concatenator(Preprocessor):
    """Stack columns into one feature matrix column (reference:
    preprocessors/concatenator.py) — the standard last step before
    `iter_jax_batches` hands a dense array to the model. Stateless."""

    def __init__(self, columns: List[str], output_column: str = "features",
                 *, dtype=np.float32, drop_inputs: bool = True):
        self.columns = list(columns)
        self.output_column = output_column
        self.dtype = dtype
        self.drop_inputs = drop_inputs
        self._fitted = True

    def _needs_fit(self) -> bool:
        return False

    def fit(self, ds):
        return self

    def _transform_batch(self, batch):
        cols = []
        for c in self.columns:
            v = np.asarray(batch[c], dtype=self.dtype)
            cols.append(v[:, None] if v.ndim == 1 else
                        v.reshape(len(v), -1))
        out = {k: v for k, v in batch.items()
               if not (self.drop_inputs and k in self.columns)}
        out[self.output_column] = np.concatenate(cols, axis=1)
        return out


class Chain(Preprocessor):
    """Sequential composition; fit() fits each stage on the output of
    the previous stages (reference: preprocessors/chain.py)."""

    def __init__(self, *stages: Preprocessor):
        self.stages = list(stages)
        self._fitted = True  # delegated to stages

    def _needs_fit(self) -> bool:
        return False

    def fit(self, ds):
        cur = ds
        for st in self.stages:
            st.fit(cur)
            cur = st.transform(cur)
        return self

    def transform(self, ds):
        for st in self.stages:
            ds = st.transform(ds)
        return ds

    def transform_batch(self, batch):
        for st in self.stages:
            batch = st.transform_batch(batch)
        return batch
