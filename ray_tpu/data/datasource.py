"""Read tasks — lazy per-source block producers.

Analogue of the reference's datasource layer (reference:
python/ray/data/_internal/datasource/ — parquet/csv/json/range readers
produce ReadTasks; python/ray/data/datasource/datasource.py ReadTask).
Each read task is a zero-arg callable yielding blocks, executed inside one
streaming source task by the executor; file formats ride pyarrow.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

DEFAULT_ROWS_PER_BLOCK = 64 * 1024


def _is_url(p: str) -> bool:
    return "://" in p


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if _is_url(p):
            # Remote paths: fsspec expands globs on filesystem-like
            # protocols; http(s) URLs pass through verbatim — a '?'
            # there is a query string, not a glob (reference:
            # datasource paths ride pyarrow.fs/fsspec).
            proto = p.split("://", 1)[0].lower()
            if proto in ("http", "https"):
                out.append(p)  # a '?' here is a query string, not a glob
            elif any(ch in p for ch in "*?["):
                import fsspec
                fs, _ = fsspec.core.url_to_fs(p)
                out.extend(f"{proto}://{m}" for m in sorted(fs.glob(p)))
            elif p.endswith("/"):
                # Explicit remote directory prefix (s3://bucket/table/):
                # expand like the local os.walk branch. Only the trailing
                # slash triggers the remote listing — probing isdir on
                # every plain file URL would cost one network round-trip
                # per path at dataset-construction time.
                import fsspec
                fs, root = fsspec.core.url_to_fs(p)
                out.extend(f"{proto}://{m}" for m in sorted(fs.find(root)))
            else:
                out.append(p)
        elif os.path.isdir(p):
            for root, _, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if not f.startswith("."))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return out


def _open_any(path: str, mode: str = "rb"):
    """Open local paths with open(); URLs (s3://, gs://, http://, ...)
    through fsspec — every file-based reader accepts either."""
    if _is_url(path):
        import fsspec
        return fsspec.open(path, mode).open()
    if "b" in mode:
        return open(path, mode)
    return open(path, mode, encoding="utf-8")


def range_read_tasks(n: int, num_blocks: Optional[int] = None
                     ) -> List[Callable[[], Iterator[Any]]]:
    num_blocks = num_blocks or max(1, min(16, n // DEFAULT_ROWS_PER_BLOCK
                                          or 1))
    per = (n + num_blocks - 1) // num_blocks
    tasks = []
    for b in range(num_blocks):
        lo, hi = b * per, min(n, (b + 1) * per)
        if lo >= hi:
            break

        def read(lo=lo, hi=hi):
            yield {"id": np.arange(lo, hi, dtype=np.int64)}

        tasks.append(read)
    return tasks


def items_read_tasks(items: List[Any], num_blocks: int = 1):
    num_blocks = max(1, min(num_blocks, len(items) or 1))
    per = (len(items) + num_blocks - 1) // num_blocks
    tasks = []
    for b in range(num_blocks):
        chunk = items[b * per:(b + 1) * per]
        if not chunk:
            break

        def read(chunk=chunk):
            yield list(chunk)

        tasks.append(read)
    return tasks


def numpy_read_tasks(batch: Dict[str, np.ndarray],
                     num_blocks: int = 1):
    n = len(next(iter(batch.values())))
    num_blocks = max(1, min(num_blocks, n))
    per = (n + num_blocks - 1) // num_blocks
    tasks = []
    for b in range(num_blocks):
        lo, hi = b * per, min(n, (b + 1) * per)
        if lo >= hi:
            break
        chunk = {k: v[lo:hi] for k, v in batch.items()}

        def read(chunk=chunk):
            yield chunk

        tasks.append(read)
    return tasks


def parquet_read_tasks(paths, columns: Optional[List[str]] = None):
    """One read task per file; row groups stream as separate blocks
    (reference: _internal/datasource/parquet_datasource.py splits by row
    group for memory-bounded streaming)."""
    files = _expand_paths(paths)
    tasks = []
    for path in files:
        def read(path=path, columns=columns):
            import pyarrow.parquet as pq
            f = pq.ParquetFile(_open_any(path) if _is_url(path) else path)
            for rg in range(f.num_row_groups):
                yield f.read_row_group(rg, columns=columns)

        tasks.append(read)
    return tasks


def csv_read_tasks(paths, **read_options):
    files = _expand_paths(paths)
    tasks = []
    for path in files:
        def read(path=path):
            import pyarrow.csv as pacsv
            yield pacsv.read_csv(_open_any(path) if _is_url(path)
                                 else path)

        tasks.append(read)
    return tasks


def json_read_tasks(paths):
    files = _expand_paths(paths)
    tasks = []
    for path in files:
        def read(path=path):
            import pyarrow.json as pajson
            yield pajson.read_json(_open_any(path) if _is_url(path)
                                   else path)

        tasks.append(read)
    return tasks


def text_read_tasks(paths, *, encoding: str = "utf-8"):
    """One task per file; each block is {"text": lines} (reference:
    _internal/datasource/text_datasource.py)."""
    files = _expand_paths(paths)
    tasks = []
    for path in files:
        def read(path=path):
            with _open_any(path, "rb") as f:
                lines = f.read().decode(encoding).splitlines()
            yield {"text": np.asarray(lines, dtype=object)}

        tasks.append(read)
    return tasks


def binary_read_tasks(paths, *, include_paths: bool = False):
    """One task per file; blocks are {"bytes": [payload]} (+"path")
    (reference: _internal/datasource/binary_datasource.py)."""
    files = _expand_paths(paths)
    tasks = []
    for path in files:
        def read(path=path):
            with _open_any(path, "rb") as f:
                payload = f.read()
            block = {"bytes": np.asarray([payload], dtype=object)}
            if include_paths:
                block["path"] = np.asarray([path], dtype=object)
            yield block

        tasks.append(read)
    return tasks


def image_read_tasks(paths, *, size=None, mode: Optional[str] = None):
    """One task per image file; blocks are {"image": [H, W, C] uint8}
    (reference: _internal/datasource/image_datasource.py — PIL decode,
    optional resize/convert; decoding runs IN the read task, so it
    parallelizes across the executor's task budget)."""
    files = _expand_paths(paths)
    tasks = []
    for path in files:
        def read(path=path):
            from PIL import Image
            img = Image.open(_open_any(path) if _is_url(path) else path)
            if mode is not None:
                img = img.convert(mode)
            if size is not None:
                img = img.resize(tuple(size))
            yield {"image": np.asarray(img)[None]}

        tasks.append(read)
    return tasks


def _decode_wds_field(ext: str, payload: bytes):
    """Default webdataset field decoders by extension (reference:
    _internal/datasource/webdataset_datasource.py default_decoder)."""
    if ext in ("txt", "text"):
        return payload.decode("utf-8")
    if ext == "json":
        import json as _json
        return _json.loads(payload)
    if ext in ("cls", "cls2", "index"):
        return int(payload.decode("utf-8").strip())
    if ext in ("jpg", "jpeg", "png", "ppm", "pgm", "pbm", "bmp"):
        import io as _io

        from PIL import Image
        return np.asarray(Image.open(_io.BytesIO(payload)))
    if ext in ("npy",):
        import io as _io
        return np.load(_io.BytesIO(payload), allow_pickle=False)
    return payload  # unknown extension: raw bytes


def webdataset_read_tasks(paths, *, rows_per_block: int = 256,
                          decode: bool = True):
    """Stream samples out of webdataset-convention tar shards: files
    sharing a dotted key prefix form one sample ({"__key__", ext: value})
    (reference: _internal/datasource/webdataset_datasource.py). One task
    per shard; samples batch into blocks of `rows_per_block`."""
    files = _expand_paths(paths)
    tasks = []
    for path in files:
        def read(path=path):
            import tarfile

            def flush(rows):
                cols = sorted({k for r in rows for k in r})
                return {c: np.asarray([r.get(c) for r in rows],
                                      dtype=object) for c in cols}

            rows: List[dict] = []
            sample: dict = {}
            key = None
            with _open_any(path, "rb") as f, \
                    tarfile.open(fileobj=f, mode="r|*") as tar:
                for member in tar:
                    if not member.isfile():
                        continue
                    # Key on the FULL path minus extension: shards that
                    # bundle directories (train/0001.jpg, val/0001.jpg)
                    # must not merge same-basename samples.
                    name = member.name
                    base = os.path.basename(name)
                    _, _, ext = base.partition(".")
                    stem = name[: len(name) - len(ext) - 1] if ext \
                        else name
                    if key is not None and stem != key and sample:
                        rows.append(sample)
                        sample = {}
                        if len(rows) >= rows_per_block:
                            yield flush(rows)
                            rows = []
                    key = stem
                    payload = tar.extractfile(member).read()
                    sample["__key__"] = stem
                    sample[ext] = (_decode_wds_field(ext.lower(), payload)
                                   if decode else payload)
            if sample:
                rows.append(sample)
            if rows:
                yield flush(rows)

        tasks.append(read)
    return tasks


def lance_read_tasks(uri, columns: Optional[List[str]] = None):
    """Lance dataset fragments as read tasks (reference:
    _internal/datasource/lance_datasource.py). Gated on the optional
    `lance` package — the seam matches the reference; environments
    without lance get a clear error instead of a silent fallback."""
    try:
        import lance  # type: ignore
    except ImportError as e:
        raise ImportError(
            "read_lance requires the 'lance' package (pip install "
            "pylance); not bundled in this environment") from e
    ds = lance.dataset(uri)
    tasks = []
    for frag in ds.get_fragments():
        def read(frag=frag, columns=columns):
            for batch in frag.to_batches(columns=columns):
                import pyarrow as pa
                yield pa.Table.from_batches([batch])

        tasks.append(read)
    return tasks


# ---------------------------------------------------------------------------
# write tasks (reference: Dataset.write_parquet/_csv/_json ->
# _internal/datasource/*_datasink.py — one output file per block)
# ---------------------------------------------------------------------------

def write_block(block, path: str, file_format: str) -> str:
    """Write ONE block as one file (runs inside a task)."""
    from ray_tpu.data.block import BlockAccessor

    acc = BlockAccessor(block)
    if file_format in ("parquet", "csv"):
        import pyarrow as pa
        table = acc.to_arrow() if not isinstance(block, pa.Table) \
            else block
        if file_format == "parquet":
            import pyarrow.parquet as pq
            pq.write_table(table, path)
        else:
            import pyarrow.csv as pacsv
            pacsv.write_csv(table, path)
    elif file_format == "json":
        import json as _json
        cols = acc.to_numpy_batch()
        names = list(cols)
        with open(path, "w") as f:
            for i in range(acc.num_rows()):
                row = {k: _to_jsonable(cols[k][i]) for k in names}
                f.write(_json.dumps(row) + "\n")
    else:
        raise ValueError(f"unknown write format {file_format!r}")
    return path


def _to_jsonable(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (bytes, bytearray)):
        import base64
        return base64.b64encode(bytes(v)).decode()  # JSON-safe binary
    return v
