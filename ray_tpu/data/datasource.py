"""Read tasks — lazy per-source block producers.

Analogue of the reference's datasource layer (reference:
python/ray/data/_internal/datasource/ — parquet/csv/json/range readers
produce ReadTasks; python/ray/data/datasource/datasource.py ReadTask).
Each read task is a zero-arg callable yielding blocks, executed inside one
streaming source task by the executor; file formats ride pyarrow.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

DEFAULT_ROWS_PER_BLOCK = 64 * 1024


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if not f.startswith("."))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return out


def range_read_tasks(n: int, num_blocks: Optional[int] = None
                     ) -> List[Callable[[], Iterator[Any]]]:
    num_blocks = num_blocks or max(1, min(16, n // DEFAULT_ROWS_PER_BLOCK
                                          or 1))
    per = (n + num_blocks - 1) // num_blocks
    tasks = []
    for b in range(num_blocks):
        lo, hi = b * per, min(n, (b + 1) * per)
        if lo >= hi:
            break

        def read(lo=lo, hi=hi):
            yield {"id": np.arange(lo, hi, dtype=np.int64)}

        tasks.append(read)
    return tasks


def items_read_tasks(items: List[Any], num_blocks: int = 1):
    num_blocks = max(1, min(num_blocks, len(items) or 1))
    per = (len(items) + num_blocks - 1) // num_blocks
    tasks = []
    for b in range(num_blocks):
        chunk = items[b * per:(b + 1) * per]
        if not chunk:
            break

        def read(chunk=chunk):
            yield list(chunk)

        tasks.append(read)
    return tasks


def numpy_read_tasks(batch: Dict[str, np.ndarray],
                     num_blocks: int = 1):
    n = len(next(iter(batch.values())))
    num_blocks = max(1, min(num_blocks, n))
    per = (n + num_blocks - 1) // num_blocks
    tasks = []
    for b in range(num_blocks):
        lo, hi = b * per, min(n, (b + 1) * per)
        if lo >= hi:
            break
        chunk = {k: v[lo:hi] for k, v in batch.items()}

        def read(chunk=chunk):
            yield chunk

        tasks.append(read)
    return tasks


def parquet_read_tasks(paths, columns: Optional[List[str]] = None):
    """One read task per file; row groups stream as separate blocks
    (reference: _internal/datasource/parquet_datasource.py splits by row
    group for memory-bounded streaming)."""
    files = _expand_paths(paths)
    tasks = []
    for path in files:
        def read(path=path, columns=columns):
            import pyarrow.parquet as pq
            f = pq.ParquetFile(path)
            for rg in range(f.num_row_groups):
                yield f.read_row_group(rg, columns=columns)

        tasks.append(read)
    return tasks


def csv_read_tasks(paths, **read_options):
    files = _expand_paths(paths)
    tasks = []
    for path in files:
        def read(path=path):
            import pyarrow.csv as pacsv
            yield pacsv.read_csv(path)

        tasks.append(read)
    return tasks


def json_read_tasks(paths):
    files = _expand_paths(paths)
    tasks = []
    for path in files:
        def read(path=path):
            import pyarrow.json as pajson
            yield pajson.read_json(path)

        tasks.append(read)
    return tasks


def text_read_tasks(paths, *, encoding: str = "utf-8"):
    """One task per file; each block is {"text": lines} (reference:
    _internal/datasource/text_datasource.py)."""
    files = _expand_paths(paths)
    tasks = []
    for path in files:
        def read(path=path):
            with open(path, encoding=encoding) as f:
                lines = f.read().splitlines()
            yield {"text": np.asarray(lines, dtype=object)}

        tasks.append(read)
    return tasks


def binary_read_tasks(paths, *, include_paths: bool = False):
    """One task per file; blocks are {"bytes": [payload]} (+"path")
    (reference: _internal/datasource/binary_datasource.py)."""
    files = _expand_paths(paths)
    tasks = []
    for path in files:
        def read(path=path):
            with open(path, "rb") as f:
                payload = f.read()
            block = {"bytes": np.asarray([payload], dtype=object)}
            if include_paths:
                block["path"] = np.asarray([path], dtype=object)
            yield block

        tasks.append(read)
    return tasks


def image_read_tasks(paths, *, size=None, mode: Optional[str] = None):
    """One task per image file; blocks are {"image": [H, W, C] uint8}
    (reference: _internal/datasource/image_datasource.py — PIL decode,
    optional resize/convert; decoding runs IN the read task, so it
    parallelizes across the executor's task budget)."""
    files = _expand_paths(paths)
    tasks = []
    for path in files:
        def read(path=path):
            from PIL import Image
            img = Image.open(path)
            if mode is not None:
                img = img.convert(mode)
            if size is not None:
                img = img.resize(tuple(size))
            yield {"image": np.asarray(img)[None]}

        tasks.append(read)
    return tasks


# ---------------------------------------------------------------------------
# write tasks (reference: Dataset.write_parquet/_csv/_json ->
# _internal/datasource/*_datasink.py — one output file per block)
# ---------------------------------------------------------------------------

def write_block(block, path: str, file_format: str) -> str:
    """Write ONE block as one file (runs inside a task)."""
    from ray_tpu.data.block import BlockAccessor

    acc = BlockAccessor(block)
    if file_format in ("parquet", "csv"):
        import pyarrow as pa
        table = acc.to_arrow() if not isinstance(block, pa.Table) \
            else block
        if file_format == "parquet":
            import pyarrow.parquet as pq
            pq.write_table(table, path)
        else:
            import pyarrow.csv as pacsv
            pacsv.write_csv(table, path)
    elif file_format == "json":
        import json as _json
        cols = acc.to_numpy_batch()
        names = list(cols)
        with open(path, "w") as f:
            for i in range(acc.num_rows()):
                row = {k: _to_jsonable(cols[k][i]) for k in names}
                f.write(_json.dumps(row) + "\n")
    else:
        raise ValueError(f"unknown write format {file_format!r}")
    return path


def _to_jsonable(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (bytes, bytearray)):
        import base64
        return base64.b64encode(bytes(v)).decode()  # JSON-safe binary
    return v
