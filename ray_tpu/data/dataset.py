"""Dataset — the lazy distributed data pipeline.

Analogue of the reference's Dataset (reference: python/ray/data/dataset.py —
map:276, map_batches:457, streaming_split:1826, iter_batches:4973,
iter_torch_batches:5044 → here iter_jax_batches) over a LOGICAL PLAN that a
small planner lowers to the operator-graph streaming executor (reference:
_internal/logical/optimizers.py fusion rule + planner/planner.py →
execution/streaming_executor.py). Consecutive row/batch transforms fuse
into one map node (the fusion rule applied eagerly at plan-build time);
actor-pool maps, all-to-all exchanges (shuffle/sort/repartition), and
unions each lower to their own physical operator.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu

_py_range = range  # the public range() below shadows the builtin
from ray_tpu.data import datasource as _ds
from ray_tpu.data.block import Block, BlockAccessor, concat_blocks
from ray_tpu.data.iterator import (iter_batches_from_refs,
                                   iter_jax_batches_from_refs)


# ---------------------------------------------------------------------------
# logical plan nodes (reference: _internal/logical/operators/*)
# ---------------------------------------------------------------------------

class _Read:
    """Source blocks: materialized ObjectRefs or zero-arg read callables."""
    __slots__ = ("sources",)

    def __init__(self, sources: List[Any]):
        self.sources = sources


class _Fused:
    """A fused chain of block -> Iterator[block] stages (the reference's
    map-fusion rule output)."""
    __slots__ = ("stages",)

    def __init__(self, stages: List[Callable]):
        self.stages = stages


class _ActorMapNode:
    """map_batches on a pool of long-lived actors."""
    __slots__ = ("fn", "batch_size", "batch_format", "concurrency",
                 "ctor_args", "fn_kwargs", "resources")

    def __init__(self, fn, batch_size, batch_format, concurrency,
                 ctor_args, fn_kwargs, resources=None):
        self.fn = fn
        self.batch_size = batch_size
        self.batch_format = batch_format
        self.concurrency = concurrency
        self.ctor_args = ctor_args
        self.fn_kwargs = fn_kwargs
        self.resources = resources


class _ExchangeNode:
    """All-to-all barrier: fn(list of input refs) -> list of output refs
    (repartition / random_shuffle / sort lower to this)."""
    __slots__ = ("fn", "name", "num_blocks_hint")

    def __init__(self, fn, name: str, num_blocks_hint: Optional[int] = None):
        self.fn = fn
        self.name = name
        self.num_blocks_hint = num_blocks_hint


class _UnionNode:
    """Ordered concatenation of several sub-plans."""
    __slots__ = ("parts",)

    def __init__(self, parts: List[List[Any]]):
        self.parts = parts


class Dataset:
    def __init__(self, sources: List[Any], stages: Optional[List] = None,
                 name: str = "dataset"):
        self._plan: List[Any] = [_Read(list(sources))]
        if stages:
            self._plan.append(_Fused(list(stages)))
        self._name = name

    @classmethod
    def _from_plan(cls, plan: List[Any], name: str) -> "Dataset":
        ds = cls.__new__(cls)
        ds._plan = plan
        ds._name = name
        return ds

    @property
    def _sources(self) -> List[Any]:
        """Source list of a plain (un-transformed) dataset — the
        materialized-refs contract shuffle.py relies on."""
        assert len(self._plan) == 1 and isinstance(self._plan[0], _Read), \
            f"_sources on a transformed dataset: {self._plan}"
        return self._plan[0].sources

    # ------------------------------------------------------------------
    # transforms (lazy; each appends to the logical plan)
    # ------------------------------------------------------------------
    def _with_stage(self, stage, name: str) -> "Dataset":
        plan = list(self._plan)
        if plan and isinstance(plan[-1], _Fused):
            plan[-1] = _Fused(plan[-1].stages + [stage])
        else:
            plan.append(_Fused([stage]))
        return Dataset._from_plan(plan, f"{self._name}->{name}")

    def _with_exchange(self, fn, name: str,
                       num_blocks_hint: Optional[int] = None) -> "Dataset":
        plan = list(self._plan) + [_ExchangeNode(fn, name, num_blocks_hint)]
        return Dataset._from_plan(plan, f"{self._name}->{name}")

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy",
                    fn_kwargs: Optional[dict] = None,
                    concurrency: Optional[int] = None,
                    fn_constructor_args: tuple = (),
                    resources: Optional[dict] = None) -> "Dataset":
        """Apply fn to batches (reference: dataset.py:457). With
        batch_size=None each block is one batch; otherwise blocks are
        re-chunked to batch_size rows (within a block; a trailing short
        batch per block is possible, as with the reference's default
        shuffle=False zero-copy path).

        concurrency=N runs the transform on a pool of N ACTORS as its own
        physical operator (reference: ActorPoolMapOperator /
        map_batches(CallableClass, concurrency=N)) — pass a callable
        CLASS to construct once per actor (model loading etc.) and call
        per batch."""
        if concurrency is not None:
            if concurrency < 1:
                raise ValueError(f"concurrency must be >= 1, "
                                 f"got {concurrency}")
            plan = list(self._plan) + [_ActorMapNode(
                fn, batch_size, batch_format, concurrency,
                fn_constructor_args, fn_kwargs or {}, resources)]
            return Dataset._from_plan(
                plan, f"{self._name}->map_batches(actors)")
        if isinstance(fn, type) or fn_constructor_args:
            # Fused stages call fn(batch); a callable CLASS would be
            # constructed per batch WITH the batch as its ctor arg.
            raise ValueError(
                "callable classes / fn_constructor_args require "
                "concurrency=N (the actor-compute strategy)")
        kwargs = fn_kwargs or {}

        def stage(block):
            yield from _map_block_batches(block, fn, batch_size,
                                          batch_format, kwargs)

        return self._with_stage(stage, "map_batches")

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        def stage(block):
            yield [fn(row) for row in BlockAccessor(block).to_rows()]

        return self._with_stage(stage, "map")

    def flat_map(self, fn: Callable[[Any], List[Any]]) -> "Dataset":
        def stage(block):
            out: List[Any] = []
            for row in BlockAccessor(block).to_rows():
                out.extend(fn(row))
            yield out

        return self._with_stage(stage, "flat_map")

    def filter(self, pred: Callable[[Any], bool]) -> "Dataset":
        def stage(block):
            acc = BlockAccessor(block)
            if isinstance(block, dict):  # columnar fast path
                rows = acc.to_rows()
                keep = [r for r in rows if pred(r)]
                if keep:
                    yield {k: np.asarray([r[k] for r in keep])
                           for k in keep[0]}
                return
            keep = [r for r in acc.to_rows() if pred(r)]
            if keep:
                yield keep

        return self._with_stage(stage, "filter")

    # ------------------------------------------------------------------
    # execution: plan -> operator topology -> streaming executor
    # ------------------------------------------------------------------
    def _build_states(self):
        from ray_tpu.data.operators import (ActorPoolMapOperator,
                                            AllToAllOperator,
                                            ConcatOperator, MapTaskOperator,
                                            SourceOperator)
        from ray_tpu.data.streaming_executor import OpState

        import cloudpickle

        states: List[OpState] = []

        def wire(up: OpState, down: OpState) -> None:
            up.downstream = (down, None)
            down.upstreams.append(up)

        def build_chain(nodes: List[Any]) -> OpState:
            head = nodes[0]
            idx = 1
            if isinstance(head, _Read):
                wire_items = [
                    s if isinstance(s, ray_tpu.ObjectRef)
                    else cloudpickle.dumps(s)
                    for s in head.sources]
                last = OpState(SourceOperator(wire_items))
                states.append(last)
                needs_task = any(not isinstance(s, ray_tpu.ObjectRef)
                                 for s in head.sources)
                if idx < len(nodes) and isinstance(nodes[idx], _Fused):
                    # The fusion payoff: read + every chained transform
                    # in ONE streaming task per source block.
                    mo = OpState(MapTaskOperator(nodes[idx].stages,
                                                 name="read->map"))
                    wire(last, mo)
                    states.append(mo)
                    last = mo
                    idx += 1
                elif needs_task:
                    mo = OpState(MapTaskOperator([], name="read"))
                    wire(last, mo)
                    states.append(mo)
                    last = mo
            elif isinstance(head, _UnionNode):
                cs = OpState(ConcatOperator(len(head.parts)))
                for bi, part in enumerate(head.parts):
                    sink = build_chain(part)
                    sink.downstream = (cs, bi)
                    cs.upstreams.append(sink)
                states.append(cs)
                last = cs
            else:
                raise AssertionError(f"bad plan head {head!r}")

            while idx < len(nodes):
                node = nodes[idx]
                if isinstance(node, _Fused):
                    op = MapTaskOperator(node.stages, name="map")
                elif isinstance(node, _ActorMapNode):
                    op = ActorPoolMapOperator(
                        node.fn, node.ctor_args, node.fn_kwargs,
                        node.batch_size, node.batch_format,
                        node.concurrency, resources=node.resources)
                elif isinstance(node, _ExchangeNode):
                    op = AllToAllOperator(node.fn, name=node.name)
                else:
                    raise AssertionError(f"bad plan node {node!r}")
                st = OpState(op)
                wire(last, st)
                states.append(st)
                last = st
                idx += 1
            return last

        build_chain(self._plan)
        return states

    def iter_block_refs(self, window: Optional[int] = None) -> Iterator[Any]:
        from ray_tpu.data.streaming_executor import (DEFAULT_TASK_BUDGET,
                                                     StreamingExecutor)
        budget = DEFAULT_TASK_BUDGET if window is None else max(1, window)
        ex = StreamingExecutor(self._build_states(), task_budget=budget)
        self._last_executor = ex  # stats() reads the live/last metrics
        return ex.run()

    def materialize(self) -> "Dataset":
        """Execute now; the result holds block refs (reference:
        dataset.py materialize -> MaterializedDataset)."""
        refs = list(self.iter_block_refs())
        return Dataset(refs, [], name=f"{self._name}(materialized)")

    def iter_batches(self, *, batch_size: Optional[int] = None,
                     batch_format: str = "numpy", prefetch_blocks: int = 2,
                     drop_last: bool = False) -> Iterator[Any]:
        return iter_batches_from_refs(
            self.iter_block_refs(), batch_size=batch_size,
            batch_format=batch_format, prefetch_blocks=prefetch_blocks,
            drop_last=drop_last)

    def iter_rows(self) -> Iterator[Any]:
        for batch in self.iter_batches(batch_format="rows"):
            yield from batch

    def iter_jax_batches(self, *, batch_size: Optional[int] = None,
                         sharding: Optional[Any] = None,
                         global_batch: bool = False,
                         prefetch_blocks: int = 2,
                         drop_last: bool = True) -> Iterator[Dict[str, Any]]:
        """Batches as jax.Arrays — the north-star ingest hop (host path is
        zero-copy out of the shm store; device transfer is the only copy)."""
        return iter_jax_batches_from_refs(
            self.iter_block_refs(), batch_size=batch_size,
            sharding=sharding, global_batch=global_batch,
            prefetch_blocks=prefetch_blocks, drop_last=drop_last)

    # ------------------------------------------------------------------
    # consumption helpers
    # ------------------------------------------------------------------
    def take(self, k: int = 20) -> List[Any]:
        out: List[Any] = []
        for batch in self.iter_batches(batch_format="rows"):
            out.extend(batch)
            if len(out) >= k:
                return out[:k]
        return out

    def take_all(self) -> List[Any]:
        out: List[Any] = []
        for batch in self.iter_batches(batch_format="rows"):
            out.extend(batch)
        return out

    def count(self) -> int:
        return sum(BlockAccessor(ray_tpu.get(r)).num_rows()
                   for r in self.iter_block_refs())

    def schema(self) -> Any:
        for ref in self.iter_block_refs(window=1):
            return BlockAccessor(ray_tpu.get(ref)).schema()
        return None

    def num_blocks(self) -> int:
        n = 0
        for node in self._plan:
            if isinstance(node, _Read):
                n = len(node.sources)
            elif isinstance(node, _UnionNode):
                n = sum(Dataset._from_plan(p, "part").num_blocks()
                        for p in node.parts)
            elif isinstance(node, _ExchangeNode) and \
                    node.num_blocks_hint is not None:
                n = node.num_blocks_hint
        return n

    # ------------------------------------------------------------------
    # reorganization (lazy all-to-all exchanges)
    # ------------------------------------------------------------------
    def repartition(self, num_blocks: int) -> "Dataset":
        """Rebalance rows into num_blocks blocks (lazy barrier)."""

        @ray_tpu.remote(num_returns="streaming")
        def _rechunk(refs, n):
            # refs ride inside a list arg so they arrive as refs (borrow-
            # accounted), not pre-resolved values.
            whole = concat_blocks([ray_tpu.get(r) for r in refs])
            yield from _emit_chunks(BlockAccessor(whole), n)

        def exchange(refs: List[Any]) -> List[Any]:
            return list(_rechunk.remote(list(refs), num_blocks))

        return self._with_exchange(exchange, "repartition",
                                   num_blocks_hint=num_blocks)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Global shuffle: permute all rows (lazy barrier; single-task
        permutation — fine at the block counts this framework targets per
        host; the reference's distributed shuffle service is multi-TB
        scale)."""
        n_blocks = max(1, self.num_blocks())

        @ray_tpu.remote(num_returns="streaming")
        def _shuffle(refs, n, seed):
            rng = np.random.RandomState(seed)
            whole = concat_blocks([ray_tpu.get(r) for r in refs])
            acc = BlockAccessor(whole)
            total = acc.num_rows()
            perm = rng.permutation(total)
            if isinstance(whole, dict):
                shuffled: Block = {k: v[perm] for k, v in whole.items()}
            else:
                rows = acc.to_rows()
                shuffled = [rows[i] for i in perm]
            yield from _emit_chunks(BlockAccessor(shuffled), n)

        def exchange(refs: List[Any]) -> List[Any]:
            return list(_shuffle.remote(list(refs), n_blocks, seed))

        return self._with_exchange(exchange, "random_shuffle",
                                   num_blocks_hint=n_blocks)

    def groupby(self, key: str, *,
                num_partitions: Optional[int] = None):
        """Group rows by a column via a distributed hash shuffle
        (reference: dataset.py:2688 groupby -> GroupedData). Aggregations
        and map_groups run one reducer task per partition."""
        from ray_tpu.data.shuffle import GroupedData
        return GroupedData(self, key, num_partitions)

    def join(self, other: "Dataset", on: str, how: str = "inner", *,
             num_partitions: Optional[int] = None) -> "Dataset":
        """Distributed hash join with another dataset (reference:
        data/_internal/execution/operators/join.py; inner/left). Both
        sides co-partition by a process-stable key hash; right-side
        column collisions get a _right suffix."""
        from ray_tpu.data.shuffle import join_datasets
        return join_datasets(self, other, on, how, num_partitions)

    def unique(self, column: str) -> List[Any]:
        """Distinct values of a column (reference: dataset.py unique)."""
        out = self.groupby(column).count().take_all()
        return [r[column] for r in out]

    def union(self, *others: "Dataset") -> "Dataset":
        """Concatenate datasets (reference: dataset.py union). Blocks of
        each input stream in order through a concat operator; transforms
        chained after the union apply to the concatenated stream."""
        parts = [list(self._plan)] + [list(o._plan) for o in others]
        return Dataset._from_plan([_UnionNode(parts)], name="union")

    def sort(self, key: str, *, descending: bool = False) -> "Dataset":
        """Global sort by a column (reference: dataset.py sort), STABLE
        in both directions (lazy barrier; single-task sort — fine at
        per-host block counts; the reference's distributed
        range-partition sort is multi-TB scale)."""
        n_blocks = max(1, self.num_blocks())

        @ray_tpu.remote(num_returns="streaming")
        def _sorted(refs, n, key, descending):
            whole = concat_blocks([ray_tpu.get(r) for r in refs])
            acc = BlockAccessor(whole)
            if isinstance(whole, dict):
                v = whole[key]
                if descending:
                    # Stable descending: argsort the negated RANK codes
                    # (reversing an ascending argsort would reverse ties).
                    _, inv = np.unique(v, return_inverse=True)
                    order = np.argsort(-inv, kind="stable")
                else:
                    order = np.argsort(v, kind="stable")
                out: Block = {k: col[order] for k, col in whole.items()}
            else:
                out = sorted(acc.to_rows(),
                             key=lambda r: r[key], reverse=descending)
            yield from _emit_chunks(BlockAccessor(out), n)

        def exchange(refs: List[Any]) -> List[Any]:
            return list(_sorted.remote(list(refs), n_blocks, key,
                                       descending))

        return self._with_exchange(exchange, "sort",
                                   num_blocks_hint=n_blocks)

    def split(self, n: int) -> List["Dataset"]:
        """Materialize and split into n datasets by whole blocks
        (reference: dataset.py split)."""
        mat = self.materialize()
        refs = mat._sources
        shards: List[List[Any]] = [[] for _ in _py_range(n)]
        for i, r in enumerate(refs):
            shards[i % n].append(r)
        return [Dataset(s, [], name=f"{self._name}(split{i})")
                for i, s in enumerate(shards)]

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List["DataIterator"]:
        """n per-consumer iterators over one shared streaming execution
        (reference: dataset.py:1826 streaming_split + output_splitter
        coordinated by a SplitCoordinator actor)."""
        from ray_tpu.data.split import create_streaming_split
        return create_streaming_split(self, n, equal=equal)

    # ------------------------------------------------------------------
    # writers (reference: dataset.py write_parquet/write_csv/write_json
    # -> one output file per block, written by parallel tasks)
    # ------------------------------------------------------------------
    def _write(self, path: str, file_format: str,
               filename_prefix: str) -> List[str]:
        import os

        from ray_tpu.data.datasource import write_block

        os.makedirs(path, exist_ok=True)

        @ray_tpu.remote
        def _write_one(block, out_path):
            return write_block(block, out_path, file_format)

        refs = []
        for i, block_ref in enumerate(self.iter_block_refs()):
            out = os.path.join(
                path, f"{filename_prefix}-{i:05d}.{file_format}")
            refs.append(_write_one.remote(block_ref, out))
        return ray_tpu.get(refs)

    def write_parquet(self, path: str, *,
                      filename_prefix: str = "part") -> List[str]:
        return self._write(path, "parquet", filename_prefix)

    def write_csv(self, path: str, *,
                  filename_prefix: str = "part") -> List[str]:
        return self._write(path, "csv", filename_prefix)

    def write_json(self, path: str, *,
                   filename_prefix: str = "part") -> List[str]:
        """JSON-lines, one file per block."""
        return self._write(path, "json", filename_prefix)

    def stats(self) -> Dict[str, Any]:
        """Plan shape + per-operator metrics of the most recent execution
        started from THIS dataset object (reference: Dataset.stats() /
        _internal/stats.py per-op counters)."""
        out: Dict[str, Any] = {
            "plan": [type(n).__name__ for n in self._plan]}
        ex = getattr(self, "_last_executor", None)
        if ex is not None:
            out["operators"] = {
                name: {"inputs": m.inputs_received,
                       "tasks_launched": m.tasks_launched,
                       "tasks_finished": m.tasks_finished,
                       "blocks_out": m.blocks_out}
                for name, m in ex.metrics().items()}
        return out

    def __repr__(self):
        return (f"Dataset(name={self._name!r}, "
                f"plan={[type(n).__name__ for n in self._plan]})")


def _emit_chunks(acc: "BlockAccessor", n: int):
    """Slice a block into ~n chunks (shared by repartition / shuffle /
    sort; handles the empty-block case)."""
    total = acc.num_rows()
    if total == 0:
        return
    per = max(1, (total + n - 1) // n)
    for lo in _py_range(0, total, per):
        yield acc.slice(lo, min(total, lo + per))


def _map_block_batches(block, call, batch_size, batch_format, kwargs):
    """One block -> transformed output batches (shared by the fused
    stage and the actor-compute worker so batching semantics can't
    diverge)."""
    from ray_tpu.data.iterator import _format_batch
    acc = BlockAccessor(block)
    n = acc.num_rows()
    step = batch_size or n or 1
    for lo in _py_range(0, n, step):
        batch = acc.slice(lo, min(n, lo + step))
        yield call(_format_batch(batch, batch_format), **kwargs)


class _MapActor:
    """Pool worker for actor-compute map_batches (reference:
    _map_actor_context in map_operator actors)."""

    def __init__(self, fn_blob: bytes, ctor_args_blob: bytes,
                 batch_size: Optional[int], batch_format: str,
                 kwargs_blob: bytes):
        import cloudpickle
        fn = cloudpickle.loads(fn_blob)
        ctor_args = cloudpickle.loads(ctor_args_blob)
        self._kwargs = cloudpickle.loads(kwargs_blob)
        # A callable CLASS is constructed once per actor.
        self._callable = fn(*ctor_args) if isinstance(fn, type) else fn
        self._batch_size = batch_size
        self._batch_format = batch_format

    def apply(self, block):
        outs = list(_map_block_batches(block, self._callable,
                                       self._batch_size,
                                       self._batch_format, self._kwargs))
        return concat_blocks(outs) if len(outs) != 1 else outs[0]


class DataIterator:
    """Per-consumer iterator facade (reference: data/iterator.py:71).

    Wraps a block-ref iterable factory so iter_batches can be called
    multiple times where the underlying source allows it."""

    def __init__(self, ref_iter_factory: Callable[[], Iterator[Any]],
                 name: str = "iter"):
        self._factory = ref_iter_factory
        self._name = name

    def iter_block_refs(self) -> Iterator[Any]:
        return self._factory()

    def iter_batches(self, *, batch_size: Optional[int] = None,
                     batch_format: str = "numpy", prefetch_blocks: int = 2,
                     drop_last: bool = False) -> Iterator[Any]:
        return iter_batches_from_refs(
            self._factory(), batch_size=batch_size,
            batch_format=batch_format, prefetch_blocks=prefetch_blocks,
            drop_last=drop_last)

    def iter_jax_batches(self, *, batch_size: Optional[int] = None,
                         sharding: Optional[Any] = None,
                         global_batch: bool = False,
                         prefetch_blocks: int = 2,
                         drop_last: bool = True) -> Iterator[Dict[str, Any]]:
        return iter_jax_batches_from_refs(
            self._factory(), batch_size=batch_size, sharding=sharding,
            global_batch=global_batch, prefetch_blocks=prefetch_blocks,
            drop_last=drop_last)

    def __repr__(self):
        return f"DataIterator({self._name})"


# ---------------------------------------------------------------------------
# constructors (reference: ray.data.range / from_items / read_*)
# ---------------------------------------------------------------------------

def range(n: int, *, num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    return Dataset(_ds.range_read_tasks(n, num_blocks), name=f"range({n})")


def from_items(items: List[Any], *, num_blocks: int = 1) -> Dataset:
    return Dataset(_ds.items_read_tasks(list(items), num_blocks),
                   name="from_items")


def from_numpy(batch, *, num_blocks: int = 1) -> Dataset:
    if isinstance(batch, np.ndarray):
        batch = {"data": batch}
    return Dataset(_ds.numpy_read_tasks(batch, num_blocks),
                   name="from_numpy")


def from_blocks(blocks: List[Block]) -> Dataset:
    return Dataset([ray_tpu.put(b) for b in blocks], name="from_blocks")


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    return Dataset(_ds.parquet_read_tasks(paths, columns),
                   name="read_parquet")


def read_csv(paths) -> Dataset:
    return Dataset(_ds.csv_read_tasks(paths), name="read_csv")


def read_json(paths) -> Dataset:
    return Dataset(_ds.json_read_tasks(paths), name="read_json")


def read_text(paths, *, encoding: str = "utf-8") -> Dataset:
    """One row per line, column "text" (reference: ray.data.read_text)."""
    return Dataset(_ds.text_read_tasks(paths, encoding=encoding),
                   name="read_text")


def read_binary_files(paths, *, include_paths: bool = False) -> Dataset:
    """One row per file, column "bytes" (reference:
    ray.data.read_binary_files)."""
    return Dataset(_ds.binary_read_tasks(paths,
                                         include_paths=include_paths),
                   name="read_binary_files")


def read_images(paths, *, size=None, mode: Optional[str] = None) -> Dataset:
    """One row per image, column "image" as [H, W, C] arrays (reference:
    ray.data.read_images; size=(w, h) resizes, mode converts e.g. "RGB")."""
    return Dataset(_ds.image_read_tasks(paths, size=size, mode=mode),
                   name="read_images")


def read_webdataset(paths, *, rows_per_block: int = 256,
                    decode: bool = True) -> Dataset:
    """Webdataset tar shards: one row per sample keyed by the dotted
    file-name prefix, columns per extension plus "__key__" (reference:
    ray.data.read_webdataset / _internal/datasource/
    webdataset_datasource.py). `decode=False` keeps raw bytes."""
    return Dataset(_ds.webdataset_read_tasks(
        paths, rows_per_block=rows_per_block, decode=decode),
        name="read_webdataset")


def read_lance(uri, *, columns: Optional[List[str]] = None) -> Dataset:
    """Lance dataset fragments (reference: ray.data.read_lance); needs
    the optional `lance` package."""
    return Dataset(_ds.lance_read_tasks(uri, columns=columns),
                   name="read_lance")
