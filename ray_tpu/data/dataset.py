"""Dataset — the lazy distributed data pipeline.

Analogue of the reference's Dataset (reference: python/ray/data/dataset.py —
map:276, map_batches:457, streaming_split:1826, iter_batches:4973,
iter_torch_batches:5044 → here iter_jax_batches). Redesigned linear:
a Dataset is (sources, fused stage chain); every transform appends a
block→blocks stage; execution streams blocks through one generator task per
source (executor.py). There is no separate logical/physical optimizer pass
because the representation IS the fused physical plan — the reference's
fusion rule output.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu

_py_range = range  # the public range() below shadows the builtin
from ray_tpu.data import datasource as _ds
from ray_tpu.data.block import Block, BlockAccessor, concat_blocks
from ray_tpu.data.executor import apply_stages, execute_streaming
from ray_tpu.data.iterator import (iter_batches_from_refs,
                                   iter_jax_batches_from_refs)


class Dataset:
    def __init__(self, sources: List[Any], stages: Optional[List] = None,
                 name: str = "dataset"):
        self._sources = sources  # ObjectRefs or read callables
        self._stages = list(stages or [])
        self._name = name

    # ------------------------------------------------------------------
    # transforms (lazy; each appends a block -> Iterator[block] stage)
    # ------------------------------------------------------------------
    def _with_stage(self, stage, name: str) -> "Dataset":
        return Dataset(self._sources, self._stages + [stage],
                       f"{self._name}->{name}")

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy",
                    fn_kwargs: Optional[dict] = None,
                    concurrency: Optional[int] = None,
                    fn_constructor_args: tuple = ()) -> "Dataset":
        """Apply fn to batches (reference: dataset.py:457). With
        batch_size=None each block is one batch; otherwise blocks are
        re-chunked to batch_size rows (within a block; a trailing short
        batch per block is possible, as with the reference's default
        shuffle=False zero-copy path).

        concurrency=N runs the transform on a pool of N ACTORS instead of
        fusing it into the source tasks (reference:
        ActorPoolMapOperator / map_batches(CallableClass, concurrency=N))
        — pass a callable CLASS to construct once per actor (model
        loading etc.) and call per batch."""
        if concurrency is not None:
            if concurrency < 1:
                raise ValueError(f"concurrency must be >= 1, "
                                 f"got {concurrency}")
            return _ActorMapDataset(self, fn, batch_size, batch_format,
                                    concurrency, fn_constructor_args,
                                    fn_kwargs or {})
        if isinstance(fn, type) or fn_constructor_args:
            # Fused stages call fn(batch); a callable CLASS would be
            # constructed per batch WITH the batch as its ctor arg.
            raise ValueError(
                "callable classes / fn_constructor_args require "
                "concurrency=N (the actor-compute strategy)")
        kwargs = fn_kwargs or {}

        def stage(block):
            yield from _map_block_batches(block, fn, batch_size,
                                          batch_format, kwargs)

        return self._with_stage(stage, "map_batches")

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        def stage(block):
            yield [fn(row) for row in BlockAccessor(block).to_rows()]

        return self._with_stage(stage, "map")

    def flat_map(self, fn: Callable[[Any], List[Any]]) -> "Dataset":
        def stage(block):
            out: List[Any] = []
            for row in BlockAccessor(block).to_rows():
                out.extend(fn(row))
            yield out

        return self._with_stage(stage, "flat_map")

    def filter(self, pred: Callable[[Any], bool]) -> "Dataset":
        def stage(block):
            acc = BlockAccessor(block)
            if isinstance(block, dict):  # columnar fast path
                rows = acc.to_rows()
                keep = [r for r in rows if pred(r)]
                if keep:
                    yield {k: np.asarray([r[k] for r in keep])
                           for k in keep[0]}
                return
            keep = [r for r in acc.to_rows() if pred(r)]
            if keep:
                yield keep

        return self._with_stage(stage, "filter")

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def iter_block_refs(self, window: int = 2) -> Iterator[Any]:
        return execute_streaming(self._sources, self._stages, window=window)

    def materialize(self) -> "Dataset":
        """Execute now; the result holds block refs (reference:
        dataset.py materialize -> MaterializedDataset)."""
        refs = list(self.iter_block_refs())
        return Dataset(refs, [], name=f"{self._name}(materialized)")

    def iter_batches(self, *, batch_size: Optional[int] = None,
                     batch_format: str = "numpy", prefetch_blocks: int = 2,
                     drop_last: bool = False) -> Iterator[Any]:
        return iter_batches_from_refs(
            self.iter_block_refs(), batch_size=batch_size,
            batch_format=batch_format, prefetch_blocks=prefetch_blocks,
            drop_last=drop_last)

    def iter_rows(self) -> Iterator[Any]:
        for batch in self.iter_batches(batch_format="rows"):
            yield from batch

    def iter_jax_batches(self, *, batch_size: Optional[int] = None,
                         sharding: Optional[Any] = None,
                         global_batch: bool = False,
                         prefetch_blocks: int = 2,
                         drop_last: bool = True) -> Iterator[Dict[str, Any]]:
        """Batches as jax.Arrays — the north-star ingest hop (host path is
        zero-copy out of the shm store; device transfer is the only copy)."""
        return iter_jax_batches_from_refs(
            self.iter_block_refs(), batch_size=batch_size,
            sharding=sharding, global_batch=global_batch,
            prefetch_blocks=prefetch_blocks, drop_last=drop_last)

    # ------------------------------------------------------------------
    # consumption helpers
    # ------------------------------------------------------------------
    def take(self, k: int = 20) -> List[Any]:
        out: List[Any] = []
        for batch in self.iter_batches(batch_format="rows"):
            out.extend(batch)
            if len(out) >= k:
                return out[:k]
        return out

    def take_all(self) -> List[Any]:
        out: List[Any] = []
        for batch in self.iter_batches(batch_format="rows"):
            out.extend(batch)
        return out

    def count(self) -> int:
        return sum(BlockAccessor(ray_tpu.get(r)).num_rows()
                   for r in self.iter_block_refs())

    def schema(self) -> Any:
        for ref in self.iter_block_refs(window=1):
            return BlockAccessor(ray_tpu.get(ref)).schema()
        return None

    def num_blocks(self) -> int:
        return len(self._sources)

    # ------------------------------------------------------------------
    # reorganization
    # ------------------------------------------------------------------
    def repartition(self, num_blocks: int) -> "Dataset":
        """Materialize then rebalance rows into num_blocks blocks."""
        mat = self.materialize()

        @ray_tpu.remote(num_returns="streaming")
        def _rechunk(refs, n):
            whole = concat_blocks([ray_tpu.get(r) for r in refs])
            yield from _emit_chunks(BlockAccessor(whole), n)

        refs = [r for r in _rechunk.remote(mat._sources, num_blocks)]
        return Dataset(refs, [], name=f"{self._name}(repartition)")

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Global shuffle: materialize + permute (single-task; fine at the
        block counts this framework targets per host — the reference's
        distributed shuffle service is multi-TB scale)."""
        n_blocks = max(1, self.num_blocks())
        mat = self.materialize()

        @ray_tpu.remote(num_returns="streaming")
        def _shuffle(refs, n, seed):
            rng = np.random.RandomState(seed)
            whole = concat_blocks([ray_tpu.get(r) for r in refs])
            acc = BlockAccessor(whole)
            total = acc.num_rows()
            perm = rng.permutation(total)
            if isinstance(whole, dict):
                shuffled: Block = {k: v[perm] for k, v in whole.items()}
            else:
                rows = acc.to_rows()
                shuffled = [rows[i] for i in perm]
            yield from _emit_chunks(BlockAccessor(shuffled), n)

        refs = [r for r in _shuffle.remote(mat._sources, n_blocks, seed)]
        return Dataset(refs, [], name=f"{self._name}(shuffled)")

    def groupby(self, key: str, *,
                num_partitions: Optional[int] = None):
        """Group rows by a column via a distributed hash shuffle
        (reference: dataset.py:2688 groupby -> GroupedData). Aggregations
        and map_groups run one reducer task per partition."""
        from ray_tpu.data.shuffle import GroupedData
        return GroupedData(self, key, num_partitions)

    def join(self, other: "Dataset", on: str, how: str = "inner", *,
             num_partitions: Optional[int] = None) -> "Dataset":
        """Distributed hash join with another dataset (reference:
        data/_internal/execution/operators/join.py; inner/left). Both
        sides co-partition by a process-stable key hash; right-side
        column collisions get a _right suffix."""
        from ray_tpu.data.shuffle import join_datasets
        return join_datasets(self, other, on, how, num_partitions)

    def unique(self, column: str) -> List[Any]:
        """Distinct values of a column (reference: dataset.py unique)."""
        out = self.groupby(column).count().take_all()
        return [r[column] for r in out]

    def union(self, *others: "Dataset") -> "Dataset":
        """Concatenate datasets (reference: dataset.py union). Blocks of
        each input stream in order (materialization-free); transforms
        chained after the union apply to every part."""
        return _UnionDataset([self, *others])

    def sort(self, key: str, *, descending: bool = False) -> "Dataset":
        """Global sort by a column (reference: dataset.py sort), STABLE
        in both directions. Materialize + single-task sort + re-chunk —
        fine at per-host block counts (the reference's distributed
        range-partition sort is multi-TB scale)."""
        n_blocks = max(1, self.num_blocks())
        mat = self.materialize()

        @ray_tpu.remote(num_returns="streaming")
        def _sorted(refs, n, key, descending):
            whole = concat_blocks([ray_tpu.get(r) for r in refs])
            acc = BlockAccessor(whole)
            if isinstance(whole, dict):
                v = whole[key]
                if descending:
                    # Stable descending: argsort the negated RANK codes
                    # (reversing an ascending argsort would reverse ties).
                    _, inv = np.unique(v, return_inverse=True)
                    order = np.argsort(-inv, kind="stable")
                else:
                    order = np.argsort(v, kind="stable")
                out: Block = {k: col[order] for k, col in whole.items()}
            else:
                out = sorted(acc.to_rows(),
                             key=lambda r: r[key], reverse=descending)
            yield from _emit_chunks(BlockAccessor(out), n)

        refs = [r for r in _sorted.remote(mat._sources, n_blocks, key,
                                          descending)]
        return Dataset(refs, [], name=f"{self._name}(sorted)")

    def split(self, n: int) -> List["Dataset"]:
        """Materialize and split into n datasets by whole blocks
        (reference: dataset.py split)."""
        mat = self.materialize()
        refs = mat._sources
        shards: List[List[Any]] = [[] for _ in _py_range(n)]
        for i, r in enumerate(refs):
            shards[i % n].append(r)
        return [Dataset(s, [], name=f"{self._name}(split{i})")
                for i, s in enumerate(shards)]

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List["DataIterator"]:
        """n per-consumer iterators over one shared streaming execution
        (reference: dataset.py:1826 streaming_split + output_splitter
        coordinated by a SplitCoordinator actor)."""
        from ray_tpu.data.split import create_streaming_split
        return create_streaming_split(self, n, equal=equal)

    def __repr__(self):
        return (f"Dataset(name={self._name!r}, "
                f"blocks={len(self._sources)}, stages={len(self._stages)})")


def _emit_chunks(acc: "BlockAccessor", n: int):
    """Slice a block into ~n chunks (shared by repartition / shuffle /
    sort; handles the empty-block case)."""
    total = acc.num_rows()
    if total == 0:
        return
    per = max(1, (total + n - 1) // n)
    for lo in _py_range(0, total, per):
        yield acc.slice(lo, min(total, lo + per))


class _UnionDataset(Dataset):
    """Concatenation of several datasets; chained transforms push down
    into every part (Dataset._with_stage would rebuild from the empty
    source list and silently drop everything)."""

    def __init__(self, parts: List["Dataset"]):
        super().__init__([], [], name="union")
        self._parts = parts

    def _with_stage(self, stage, name: str) -> "Dataset":
        return _UnionDataset([p._with_stage(stage, name)
                              for p in self._parts])

    def num_blocks(self) -> int:
        return sum(p.num_blocks() for p in self._parts)

    def iter_block_refs(self, window: int = 2):
        for p in self._parts:
            yield from p.iter_block_refs(window=window)


def _map_block_batches(block, call, batch_size, batch_format, kwargs):
    """One block -> transformed output batches (shared by the fused
    stage and the actor-compute worker so batching semantics can't
    diverge)."""
    from ray_tpu.data.iterator import _format_batch
    acc = BlockAccessor(block)
    n = acc.num_rows()
    step = batch_size or n or 1
    for lo in _py_range(0, n, step):
        batch = acc.slice(lo, min(n, lo + step))
        yield call(_format_batch(batch, batch_format), **kwargs)


class _MapActor:
    """Pool worker for actor-compute map_batches (reference:
    _map_actor_context in map_operator actors)."""

    def __init__(self, fn_blob: bytes, ctor_args_blob: bytes,
                 batch_size: Optional[int], batch_format: str,
                 kwargs_blob: bytes):
        import cloudpickle
        fn = cloudpickle.loads(fn_blob)
        ctor_args = cloudpickle.loads(ctor_args_blob)
        self._kwargs = cloudpickle.loads(kwargs_blob)
        # A callable CLASS is constructed once per actor.
        self._callable = fn(*ctor_args) if isinstance(fn, type) else fn
        self._batch_size = batch_size
        self._batch_format = batch_format

    def apply(self, block):
        outs = list(_map_block_batches(block, self._callable,
                                       self._batch_size,
                                       self._batch_format, self._kwargs))
        return concat_blocks(outs) if len(outs) != 1 else outs[0]


class _ActorMapDataset(Dataset):
    """A Dataset whose next stage runs on an actor pool; further
    transforms chain as fused per-block streaming tasks downstream."""

    def __init__(self, upstream: Dataset, fn, batch_size, batch_format,
                 concurrency: int, ctor_args: tuple, fn_kwargs: dict,
                 stages: Optional[List] = None):
        super().__init__([], stages,
                         name=f"{upstream._name}->map_batches(actors)")
        self._upstream = upstream
        self._fn = fn
        self._batch_size = batch_size
        self._batch_format = batch_format
        self._concurrency = concurrency
        self._ctor_args = ctor_args
        self._fn_kwargs = fn_kwargs

    def _with_stage(self, stage, name: str) -> "Dataset":
        return _ActorMapDataset(self._upstream, self._fn,
                                self._batch_size, self._batch_format,
                                self._concurrency, self._ctor_args,
                                self._fn_kwargs,
                                self._stages + [stage])

    def num_blocks(self) -> int:
        return self._upstream.num_blocks()

    def iter_block_refs(self, window: int = 2) -> Iterator[Any]:
        from collections import deque

        import cloudpickle

        import ray_tpu

        actor_cls = ray_tpu.remote(_MapActor)
        actors = [actor_cls.remote(
            cloudpickle.dumps(self._fn), cloudpickle.dumps(self._ctor_args),
            self._batch_size, self._batch_format,
            cloudpickle.dumps(self._fn_kwargs))
            for _ in _py_range(self._concurrency)]
        cap = 2 * self._concurrency

        def actor_refs():
            recent: deque = deque(maxlen=cap)
            exhausted = False
            try:
                inflight: deque = deque()
                rr = 0
                for ref in self._upstream.iter_block_refs(window=window):
                    if len(inflight) >= cap:  # upstream backpressure
                        head = inflight.popleft()
                        ray_tpu.wait([head], num_returns=1)
                        yield head
                    out = actors[rr % len(actors)].apply.remote(ref)
                    rr += 1
                    inflight.append(out)
                    recent.append(out)
                while inflight:
                    yield inflight.popleft()
                exhausted = True
            finally:
                # Normal exhaustion: wait for yielded-but-unfetched
                # results to finish materializing (consumers prefetch
                # refs) — no arbitrary cutoff killing slow transforms.
                # Early abandonment (take(k), closed generator): the
                # consumer won't fetch anything more; kill immediately.
                if exhausted and recent:
                    try:
                        ray_tpu.wait(list(recent),
                                     num_returns=len(recent))
                    except Exception:
                        pass
                for a in actors:
                    try:
                        ray_tpu.kill(a)
                    except Exception:
                        pass

        refs = actor_refs()
        if not self._stages:
            yield from refs
            return
        # Chained transforms run as fused per-block streaming tasks.
        from collections import deque

        from ray_tpu.data.executor import _source_task_fn
        stages_blob = cloudpickle.dumps(self._stages)
        remote_fn = ray_tpu.remote(num_returns="streaming")(_source_task_fn)
        pending: deque = deque()
        for ref in refs:
            pending.append(remote_fn.remote(ref, stages_blob))
            while len(pending) > window:
                yield from pending.popleft()
        while pending:
            yield from pending.popleft()


class DataIterator:
    """Per-consumer iterator facade (reference: data/iterator.py:71).

    Wraps a block-ref iterable factory so iter_batches can be called
    multiple times where the underlying source allows it."""

    def __init__(self, ref_iter_factory: Callable[[], Iterator[Any]],
                 name: str = "iter"):
        self._factory = ref_iter_factory
        self._name = name

    def iter_block_refs(self) -> Iterator[Any]:
        return self._factory()

    def iter_batches(self, *, batch_size: Optional[int] = None,
                     batch_format: str = "numpy", prefetch_blocks: int = 2,
                     drop_last: bool = False) -> Iterator[Any]:
        return iter_batches_from_refs(
            self._factory(), batch_size=batch_size,
            batch_format=batch_format, prefetch_blocks=prefetch_blocks,
            drop_last=drop_last)

    def iter_jax_batches(self, *, batch_size: Optional[int] = None,
                         sharding: Optional[Any] = None,
                         global_batch: bool = False,
                         prefetch_blocks: int = 2,
                         drop_last: bool = True) -> Iterator[Dict[str, Any]]:
        return iter_jax_batches_from_refs(
            self._factory(), batch_size=batch_size, sharding=sharding,
            global_batch=global_batch, prefetch_blocks=prefetch_blocks,
            drop_last=drop_last)

    def __repr__(self):
        return f"DataIterator({self._name})"


# ---------------------------------------------------------------------------
# constructors (reference: ray.data.range / from_items / read_*)
# ---------------------------------------------------------------------------

def range(n: int, *, num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    return Dataset(_ds.range_read_tasks(n, num_blocks), name=f"range({n})")


def from_items(items: List[Any], *, num_blocks: int = 1) -> Dataset:
    return Dataset(_ds.items_read_tasks(list(items), num_blocks),
                   name="from_items")


def from_numpy(batch, *, num_blocks: int = 1) -> Dataset:
    if isinstance(batch, np.ndarray):
        batch = {"data": batch}
    return Dataset(_ds.numpy_read_tasks(batch, num_blocks),
                   name="from_numpy")


def from_blocks(blocks: List[Block]) -> Dataset:
    return Dataset([ray_tpu.put(b) for b in blocks], name="from_blocks")


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    return Dataset(_ds.parquet_read_tasks(paths, columns),
                   name="read_parquet")


def read_csv(paths) -> Dataset:
    return Dataset(_ds.csv_read_tasks(paths), name="read_csv")


def read_json(paths) -> Dataset:
    return Dataset(_ds.json_read_tasks(paths), name="read_json")
