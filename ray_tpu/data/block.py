"""Blocks — the unit of data movement (reference: python/ray/data/block.py:51
Block = Arrow table | pandas frame; here Arrow table | numpy-dict | row list,
TPU-first: numpy-dict is the native batch format because it zero-copies from
the shm store into ``jax.Array`` via DLPack).

A block travels the cluster as one ObjectRef in the shared-memory store;
numpy/Arrow payloads use pickle-5 out-of-band buffers, so workers map them
zero-copy from tmpfs.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np

try:
    import pyarrow as pa
except Exception:  # pragma: no cover
    pa = None

Block = Union["pa.Table", Dict[str, np.ndarray], List[Any]]


def _column_array(vals: list) -> "np.ndarray":
    """Column values -> numpy, falling back to dtype=object for RAGGED
    columns (per-row arrays/lists of differing lengths — e.g. token-id
    prompts); a bare np.asarray would raise on the inhomogeneous
    shape."""
    try:
        return np.asarray(vals)
    except ValueError:
        out = np.empty(len(vals), object)
        out[:] = vals
        return out


class BlockAccessor:
    """Uniform view over the three block representations (reference:
    python/ray/data/block.py BlockAccessor.for_block)."""

    def __init__(self, block: Block):
        self._b = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    # -- shape -----------------------------------------------------------
    def num_rows(self) -> int:
        b = self._b
        if pa is not None and isinstance(b, pa.Table):
            return b.num_rows
        if isinstance(b, dict):
            return len(next(iter(b.values()))) if b else 0
        return len(b)

    def size_bytes(self) -> int:
        b = self._b
        if pa is not None and isinstance(b, pa.Table):
            return b.nbytes
        if isinstance(b, dict):
            return sum(v.nbytes for v in b.values())
        import sys
        return sum(sys.getsizeof(x) for x in b)

    def schema(self) -> Any:
        b = self._b
        if pa is not None and isinstance(b, pa.Table):
            return b.schema
        if isinstance(b, dict):
            return {k: v.dtype for k, v in b.items()}
        return type(b[0]).__name__ if b else None

    # -- conversions -----------------------------------------------------
    def to_arrow(self) -> "pa.Table":
        b = self._b
        if pa is None:
            raise RuntimeError("pyarrow unavailable")
        if isinstance(b, pa.Table):
            return b
        if isinstance(b, dict):
            return pa.table({k: pa.array(v) for k, v in b.items()})
        if b and isinstance(b[0], dict):
            return pa.Table.from_pylist(b)
        return pa.table({"item": pa.array(b)})

    def to_numpy_batch(self) -> Dict[str, np.ndarray]:
        """Columnar numpy dict — zero-copy from Arrow where dtypes allow."""
        b = self._b
        if pa is not None and isinstance(b, pa.Table):
            out = {}
            for name in b.column_names:
                col = b.column(name)
                try:
                    out[name] = col.to_numpy(zero_copy_only=True)
                except Exception:
                    out[name] = col.to_numpy(zero_copy_only=False)
            return out
        if isinstance(b, dict):
            return b
        if b and isinstance(b[0], dict):
            keys = b[0].keys()
            return {k: _column_array([r[k] for r in b]) for k in keys}
        return {"item": _column_array(list(b))}

    def to_rows(self) -> List[Any]:
        b = self._b
        if pa is not None and isinstance(b, pa.Table):
            return b.to_pylist()
        if isinstance(b, dict):
            keys = list(b)
            n = self.num_rows()
            return [{k: b[k][i] for k in keys} for i in range(n)]
        return list(b)

    # -- slicing ---------------------------------------------------------
    def slice(self, start: int, end: int) -> Block:
        b = self._b
        if pa is not None and isinstance(b, pa.Table):
            return b.slice(start, end - start)
        if isinstance(b, dict):
            return {k: v[start:end] for k, v in b.items()}
        return b[start:end]


def concat_blocks(blocks: List[Block]) -> Block:
    """Concatenate same-representation blocks."""
    blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0]
    if not blocks:
        return []
    b0 = blocks[0]
    if pa is not None and isinstance(b0, pa.Table):
        return pa.concat_tables([BlockAccessor(b).to_arrow() for b in blocks])
    if isinstance(b0, dict):
        keys = b0.keys()
        return {k: np.concatenate([b[k] for b in blocks]) for k in keys}
    out: List[Any] = []
    for b in blocks:
        out.extend(b)
    return out
