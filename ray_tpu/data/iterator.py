"""DataIterator: batch formation + prefetch + JAX conversion.

Analogue of the reference's iteration path (reference:
python/ray/data/iterator.py:71 DataIterator.iter_batches +
_internal/block_batching/ prefetch windows; iter_torch_batches →
here iter_jax_batches, the BASELINE north-star Arrow→DLPack→jax.Array
host-zero-copy hop).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor, concat_blocks


def _format_batch(batch, batch_format: str):
    acc = BlockAccessor(batch)
    if batch_format == "numpy":
        return acc.to_numpy_batch()
    if batch_format == "pyarrow":
        return acc.to_arrow()
    if batch_format == "rows":
        return acc.to_rows()
    raise ValueError(f"unknown batch_format {batch_format!r}")


def iter_batches_from_refs(ref_iter: Iterator[Any], *, batch_size: Optional[int],
                           batch_format: str = "numpy",
                           prefetch_blocks: int = 2,
                           drop_last: bool = False) -> Iterator[Any]:
    """Stream blocks (prefetching refs ahead) and re-chunk rows into batches
    of exactly batch_size (except possibly the last)."""
    window: List[Any] = []

    def fill(it):
        while len(window) < prefetch_blocks + 1:
            try:
                window.append(next(it))
            except StopIteration:
                return False
        return True

    it = iter(ref_iter)
    carry = None  # leftover rows as a block
    while True:
        fill(it)
        if not window:
            break
        block = ray_tpu.get(window.pop(0))
        if carry is not None:
            block = concat_blocks([carry, block])
            carry = None
        acc = BlockAccessor(block)
        n = acc.num_rows()
        if batch_size is None:
            if n:
                yield _format_batch(block, batch_format)
            continue
        start = 0
        while n - start >= batch_size:
            yield _format_batch(acc.slice(start, start + batch_size),
                                batch_format)
            start += batch_size
        if start < n:
            carry = acc.slice(start, n)
    if carry is not None and BlockAccessor(carry).num_rows() and not drop_last:
        if batch_size is None or not drop_last:
            yield _format_batch(carry, batch_format)


def iter_jax_batches_from_refs(ref_iter: Iterator[Any], *,
                               batch_size: Optional[int],
                               sharding: Optional[Any] = None,
                               prefetch_blocks: int = 2,
                               drop_last: bool = True,
                               global_batch: bool = False
                               ) -> Iterator[Dict[str, Any]]:
    """numpy batches → jax.Arrays.

    The host path is zero-copy: block bytes are mmapped from the shm store
    and deserialized as views; device transfer is the only copy. With
    ``sharding`` set, arrays are placed with jax.device_put(sharding); with
    ``global_batch=True`` (multi-host SPMD), each process's batch is treated
    as its shard of the global batch via
    jax.make_array_from_process_local_data (reference north star:
    Arrow → DLPack → jax.Array on the workers of a JaxTrainer).
    """
    import jax

    for batch in iter_batches_from_refs(ref_iter, batch_size=batch_size,
                                        batch_format="numpy",
                                        prefetch_blocks=prefetch_blocks,
                                        drop_last=drop_last):
        if batch_size is not None and drop_last:
            n = len(next(iter(batch.values()))) if batch else 0
            if n != batch_size:
                continue
        if sharding is not None and global_batch:
            yield {k: jax.make_array_from_process_local_data(sharding, v)
                   for k, v in batch.items()}
        elif sharding is not None:
            yield {k: jax.device_put(v, sharding) for k, v in batch.items()}
        else:
            yield {k: jax.device_put(v) for k, v in batch.items()}
