"""ray_tpu.data — streaming datasets for TPU ingest.

Analogue of Ray Data (reference: python/ray/data/__init__.py public
surface), rebuilt linear + TPU-first: blocks stream through generator
tasks; batches land as ``jax.Array`` via the zero-copy host path
(SURVEY north star: Arrow -> DLPack -> jax.Array).
"""

from ray_tpu.data.block import Block, BlockAccessor, concat_blocks
from ray_tpu.data.dataset import (DataIterator, Dataset, from_blocks,
                                  from_items, from_numpy, range,  # noqa: A004
                                  read_binary_files, read_csv, read_images,
                                  read_json, read_lance, read_parquet,
                                  read_text, read_webdataset)
from ray_tpu.data import preprocessors

__all__ = [
    "Block", "BlockAccessor", "concat_blocks",
    "Dataset", "DataIterator",
    "range", "from_items", "from_numpy", "from_blocks",
    "read_parquet", "read_csv", "read_json", "read_text",
    "read_binary_files", "read_images", "read_webdataset",
    "read_lance", "preprocessors",
]

from ray_tpu.data import llm  # noqa: E402,F401  (batch inference bridge)
