"""Hash-shuffle stage: distributed groupby/aggregate/join.

Analogue of the reference's hash-shuffle operators (reference:
python/ray/data/_internal/execution/operators/hash_shuffle.py:1032
HashShufflingOperatorBase, hash_aggregate.py, join.py). Redesign for this
framework's linear-plan executor: the all-to-all exchange is two task
waves —

  map wave:    one task per input block, partitioning rows by a
               process-stable hash of the key into P column-blocks
               (num_returns=P: each part is its own object, so reducers
               pull only their partition)
  reduce wave: P tasks; reducer j concatenates part j of every map task
               and runs the per-partition reduction (vectorized
               aggregation, hash join, or a user map_groups fn)

Keys hash with crc32 (NOT Python's per-process-randomized str hash):
both sides of a join partition identically in different worker
processes.
"""

from __future__ import annotations

import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor, concat_blocks

AggSpec = Tuple[str, Optional[str]]  # (op, column); column None for count


def _hash_partition_codes(vals: np.ndarray, num_partitions: int
                          ) -> np.ndarray:
    """Process-stable partition code per row.

    Numeric keys normalize to float64 then mix the bit pattern
    (splitmix64, vectorized) — equal values of different dtypes (3 vs
    3.0, int32 vs int64) land in the same partition, and strided key
    spaces don't degenerate onto one reducer the way raw modulo would.
    Everything else hashes crc32 of its string form (NOT Python's
    per-process-randomized hash)."""
    if vals.dtype.kind in "iufb":
        v = vals.astype(np.float64) + 0.0  # -0.0 -> +0.0
        h = v.view(np.uint64).copy()
        c1 = np.uint64(0xFF51AFD7ED558CCD)
        c2 = np.uint64(0xC4CEB9FE1A85EC53)
        s = np.uint64(33)
        h ^= h >> s
        h *= c1
        h ^= h >> s
        h *= c2
        h ^= h >> s
        return (h % np.uint64(num_partitions)).astype(np.int64)
    out = np.empty(len(vals), np.int64)
    for i, v in enumerate(vals):
        out[i] = zlib.crc32(str(v).encode()) % num_partitions
    return out


@ray_tpu.remote
def _block_columns(block: Any) -> List[str]:
    """Column names of a block ([] when empty) — schema without moving
    the data to the driver."""
    acc = BlockAccessor(block)
    if not acc.num_rows():
        return []
    return list(acc.to_numpy_batch().keys())


@ray_tpu.remote
def _partition_block(block: Any, key: str, num_partitions: int):
    """Map side: split one block into per-partition column blocks."""
    cols = BlockAccessor(block).to_numpy_batch()
    if key not in cols:
        raise KeyError(f"groupby/join key {key!r} not in columns "
                       f"{sorted(cols)}")
    codes = _hash_partition_codes(np.asarray(cols[key]), num_partitions)
    parts = []
    for j in range(num_partitions):
        mask = codes == j
        parts.append({k: np.asarray(v)[mask] for k, v in cols.items()})
    return tuple(parts)


def _partition_refs(ds, key: str, num_partitions: int) -> List[List[Any]]:
    """All input blocks -> refs[part_j] = [map task parts]."""
    mat = ds.materialize()
    if num_partitions == 1:
        # hash % 1 == 0 for every row: blocks pass through unpartitioned.
        return [list(mat._sources)]
    per_map = [
        _partition_block.options(num_returns=num_partitions).remote(
            ref, key, num_partitions)
        for ref in mat._sources
    ]
    return [[parts[j] for parts in per_map]
            for j in range(num_partitions)]


def _default_partitions(*datasets) -> int:
    return max(1, *(d.num_blocks() for d in datasets))


# ----------------------------------------------------------------------
# aggregation reducers
# ----------------------------------------------------------------------

def _agg_name(op: str, col: Optional[str]) -> str:
    return f"{op}({col})" if col else f"{op}()"


@ray_tpu.remote
def _agg_reduce(key: str, aggs: List[AggSpec], *parts):
    """Reduce side: vectorized per-key aggregation of one partition."""
    block = concat_blocks(list(parts))
    if BlockAccessor(block).num_rows() == 0:
        return {}
    cols = BlockAccessor(block).to_numpy_batch()
    uniq, inv = np.unique(np.asarray(cols[key]), return_inverse=True)
    n = len(uniq)
    counts = np.bincount(inv, minlength=n)
    out: Dict[str, np.ndarray] = {key: uniq}
    for spec in aggs:
        op, col = spec[0], spec[1]
        if op == "count":
            out[_agg_name(op, col)] = counts
            continue
        v = np.asarray(cols[col], dtype=np.float64)
        if op in ("sum", "mean", "std"):
            sums = np.zeros(n)
            np.add.at(sums, inv, v)
            if op == "sum":
                out[_agg_name(op, col)] = sums
            elif op == "mean":
                out[_agg_name(op, col)] = sums / counts
            else:  # std; ddof rides as the spec's third element
                ddof = spec[2] if len(spec) > 2 else 0
                sq = np.zeros(n)
                np.add.at(sq, inv, v * v)
                mean = sums / counts
                var = np.maximum(sq / counts - mean * mean, 0.0)
                denom = np.maximum(counts - ddof, 1)
                var = var * counts / denom
                out[_agg_name(op, col)] = np.sqrt(var)
        elif op == "min":
            acc = np.full(n, np.inf)
            np.minimum.at(acc, inv, v)
            out[_agg_name(op, col)] = acc
        elif op == "max":
            acc = np.full(n, -np.inf)
            np.maximum.at(acc, inv, v)
            out[_agg_name(op, col)] = acc
        else:
            raise ValueError(f"unsupported aggregation {op!r}")
    return out


@ray_tpu.remote
def _map_groups_reduce(key: str, fn_blob: bytes, *parts):
    """Reduce side: run a user function once per key group."""
    import cloudpickle

    fn = cloudpickle.loads(fn_blob)
    block = concat_blocks(list(parts))
    if BlockAccessor(block).num_rows() == 0:
        return []
    cols = BlockAccessor(block).to_numpy_batch()
    uniq, inv = np.unique(np.asarray(cols[key]), return_inverse=True)
    out_blocks = []
    for g in range(len(uniq)):
        mask = inv == g
        group = {k: np.asarray(v)[mask] for k, v in cols.items()}
        res = fn(group)
        if res is not None:
            out_blocks.append(res)
    return concat_blocks(out_blocks) if out_blocks else []


class GroupedData:
    """Deferred groupby (reference: grouped_data.py GroupedData)."""

    def __init__(self, ds, key: str,
                 num_partitions: Optional[int] = None):
        self._ds = ds
        self._key = key
        self._parts = num_partitions
        # One shuffle serves every aggregation on this GroupedData:
        # repeated g.count(); g.mean() must not re-run the exchange.
        self._part_cache: Dict[int, List[List[Any]]] = {}

    def _partitions(self, P: int) -> List[List[Any]]:
        refs = self._part_cache.get(P)
        if refs is None:
            refs = self._part_cache[P] = _partition_refs(
                self._ds, self._key, P)
        return refs

    def _agg(self, aggs: List[AggSpec]):
        from ray_tpu.data.dataset import Dataset

        P = self._parts or _default_partitions(self._ds)
        part_refs = self._partitions(P)
        refs = [_agg_reduce.remote(self._key, aggs, *part_refs[j])
                for j in range(P)]
        return Dataset(refs, [],
                       name=f"{self._ds._name}(groupby:{self._key})")

    def count(self):
        return self._agg([("count", None)])

    def sum(self, on: str):
        return self._agg([("sum", on)])

    def mean(self, on: str):
        return self._agg([("mean", on)])

    def min(self, on: str):
        return self._agg([("min", on)])

    def max(self, on: str):
        return self._agg([("max", on)])

    def std(self, on: str, ddof: int = 0):
        return self._agg([("std", on, ddof)])

    def aggregate(self, *specs: AggSpec):
        """Multiple aggregations at once: aggregate(("sum", "x"),
        ("mean", "y"), ("count", None))."""
        return self._agg(list(specs))

    def map_groups(self, fn: Callable[[Dict[str, np.ndarray]], Any]):
        """fn(group_columns) -> block (columns dict or row list) per
        key group (reference: grouped_data.py map_groups)."""
        import cloudpickle

        from ray_tpu.data.dataset import Dataset

        P = self._parts or _default_partitions(self._ds)
        part_refs = self._partitions(P)
        blob = cloudpickle.dumps(fn)
        refs = [_map_groups_reduce.remote(self._key, blob, *part_refs[j])
                for j in range(P)]
        return Dataset(refs, [],
                       name=f"{self._ds._name}(map_groups:{self._key})")


# ----------------------------------------------------------------------
# joins
# ----------------------------------------------------------------------

@ray_tpu.remote
def _join_reduce(on: str, how: str, n_left: int, right_cols: List[str],
                 *parts):
    """Reduce side: hash join of one partition (both sides already
    co-partitioned by the same stable key hash). right_cols is the
    GLOBAL right-side schema — a partition whose right side is empty
    must still emit None for every right column on `how=left`, or the
    output schema varies by partition."""
    left = concat_blocks(list(parts[:n_left]))
    right = concat_blocks(list(parts[n_left:]))
    lrows = BlockAccessor(left).to_rows() if \
        BlockAccessor(left).num_rows() else []
    rrows = BlockAccessor(right).to_rows() if \
        BlockAccessor(right).num_rows() else []
    by_key: Dict[Any, List[dict]] = {}
    for r in rrows:
        by_key.setdefault(r[on], []).append(r)
    rcols = set(right_cols) - {on}
    out = []
    for lr in lrows:
        matches = by_key.get(lr[on])
        if matches:
            for rr in matches:
                row = dict(lr)
                for k in rr:
                    if k == on:
                        continue
                    # collision -> right column gets a _right suffix
                    row[f"{k}_right" if k in row else k] = rr[k]
                out.append(row)
        elif how == "left":
            row = dict(lr)
            for k in rcols:
                row[f"{k}_right" if k in row else k] = None
            out.append(row)
    return out


def join_datasets(left, right, on: str, how: str = "inner",
                  num_partitions: Optional[int] = None):
    """Distributed hash join (reference: join.py JoinOperator;
    inner/left)."""
    from ray_tpu.data.dataset import Dataset

    if how not in ("inner", "left"):
        raise ValueError(f"unsupported join type {how!r}")
    P = num_partitions or _default_partitions(left, right)
    right = right.materialize()
    lparts = _partition_refs(left, on, P)
    rparts = _partition_refs(right, on, P)
    right_cols: List[str] = []
    if how == "left":
        # Schema only — a tiny task per block, never the block itself.
        for ref in right._sources:
            cols = ray_tpu.get(_block_columns.remote(ref))
            if cols:
                right_cols = cols
                break
    refs = [
        _join_reduce.remote(on, how, len(lparts[j]), right_cols,
                            *lparts[j], *rparts[j])
        for j in range(P)
    ]
    return Dataset(refs, [],
                   name=f"{left._name}(join:{on}:{right._name})")
