"""Physical operators for the Data streaming executor.

Analogue of the reference's execution operators (reference:
python/ray/data/_internal/execution/operators/map_operator.py,
actor_pool_map_operator.py, base_physical_operator.py AllToAllOperator,
output_splitter.py; interfaces in execution/interfaces/physical_operator.py).
Redesigned around this runtime's primitives:

  * Map work runs as STREAMING GENERATOR tasks (one per input item) whose
    per-task output window is bounded by the runtime's generator
    backpressure — an operator's memory footprint is therefore
    (active tasks x backpressure window) blocks, both factors bounded by
    the executor's resource manager.
  * Operators are PULL-polled by the executor loop (no operator threads):
    `poll()` harvests whatever finished without blocking, `dispatch()`
    launches at most one unit of work. All scheduling policy (budgets,
    backpressure, priority) lives in the executor, not the operators.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.core.common import GetTimeoutError
from ray_tpu.utils import get_logger

logger = get_logger("data.operators")


class OpMetrics:
    """Per-operator counters the resource manager and tests read."""

    def __init__(self) -> None:
        self.inputs_received = 0
        self.tasks_launched = 0
        self.tasks_finished = 0
        self.blocks_out = 0
        self.bytes_out_estimate = 0

    def __repr__(self) -> str:
        return (f"OpMetrics(in={self.inputs_received}, "
                f"tasks={self.tasks_launched}/{self.tasks_finished}, "
                f"blocks_out={self.blocks_out})")


class PhysicalOperator:
    """Base operator: the executor pushes inputs in, polls outputs out.

    Lifecycle: start() -> {add_input()* , dispatch()*, poll()*} ->
    all_inputs_done() -> (drain) -> completed() -> shutdown().
    """

    def __init__(self, name: str):
        self.name = name
        self.metrics = OpMetrics()
        self._input_queue: deque = deque()
        self._inputs_done = False

    # -- input side (executor calls) -----------------------------------
    def add_input(self, item: Any) -> None:
        self.metrics.inputs_received += 1
        self._input_queue.append(item)

    def all_inputs_done(self) -> None:
        self._inputs_done = True

    def num_queued_inputs(self) -> int:
        return len(self._input_queue)

    # -- work side ------------------------------------------------------
    def start(self) -> None:
        pass

    def can_dispatch(self) -> bool:
        """True if a dispatch() call would launch work right now."""
        return bool(self._input_queue)

    def dispatch(self) -> bool:
        """Launch at most ONE unit of work (a task / an actor call).
        Returns True if something was launched."""
        return False

    def num_active_tasks(self) -> int:
        return 0

    def poll(self) -> List[Any]:
        """Harvest finished work WITHOUT blocking; returns output block
        refs in operator order."""
        return []

    def wait_any(self, timeout: float) -> None:
        """Block up to `timeout` for progress (executor idle path)."""
        import time
        time.sleep(timeout)

    def completed(self) -> bool:
        return (self._inputs_done and not self._input_queue
                and self.num_active_tasks() == 0)

    def shutdown(self) -> None:
        pass


class SourceOperator(PhysicalOperator):
    """Emits a fixed list of source items (materialized block refs or
    pickled read callables). The no-op head of every topology (reference:
    InputDataBuffer)."""

    def __init__(self, sources: List[Any], name: str = "input"):
        super().__init__(name)
        for s in sources:
            self._input_queue.append(s)
        self.metrics.inputs_received = len(sources)
        self._inputs_done = True

    def poll(self) -> List[Any]:
        out = list(self._input_queue)
        self._input_queue.clear()
        self.metrics.blocks_out += len(out)
        return out


class _StreamHandle:
    """One in-flight streaming task: non-blocking harvest of its yielded
    refs via next_stream_item(timeout=0), staged locally so EVERY
    stream's backpressure window keeps rolling even while output order
    holds emission to the head stream."""

    __slots__ = ("gen", "idx", "done", "staged")

    def __init__(self, gen):
        self.gen = gen          # ObjectRefGenerator
        self.idx = 0
        self.done = False
        self.staged: deque = deque()

    def drain(self, limit: int) -> int:
        """Pull up to `limit` ready items into the staging queue; returns
        the number pulled."""
        from ray_tpu.core.ref import get_core_worker
        cw = get_core_worker()
        pulled = 0
        while not self.done and pulled < limit:
            try:
                ref = cw.next_stream_item(self.gen.task_id, self.idx,
                                          timeout=0)
            except GetTimeoutError:
                break
            if ref is None:
                self.done = True
                break
            self.idx += 1
            self.staged.append(ref)
            pulled += 1
        return pulled

    def wait(self, timeout: float) -> None:
        from ray_tpu.core.ref import get_core_worker
        # Peek-wait: park until item `idx` is ready without consuming it.
        get_core_worker().wait_stream_item(self.gen.task_id, self.idx,
                                           timeout)


class MapTaskOperator(PhysicalOperator):
    """Fused map chain as streaming tasks: one task per input item
    (reference: MapOperator via TaskPoolMapOperator + the fusion rule).

    Input items are materialized block refs OR pickled zero-arg read
    callables; the task body applies the fused stage chain and yields
    output blocks (executor.py _source_task_fn).
    """

    def __init__(self, stages: List[Callable], name: str = "map",
                 resources: Optional[dict] = None):
        super().__init__(name)
        import cloudpickle
        self._stages_blob = cloudpickle.dumps(list(stages))
        self._resources = resources
        self._streams: deque[_StreamHandle] = deque()
        self._remote_fn = None

    def start(self) -> None:
        from ray_tpu.data.executor import _source_task_fn
        fn = ray_tpu.remote(num_returns="streaming")(_source_task_fn)
        if self._resources:
            fn = fn.options(resources=self._resources)
        self._remote_fn = fn

    def dispatch(self) -> bool:
        if not self._input_queue:
            return False
        item = self._input_queue.popleft()
        gen = self._remote_fn.remote(item, self._stages_blob)
        self._streams.append(_StreamHandle(gen))
        self.metrics.tasks_launched += 1
        return True

    def num_active_tasks(self) -> int:
        return len(self._streams)

    # Per-stream staging bound: keeps output order without re-parking a
    # stream the instant its runtime backpressure window frees up.
    _STAGE_LIMIT = 16

    def poll(self) -> List[Any]:
        """Drain EVERY in-flight stream into its staging queue (so all
        backpressure windows roll), then emit staged items in stream
        order (output order = input order)."""
        out: List[Any] = []
        for h in self._streams:
            h.drain(self._STAGE_LIMIT - len(h.staged))
        while self._streams:
            head = self._streams[0]
            while head.staged:
                out.append(head.staged.popleft())
            if head.done:
                self._streams.popleft()
                self.metrics.tasks_finished += 1
            else:
                break
        self.metrics.blocks_out += len(out)
        return out

    def wait_any(self, timeout: float) -> None:
        if self._streams:
            self._streams[0].wait(timeout)
        else:
            super().wait_any(timeout)

    def shutdown(self) -> None:
        for h in self._streams:
            try:
                h.gen.release()
            except Exception:
                pass
        self._streams.clear()


class ActorPoolMapOperator(PhysicalOperator):
    """Map via a pool of long-lived actors — for callable-class
    transforms that carry per-worker state (reference:
    actor_pool_map_operator.py + _ActorPool).

    Output order preserved: results are queued per-dispatch and yielded
    head-first once ready. Dispatch targets the least-loaded actor.
    """

    def __init__(self, fn, ctor_args: tuple, fn_kwargs: dict,
                 batch_size: Optional[int], batch_format: str,
                 pool_size: int, name: str = "map(actors)",
                 max_inflight_per_actor: int = 2,
                 resources: Optional[dict] = None):
        super().__init__(name)
        import cloudpickle
        self._fn_blob = cloudpickle.dumps(fn)
        self._ctor_blob = cloudpickle.dumps(ctor_args)
        self._kwargs_blob = cloudpickle.dumps(fn_kwargs)
        self._actor_resources = dict(resources or {})
        self._batch_size = batch_size
        self._batch_format = batch_format
        self._pool_size = pool_size
        self._max_inflight = max_inflight_per_actor
        self._actors: List[Any] = []
        self._actor_load: List[int] = []
        # [ref, actor_idx, ready] in dispatch order (output order).
        self._inflight: deque = deque()

    def start(self) -> None:
        from ray_tpu.data.dataset import _MapActor
        actor_cls = ray_tpu.remote(_MapActor)
        if self._actor_resources:
            # Pool actors with device/resource requests (e.g. one TPU
            # per batch-inference engine — reference: map_batches
            # num_gpus/resources options).
            res = dict(self._actor_resources)
            opts = {}
            if "CPU" in res:
                opts["num_cpus"] = res.pop("CPU")
            if "TPU" in res:
                opts["num_tpus"] = res.pop("TPU")
            if res:
                opts["resources"] = res
            actor_cls = actor_cls.options(**opts)
        self._actors = [
            actor_cls.remote(self._fn_blob, self._ctor_blob,
                             self._batch_size, self._batch_format,
                             self._kwargs_blob)
            for _ in range(self._pool_size)]
        self._actor_load = [0] * self._pool_size

    def can_dispatch(self) -> bool:
        return (bool(self._input_queue)
                and len(self._inflight) < self._pool_size * self._max_inflight)

    def dispatch(self) -> bool:
        if not self.can_dispatch():
            return False
        item = self._input_queue.popleft()
        ai = min(range(len(self._actors)), key=lambda i: self._actor_load[i])
        ref = self._actors[ai].apply.remote(item)
        self._actor_load[ai] += 1
        self._inflight.append([ref, ai, False])
        self.metrics.tasks_launched += 1
        return True

    def num_active_tasks(self) -> int:
        return len(self._inflight)

    def poll(self) -> List[Any]:
        # Readiness scan over ALL in-flight entries (not just the head):
        # load accounting must see completions behind a straggling head or
        # least-loaded dispatch piles onto the slow actor.
        for entry in self._inflight:
            if not entry[2]:
                ready, _ = ray_tpu.wait([entry[0]], num_returns=1, timeout=0)
                if ready:
                    entry[2] = True
                    self._actor_load[entry[1]] -= 1
                    self.metrics.tasks_finished += 1
        out: List[Any] = []
        while self._inflight and self._inflight[0][2]:
            out.append(self._inflight.popleft()[0])
        self.metrics.blocks_out += len(out)
        return out

    def wait_any(self, timeout: float) -> None:
        if self._inflight:
            ray_tpu.wait([self._inflight[0][0]], num_returns=1,
                         timeout=timeout)
        else:
            super().wait_any(timeout)

    def completed(self) -> bool:
        return (self._inputs_done and not self._input_queue
                and not self._inflight)

    def shutdown(self) -> None:
        # poll() only emits SEALED results (ray_tpu.wait said ready), so
        # killing the pool never invalidates refs already handed
        # downstream; in-flight work (early abandonment via take(k))
        # dies with the actors.
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors = []


class AllToAllOperator(PhysicalOperator):
    """Barrier operator: collects EVERY input ref, then runs a driver-side
    exchange function refs -> refs (hash shuffle, sort, repartition)
    (reference: base_physical_operator.py AllToAllOperator; the exchange
    fns themselves stay the two-wave task pipelines in shuffle.py).

    The exchange runs on a worker THREAD launched by dispatch() — the
    exchange fns block on their barrier task waves, and running them on
    the executor loop would stall harvesting/dispatch for every
    independent operator (e.g. the other branch of a union) while the
    barrier runs. Driver API calls are thread-safe (the core worker
    marshals them onto its IO loop)."""

    def __init__(self, exchange_fn: Callable[[List[Any]], List[Any]],
                 name: str = "all_to_all"):
        super().__init__(name)
        self._exchange_fn = exchange_fn
        self._collected: List[Any] = []
        self._emitted = False
        self._thread = None
        self._result: Optional[List[Any]] = None
        self._error: Optional[BaseException] = None

    def can_dispatch(self) -> bool:
        # Runs exactly once, only after the full input set arrived.
        return (self._inputs_done and not self._emitted
                and self._thread is None)

    def dispatch(self) -> bool:
        if not self.can_dispatch():
            return False
        import threading
        self._collected.extend(self._input_queue)
        self._input_queue.clear()

        def _run():
            try:
                self._result = list(self._exchange_fn(self._collected))
            except BaseException as e:  # surfaced from poll()
                self._error = e

        self._thread = threading.Thread(
            target=_run, name=f"data-{self.name}", daemon=True)
        self._thread.start()
        self.metrics.tasks_launched += 1
        return True

    def num_active_tasks(self) -> int:
        return 1 if (self._thread is not None
                     and not self._emitted) else 0

    def poll(self) -> List[Any]:
        if self._thread is None:
            # keep collecting as inputs stream in
            self._collected.extend(self._input_queue)
            self._input_queue.clear()
            return []
        if self._thread.is_alive():
            return []
        self._thread.join()
        if self._error is not None:
            err, self._error = self._error, None
            self._emitted = True
            raise err
        if self._emitted:
            return []
        out = self._result or []
        self._result = None
        self._collected = []
        self._emitted = True
        self.metrics.tasks_finished += 1
        self.metrics.blocks_out += len(out)
        return out

    def wait_any(self, timeout: float) -> None:
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout)
        else:
            super().wait_any(timeout)

    def completed(self) -> bool:
        return self._emitted


class ConcatOperator(PhysicalOperator):
    """Union glue: forwards branch outputs in branch order (reference:
    union is a logical concat of input streams). The executor wires every
    branch's sink here; branch i+1's blocks are held until branch i is
    exhausted so output order matches the union order."""

    def __init__(self, num_branches: int, name: str = "union"):
        super().__init__(name)
        self._branch_queues: List[deque] = [deque()
                                            for _ in range(num_branches)]
        self._branch_done = [False] * num_branches
        self._next_branch = 0

    def add_branch_input(self, branch: int, item: Any) -> None:
        self.metrics.inputs_received += 1
        self._branch_queues[branch].append(item)

    def branch_done(self, branch: int) -> None:
        self._branch_done[branch] = True

    def poll(self) -> List[Any]:
        out: List[Any] = []
        while self._next_branch < len(self._branch_queues):
            q = self._branch_queues[self._next_branch]
            while q:
                out.append(q.popleft())
            if self._branch_done[self._next_branch]:
                self._next_branch += 1
            else:
                break
        self.metrics.blocks_out += len(out)
        return out

    def completed(self) -> bool:
        return self._next_branch >= len(self._branch_queues)
