"""Fused map task bodies for the Data streaming executor.

The planner (dataset.py _build_states) fuses every chain of row/batch
transforms into ONE streaming task per source block (the reference's
MapOperator fusion rule — reference:
python/ray/data/_internal/logical/rules/operator_fusion.py — taken to its
limit); this module holds the task-side machinery those fused tasks run.
The executor loop, operators, and backpressure live in
streaming_executor.py / operators.py.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List

# A stage maps one block to zero or more output blocks.
Stage = Callable[[Any], Iterator[Any]]


def apply_stages(block: Any, stages: List[Stage]) -> Iterator[Any]:
    """Run the fused stage chain over one block (executes inside a task)."""
    if not stages:
        yield block
        return
    head, rest = stages[0], stages[1:]
    for out in head(block):
        yield from apply_stages(out, rest)


def _source_task_fn(source, stages_blob: bytes):
    """Body of one fused streaming source task: read -> stages -> yield.

    `source` arrives as either a pickled read callable (bytes) or the
    BLOCK VALUE itself: a materialized ObjectRef source is passed as a real
    task arg (so borrow accounting pins it) and the runtime resolves ref
    args to values before execution.
    """
    import cloudpickle as cp

    stages = cp.loads(stages_blob)
    if isinstance(source, (bytes, bytearray)):
        blocks: Iterator[Any] = cp.loads(source)()  # read callable
    else:
        blocks = iter([source])  # already-resolved materialized block
    for block in blocks:
        yield from apply_stages(block, stages)
