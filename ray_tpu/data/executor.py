"""Streaming executor: drives fused per-block pipelines through the task
runtime with bounded in-flight work.

Analogue of the reference's streaming execution (reference:
python/ray/data/_internal/execution/streaming_executor.py:61 executor loop,
streaming_executor_state.py select_operator_to_run/process_completed_tasks,
logical/optimizers.py operator fusion). Redesigned for the linear plans this
framework supports: consecutive map-like stages FUSE into one remote task
per block (the reference's MapOperator fusion rule), and the executor is a
pull-based generator — blocks are submitted as a sliding window
(backpressure = window size) and yielded in order as they complete, so
downstream consumption (e.g. feeding a TPU train step) overlaps with
upstream task execution.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional

import ray_tpu
from ray_tpu.utils import get_logger

logger = get_logger("data.executor")

# In-flight block-task window (reference analogue: resource_manager.py
# ReservationOpResourceAllocator, collapsed to a static window).
DEFAULT_WINDOW = 8


def _apply_stages(block, stages):
    """Run the fused stage chain over one block (executes inside a task)."""
    for fn in stages:
        block = fn(block)
    return block


def execute_streaming(input_refs: List[Any], stages: List[Callable],
                      window: int = DEFAULT_WINDOW,
                      resources: Optional[dict] = None) -> Iterator[Any]:
    """Yield output block refs in input order, keeping at most `window`
    fused-block tasks in flight."""
    if not stages:
        yield from input_refs
        return

    import cloudpickle
    stages_blob = cloudpickle.dumps(stages)

    @ray_tpu.remote
    def _fused(blob, block):
        import cloudpickle as cp
        return _apply_stages(block, cp.loads(blob))

    task = _fused.options(resources=resources) if resources else _fused

    pending: List[Any] = []
    it = iter(input_refs)
    exhausted = False
    while True:
        while not exhausted and len(pending) < window:
            try:
                ref = next(it)
            except StopIteration:
                exhausted = True
                break
            pending.append(task.remote(stages_blob, ref))
        if not pending:
            return
        head = pending.pop(0)
        yield head


def execute_to_blocks(input_refs: List[Any], stages: List[Callable],
                      window: int = DEFAULT_WINDOW) -> List[Any]:
    return list(execute_streaming(input_refs, stages, window))
