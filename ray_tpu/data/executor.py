"""Streaming executor: drives per-source block pipelines through the task
runtime as STREAMING GENERATOR tasks with bounded in-flight work.

Analogue of the reference's streaming execution (reference:
python/ray/data/_internal/execution/streaming_executor.py:61 executor loop,
streaming_executor_state.py select_operator_to_run/process_completed_tasks,
operators/map_operator.py tasks returning ObjectRefGenerators of blocks,
logical/optimizers.py operator fusion). Redesigned for the linear plans this
framework supports:

  * ALL map-like stages FUSE into the read/source task — one streaming
    remote task per source yields transformed blocks as they are produced
    (the reference's MapOperator fusion rule taken to its limit).
  * Backpressure is the generator backpressure built into the runtime: a
    producer task stalls once `streaming_generator_backpressure_items`
    yielded blocks sit unconsumed, so the executor needs no resource
    manager of its own for the linear case.
  * The executor keeps `window` source tasks active and yields block refs
    in source order — downstream consumption (a TPU train step) overlaps
    with upstream reads and transforms.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.utils import get_logger

logger = get_logger("data.executor")

# Number of source tasks kept in flight (reference analogue:
# resource_manager.py ReservationOpResourceAllocator, collapsed to a window;
# per-task block backpressure bounds memory within each).
DEFAULT_WINDOW = 2

# A stage maps one block to zero or more output blocks.
Stage = Callable[[Any], Iterator[Any]]


def apply_stages(block: Any, stages: List[Stage]) -> Iterator[Any]:
    """Run the fused stage chain over one block (executes inside a task)."""
    if not stages:
        yield block
        return
    head, rest = stages[0], stages[1:]
    for out in head(block):
        yield from apply_stages(out, rest)


def _source_task_fn(source, stages_blob: bytes):
    """Body of one fused streaming source task: read -> stages -> yield.

    `source` arrives as either a pickled read callable (bytes) or the
    BLOCK VALUE itself: a materialized ObjectRef source is passed as a real
    task arg (so borrow accounting pins it) and the runtime resolves ref
    args to values before execution.
    """
    import cloudpickle as cp

    stages = cp.loads(stages_blob)
    if isinstance(source, (bytes, bytearray)):
        blocks: Iterator[Any] = cp.loads(source)()  # read callable
    else:
        blocks = iter([source])  # already-resolved materialized block
    for block in blocks:
        yield from apply_stages(block, stages)


def execute_streaming(sources: List[Any], stages: List[Stage],
                      window: int = DEFAULT_WINDOW,
                      resources: Optional[dict] = None) -> Iterator[Any]:
    """Yield output block refs in source order.

    `sources` entries are either ObjectRefs of materialized blocks or
    zero-arg callables yielding blocks (read tasks). With no stages,
    materialized refs pass through without spawning tasks.
    """
    import cloudpickle

    if not stages and all(isinstance(s, ray_tpu.ObjectRef) for s in sources):
        yield from sources
        return

    stages_blob = cloudpickle.dumps(stages)

    remote_fn = ray_tpu.remote(num_returns="streaming")(_source_task_fn)
    if resources:
        remote_fn = remote_fn.options(resources=resources)

    def _wire_source(s):
        return s if isinstance(s, ray_tpu.ObjectRef) else \
            cloudpickle.dumps(s)

    window = max(1, window)
    gens: List[Any] = []
    idx = 0
    # Prime the window, then drain generators in order, topping up as
    # sources complete. Each active generator produces autonomously into
    # its backpressure window.
    while idx < len(sources) and len(gens) < window:
        gens.append(remote_fn.remote(_wire_source(sources[idx]),
                                     stages_blob))
        idx += 1
    while gens:
        head = gens.pop(0)
        for ref in head:
            yield ref
        if idx < len(sources) and len(gens) < window:
            gens.append(remote_fn.remote(_wire_source(sources[idx]),
                                         stages_blob))
            idx += 1


def execute_to_blocks(sources: List[Any], stages: List[Stage],
                      window: int = DEFAULT_WINDOW) -> List[Any]:
    return list(execute_streaming(sources, stages, window))
