"""Batch LLM inference over Datasets.

Analogue of the reference's Data+LLM bridge (reference:
python/ray/llm/_internal/batch/processor/ — build_llm_processor wraps a
vLLM engine as a Dataset stage with actor-pool concurrency). Here the
stage hosts THIS framework's paged-KV engine: each pool actor builds the
engine once, and a batch's prompts are submitted together so the
engine's continuous batching decodes them concurrently across slots —
offline throughput from the same machinery that serves online traffic.

    from ray_tpu.data.llm import build_llm_processor
    from ray_tpu.serve.llm import LLMConfig

    proc = build_llm_processor(LLMConfig(...), max_tokens=32)
    out = proc(ds)           # adds a "generated" column
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional


class _LLMBatchWorker:
    """Actor-pool stage body: one engine per pool actor."""

    def __init__(self, cfg_blob: bytes, prompt_column: str,
                 output_column: str, max_tokens: int, temperature: float,
                 top_k: int, seed: int):
        import cloudpickle

        from ray_tpu.serve.engine import Engine
        from ray_tpu.serve.llm import _model_from_cfg

        cfg = cloudpickle.loads(cfg_blob)
        self.cfg = cfg
        self.mcfg, params = _model_from_cfg(cfg)
        self.engine = Engine(params, self.mcfg,
                             n_slots=cfg.max_ongoing_requests,
                             decode_chunk=cfg.decode_chunk,
                             page_size=cfg.page_size,
                             n_pages=cfg.kv_pages)
        self.prompt_column = prompt_column
        self.output_column = output_column
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.seed = seed

    def _row_seed(self, ids) -> int:
        """Per-row sampling seed derived from the prompt CONTENT plus
        the configured seed: identical across reruns AND across
        batch-size changes, with distinct Gumbel streams for distinct
        prompts (r5 advisor — seed+index-within-batch reused streams
        across batches and shifted them when batch_size changed)."""
        import hashlib
        h = hashlib.blake2b(digest_size=8)
        h.update(int(self.seed).to_bytes(8, "little", signed=True))
        for t in ids:
            h.update(int(t).to_bytes(4, "little", signed=True))
        return int.from_bytes(h.digest(), "little")

    def __call__(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        import numpy as np

        from ray_tpu.serve.llm import _encode_prompt

        prompts = batch[self.prompt_column]
        # Submit the WHOLE batch first: the engine's continuous batching
        # decodes all of them concurrently across KV slots.
        streams = []
        for prompt in prompts:
            if isinstance(prompt, np.ndarray):
                prompt = prompt.tolist()
            ids = _encode_prompt(self.cfg, prompt)
            streams.append(self.engine.submit(
                ids, self.max_tokens, temperature=self.temperature,
                top_k=self.top_k,
                seed=self._row_seed(ids) if self.temperature > 0 else 0))
        outs = []
        for q in streams:
            toks: list = []
            while True:
                item = q.get()
                if item is None:
                    break
                toks.extend(item)
            if self.cfg.detokenizer is not None:
                outs.append(self.cfg.detokenizer(toks))
            else:
                outs.append(np.asarray(toks, np.int32))
        from ray_tpu.data.block import _column_array
        out = dict(batch)
        out[self.output_column] = _column_array(outs)
        return out


def build_llm_processor(cfg, *, prompt_column: str = "prompt",
                        output_column: str = "generated",
                        max_tokens: int = 16, temperature: float = 0.0,
                        top_k: int = 0, seed: int = 0,
                        batch_size: int = 32,
                        concurrency: int = 1) -> Callable:
    """Dataset -> Dataset stage generating completions for
    `prompt_column` (token-id lists, or strings via cfg.tokenizer)
    into `output_column`. `concurrency` engines run as an actor pool
    (one TPU each when cfg.num_tpus is set). Reference:
    ray.data.llm.build_llm_processor."""
    import cloudpickle

    blob = cloudpickle.dumps(cfg)

    def apply(ds):
        res = {"TPU": float(cfg.num_tpus)} if cfg.num_tpus else None
        return ds.map_batches(
            _LLMBatchWorker, batch_size=batch_size,
            concurrency=max(1, concurrency),  # engines live in actors
            fn_constructor_args=(blob, prompt_column, output_column,
                                 max_tokens, temperature, top_k, seed),
            resources=res)

    return apply
