"""streaming_split — n consumers over one shared streaming execution.

Analogue of the reference's streaming_split (reference:
python/ray/data/dataset.py:1826 + _internal/execution/operators/
output_splitter.py, coordinated by a SplitCoordinator actor): a coordinator
actor drives the dataset's streaming executor once and hands out block refs
to consumers on demand. First-come-first-served hand-out doubles as dynamic
load balancing (the reference's equal=False mode); equal=True enforces
strict round-robin so every consumer sees the same number of blocks (SPMD
train loops need equal step counts).
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

import ray_tpu


class _SplitCoordinator:
    """Actor: owns the single streaming execution; consumers pull blocks.

    equal=True slices EVERY upstream block into n equal-row sub-blocks
    (consumer i always gets slice i), so all consumers see identical block
    AND row counts regardless of upstream block-count divisibility — an
    SPMD train loop running a collective per batch stays in lockstep. Up
    to n-1 remainder rows per block are dropped (the reference's
    equal=True similarly discards rows to equalize output splits).
    """

    def __init__(self, ds_blob: bytes, n: int, equal: bool):
        import cloudpickle

        ds = cloudpickle.loads(ds_blob)
        self._n = n
        self._equal = equal
        self._it = ds.iter_block_refs()
        self._lock = threading.Lock()
        self._exhausted = False
        # equal mode: per-consumer queues of equal-row sub-block refs.
        self._queues: List[List[Any]] = [[] for _ in range(n)]

    def _pump_equal_once(self) -> bool:
        """Slice one upstream block into n equal sub-blocks; False at end."""
        import ray_tpu
        from ray_tpu.data.block import BlockAccessor

        try:
            ref = next(self._it)
        except StopIteration:
            self._exhausted = True
            return False
        acc = BlockAccessor(ray_tpu.get(ref))
        rows = acc.num_rows()
        per = rows // self._n
        if per == 0:
            return True  # block smaller than n rows: drop (all-equal: none)
        for i in range(self._n):
            self._queues[i].append(
                ray_tpu.put(acc.slice(i * per, (i + 1) * per)))
        return True

    def next_block(self, split_idx: int):
        """Next block ref for consumer split_idx, or None when exhausted."""
        with self._lock:
            if self._equal:
                q = self._queues[split_idx]
                while not q and not self._exhausted:
                    self._pump_equal_once()
                return q.pop(0) if q else None
            # Dynamic mode: whoever asks first gets the next block.
            if self._exhausted:
                return None
            try:
                return next(self._it)
            except StopIteration:
                self._exhausted = True
                return None


def create_streaming_split(ds, n: int, *, equal: bool = False):
    import cloudpickle

    from ray_tpu.data.dataset import DataIterator

    coordinator = ray_tpu.remote(_SplitCoordinator).remote(
        cloudpickle.dumps(ds), n, equal)

    def make_factory(idx: int):
        def factory():
            while True:
                ref = ray_tpu.get(coordinator.next_block.remote(idx))
                if ref is None:
                    return
                yield ref

        return factory

    iters = [DataIterator(make_factory(i), name=f"split{i}/{n}")
             for i in range(n)]
    # Keep the coordinator alive as long as the iterators are.
    for it in iters:
        it._coordinator = coordinator
    return iters
