"""Compiled actor DAGs (aDAG-lite).

Analogue of the reference's compiled graphs (reference: python/ray/dag/ —
dag_node.py lazy nodes, input_node.py InputNode, output_node.py
MultiOutputNode, compiled_dag_node.py CompiledDAG:805 with NCCL channels
and overlap scheduling; collective_node.py:252 CollectiveOutputNode). TPU
redesign: the lazy ``bind`` API is kept verbatim; compilation
topologically sorts the graph ONCE and replays it per execute() with
direct pipelined actor pushes and ObjectRef plumbing. Edges marked
``.with_tensor_transport()`` move their tensors over the DEVICE plane:
the producer keeps the array in HBM and ships a tiny DeviceRef; the
consumer pulls it device-to-device through the PJRT transfer server (DMA
on TPU) — no host pickle round-trip. ``allreduce([...])`` is the in-DAG
collective node.

    with InputNode() as inp:
        x = preproc.run.bind(inp).with_tensor_transport()
        y = model.forward.bind(x)
        dag = MultiOutputNode([y, postproc.run.bind(y)])
    compiled = dag.experimental_compile()
    out_refs = compiled.execute(batch)

    # in-DAG collective: one output per participating actor
    outs = allreduce([w1.grad.bind(inp), w2.grad.bind(inp)], op="mean")
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional


class DAGNode:
    def __init__(self):
        self._upstream: List["DAGNode"] = []

    def experimental_compile(self) -> "CompiledDAG":
        return CompiledDAG(self)

    def execute(self, *args) -> Any:
        """Eager one-shot execution (compiles a throwaway plan)."""
        return CompiledDAG(self).execute(*args)


class InputNode(DAGNode):
    """The DAG's input placeholder (reference: input_node.py). Usable as
    a context manager purely for the reference's familiar spelling — the
    graph edges come from passing the node into bind()."""

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        pass


def _scan_nodes(value, out: List["DAGNode"]) -> None:
    """Collect DAGNodes nested inside containers (one task arg may be a
    list/tuple/dict holding node outputs)."""
    if isinstance(value, DAGNode):
        out.append(value)
    elif isinstance(value, (list, tuple)):
        for v in value:
            _scan_nodes(v, out)
    elif isinstance(value, dict):
        for v in value.values():
            _scan_nodes(v, out)


def _substitute(value, resolved: Dict[int, Any]):
    if isinstance(value, DAGNode):
        return resolved[id(value)]
    if isinstance(value, list):
        return [_substitute(v, resolved) for v in value]
    if isinstance(value, tuple):
        return tuple(_substitute(v, resolved) for v in value)
    if isinstance(value, dict):
        return {k: _substitute(v, resolved) for k, v in value.items()}
    return value


class ClassMethodNode(DAGNode):
    """One actor-method invocation in the graph (reference:
    dag/class_node.py ClassMethodNode)."""

    def __init__(self, actor, method_name: str, args: tuple, kwargs: dict):
        super().__init__()
        self.actor = actor
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs
        self.tensor_transport = False
        found: List[DAGNode] = []
        _scan_nodes(list(args) + list(kwargs.values()), found)
        self._upstream.extend(found)

    def with_tensor_transport(self, transport: str = "auto"
                              ) -> "ClassMethodNode":
        """Keep this node's output in device memory: downstream nodes
        receive it device-to-device over the transfer plane instead of
        through the host object path (reference:
        dag_node.py with_tensor_transport / TorchTensorType hints)."""
        self.tensor_transport = True
        return self


class AllReduceNode(DAGNode):
    """One participant's output of an in-DAG allreduce (reference:
    dag/collective_node.py:252 CollectiveOutputNode). Created via
    `allreduce(nodes, op)`; executes on the same actor as its input."""

    def __init__(self, input_node: ClassMethodNode, rank: int,
                 group: List[ClassMethodNode], op: str):
        super().__init__()
        self.input_node = input_node
        self.rank = rank
        self.group = group
        self.op = op
        self._upstream = list(group)  # needs every participant's tensor


def allreduce(nodes: List[ClassMethodNode],
              op: str = "sum") -> List[AllReduceNode]:
    """Bind an allreduce across the outputs of `nodes` (one per actor).
    Returns one AllReduceNode per participant, each device-resident on
    its actor. Inputs are auto-marked for tensor transport."""
    if not nodes:
        raise ValueError("allreduce needs at least one input node")
    for n in nodes:
        if not isinstance(n, ClassMethodNode):
            raise TypeError("allreduce inputs must be actor-method nodes")
        n.with_tensor_transport()
    group = list(nodes)  # ONE shared list: execute() keys the op by it
    return [AllReduceNode(n, i, group, op) for i, n in enumerate(nodes)]


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        super().__init__()
        self.outputs = list(outputs)
        self._upstream = list(outputs)


class CompiledDAG:
    """Topologically-sorted replayable plan (reference:
    compiled_dag_node.py CompiledDAG — ours replays direct actor pushes;
    the runtime already pipelines and ships refs worker-to-worker)."""

    def __init__(self, root: DAGNode):
        self._root = root
        self._order: List[DAGNode] = []
        self._input: Optional[InputNode] = None
        self._toposort(root, set())
        for node in self._order:
            if isinstance(node, InputNode):
                if self._input is not None and self._input is not node:
                    raise ValueError("a DAG supports one InputNode")
                self._input = node

    def _toposort(self, node: DAGNode, seen: set) -> None:
        if id(node) in seen:
            return
        seen.add(id(node))
        for up in node._upstream:
            self._toposort(up, seen)
        self._order.append(node)

    def execute(self, *args) -> Any:
        """Run the plan; returns the ObjectRef of the root node (or a
        list of refs for MultiOutputNode). Intermediate results flow as
        ObjectRefs straight between the actors."""
        if self._input is not None:
            if len(args) != 1:
                raise TypeError(
                    f"DAG takes exactly 1 input, got {len(args)}")
        from ray_tpu.core.ref import ActorMethod

        values: Dict[int, Any] = {}
        op_keys: Dict[int, bytes] = {}  # allreduce group -> this round's key
        for node in self._order:
            if isinstance(node, InputNode):
                values[id(node)] = args[0]
            elif isinstance(node, ClassMethodNode):
                call_args = [_substitute(a, values) for a in node.args]
                call_kwargs = {k: _substitute(v, values)
                               for k, v in node.kwargs.items()}
                device_in = any(
                    isinstance(up, (ClassMethodNode, AllReduceNode))
                    and getattr(up, "tensor_transport", True)
                    for up in node._upstream)
                if node.tensor_transport or device_in:
                    # Device-plane edge: run through the worker builtin
                    # that unwraps DeviceRef args (device-to-device pull)
                    # and/or keeps the output in HBM.
                    out_mode = "device" if node.tensor_transport else "host"
                    method = ActorMethod(node.actor, "__rt_dag_call__")
                    values[id(node)] = method.remote(
                        node.method_name, out_mode, *call_args,
                        **call_kwargs)
                else:
                    method = getattr(node.actor, node.method_name)
                    values[id(node)] = method.remote(*call_args,
                                                     **call_kwargs)
            elif isinstance(node, AllReduceNode):
                key = op_keys.get(id(node.group))
                if key is None:
                    key = op_keys[id(node.group)] = os.urandom(16)
                inputs = [values[id(n)] for n in node.group]
                method = ActorMethod(node.input_node.actor,
                                     "__rt_dag_allreduce__")
                values[id(node)] = method.remote(
                    key, node.rank, len(node.group), node.op, inputs)
            elif isinstance(node, MultiOutputNode):
                values[id(node)] = [values[id(o)] for o in node.outputs]
            else:
                raise TypeError(f"unknown DAG node {type(node).__name__}")
        return values[id(self._root)]

    def teardown(self) -> None:
        pass  # no channel resources to release in the ref-based plan
