"""Device-resident object refs (RDT) — tensors that stay in HBM.

Analogue of the reference's Ray Direct Transport (reference:
python/ray/experimental/gpu_object_manager/gpu_object_manager.py:61
GPUObjectManager — the ObjectRef travels the control plane, the tensor
stays in device memory on its owner and moves out-of-band on demand).
TPU-native shape:

    ref = device_put_ref(jax_array)        # stays in this process's HBM
    # ... ship `ref` through actor calls / task args (tiny metadata) ...
    arr = device_get(ref)                  # owner->here transfer, then
                                           # host->device onto local chips

Transfer rides the core-worker RPC plane as host bytes (the DCN-equivalent
path); intra-slice ICI device-to-device via the jax transfer server is the
planned fast path. free_ref() drops the owner's HBM reference.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from ray_tpu.core.ref import get_core_worker


class DeviceRef:
    """Handle to an array resident on its owner process's devices."""

    __slots__ = ("owner_addr", "key", "shape", "dtype")

    def __init__(self, owner_addr, key: bytes, shape, dtype: str):
        self.owner_addr = tuple(owner_addr)
        self.key = key
        self.shape = tuple(shape)
        self.dtype = dtype

    def __reduce__(self):
        return (DeviceRef, (self.owner_addr, self.key, self.shape,
                            self.dtype))

    def __repr__(self):
        return (f"DeviceRef({self.key.hex()[:8]}, shape={self.shape}, "
                f"dtype={self.dtype}, owner={self.owner_addr})")


def device_put_ref(array: Any) -> DeviceRef:
    """Register a (jax) array as device-resident in THIS process; the
    returned ref is cheap to pass around the cluster."""
    cw = get_core_worker()
    key = os.urandom(16)
    cw.put_device_object(key, array)
    return DeviceRef(cw.address, key, getattr(array, "shape", ()),
                     str(getattr(array, "dtype", "float32")))


def device_get(ref: DeviceRef, *, sharding: Optional[Any] = None,
               timeout: float = 120.0) -> Any:
    """Materialize the array locally. Same-process: zero-copy handle.
    Remote: out-of-band fetch from the owner, then jax.device_put
    (optionally with a target sharding)."""
    import numpy as np

    cw = get_core_worker()
    if tuple(ref.owner_addr) == cw.address:
        local = cw.get_device_object_local(ref.key)
        if local is None:
            raise KeyError(f"device object freed: {ref}")
        if sharding is not None:  # honor the contract on BOTH paths
            import jax
            return jax.device_put(local, sharding)
        return local
    client = cw._client_for_worker(ref.owner_addr)
    got = cw._run(client.call("fetch_device_object",
                              ref.key)).result(timeout)
    if got is None:
        raise KeyError(f"device object freed on owner: {ref}")
    data, _dtype, _shape = got  # pickle-5 already rebuilt the ndarray
    host = np.asarray(data)
    try:
        import jax
        return jax.device_put(host, sharding) if sharding is not None \
            else jax.device_put(host)
    except Exception:
        return host


def free_ref(ref: DeviceRef) -> None:
    """Drop the owner's HBM reference (idempotent)."""
    cw = get_core_worker()
    if tuple(ref.owner_addr) == cw.address:
        cw.free_device_object(ref.key)
        return
    client = cw._client_for_worker(ref.owner_addr)
    try:
        cw._run(client.call("free_device_object_remote", ref.key)).result(30)
    except Exception:
        pass
