"""Device-resident object refs (RDT) — tensors that stay in HBM.

Analogue of the reference's Ray Direct Transport (reference:
python/ray/experimental/gpu_object_manager/gpu_object_manager.py:61
GPUObjectManager — the ObjectRef travels the control plane, the tensor
stays in device memory on its owner and moves out-of-band on demand).
TPU-native shape:

    ref = device_put_ref(jax_array)        # stays in this process's HBM
    # ... ship `ref` through actor calls / task args (tiny metadata) ...
    arr = device_get(ref)                  # device-to-device pull through
                                           # the transfer plane

Ownership rides the ObjectRef protocol: a DeviceRef wraps a real
ObjectRef, so serializing it inside values registers borrows, and the
HBM array frees automatically when the last reference anywhere drops
(core_worker frees the device twin with the ledger entry). free_ref()
remains as an explicit early-free.

Transfers are device-to-device through the PJRT transfer plane
(experimental/device_plane.py — DMA over ICI/DCN on TPU); the host-bytes
RPC path survives only as a cross-backend fallback.
"""

from __future__ import annotations

from typing import Any, Optional

from ray_tpu.core.ids import ObjectID
from ray_tpu.core.ref import ObjectRef, get_core_worker

# (owner slice, local slice) pairs whose host-relay routing was logged.
_cross_slice_logged: set = set()


class DeviceRef:
    """Handle to an array resident on its owner process's devices.

    Wraps an ObjectRef (`.ref`) so reference counting, borrows, and
    owner-death cleanup work exactly like host objects. Carries the
    owner's SLICE identity so readers can route: same slice -> ICI/DMA
    transfer plane; different slice -> host relay over the object plane
    (DCN) unless cross_slice_device_dma says the plane spans slices."""

    __slots__ = ("ref", "shape", "dtype", "slice")

    def __init__(self, ref: ObjectRef, shape, dtype: str,
                 slice: str = ""):  # noqa: A002
        self.ref = ref
        self.shape = tuple(shape)
        self.dtype = dtype
        self.slice = slice

    @property
    def owner_addr(self):
        return self.ref.owner_addr

    @property
    def key(self) -> bytes:
        return self.ref.binary()

    def __reduce__(self):
        # Pickling recurses into self.ref -> ObjectRef.__reduce__ ->
        # note_contained_ref: borrower accounting comes for free.
        return (DeviceRef, (self.ref, self.shape, self.dtype, self.slice))

    def __repr__(self):
        return (f"DeviceRef({self.ref.hex()[:12]}, shape={self.shape}, "
                f"dtype={self.dtype}, owner={self.owner_addr}, "
                f"slice={self.slice!r})")


def device_put_ref(array: Any) -> DeviceRef:
    """Register a (jax) array as device-resident in THIS process; the
    returned ref is cheap to pass around the cluster and frees the HBM
    array when the last copy drops."""
    cw = get_core_worker()
    oid = ObjectID.from_put()
    ref = ObjectRef(oid, cw.address)
    cw.add_local_ref(ref)
    cw.put_device_object(oid.binary(), array)
    # Ledger entry: a tiny READY marker so get/wait/refcount see a normal
    # owned object; the array itself lives in the device table. Registered
    # synchronously (callable from exec threads AND async actor methods
    # running on the io loop).
    from ray_tpu.core import serialization
    sv = serialization.serialize({"__device_marker__": True})
    cw.put_inline_marker(oid.binary(), sv)
    from ray_tpu.accelerators import slice_name
    return DeviceRef(ref, getattr(array, "shape", ()),
                     str(getattr(array, "dtype", "float32")),
                     slice=slice_name())


def device_get(ref: DeviceRef, *, sharding: Optional[Any] = None,
               timeout: float = 120.0) -> Any:
    """Materialize the array locally. Same-process: zero-copy handle.
    Remote: device-to-device pull via the transfer plane (host-bytes RPC
    only as a cross-backend fallback), then optional resharding."""
    import jax

    cw = get_core_worker()
    key = ref.key
    if ref.owner_addr is None or tuple(ref.owner_addr) == cw.address:
        local = cw.get_device_object_local(key)
        if local is None:
            raise KeyError(f"device object freed: {ref}")
        if sharding is not None:  # honor the contract on BOTH paths
            return jax.device_put(local, sharding)
        return local
    client = cw._client_for_worker(tuple(ref.owner_addr))
    # Slice-aware routing (SURVEY §5.8 two-plane mapping): the transfer
    # plane is an ICI/DMA-domain transport — across slice boundaries it
    # only applies when the deployment says the plane spans slices
    # (cross_slice_device_dma); otherwise relay device->host->DCN->device
    # through the ordinary object-plane RPC. Decided BEFORE
    # device_pull_info so no ticket is staged (staging pins the array).
    from ray_tpu.accelerators import slice_name
    from ray_tpu.utils.config import GlobalConfig
    cross_slice = getattr(ref, "slice", "") != slice_name()
    if cross_slice and not GlobalConfig.cross_slice_device_dma:
        # Once per (owner slice, local slice) pair: an env asymmetry in
        # TPU_NAME would silently demote SAME-slice pulls to host-relay
        # speed forever — make the routing decision observable.
        pair = (getattr(ref, "slice", ""), slice_name())
        if pair not in _cross_slice_logged:
            _cross_slice_logged.add(pair)
            from ray_tpu.utils import get_logger
            get_logger("device_objects").info(
                "cross-slice device_get (owner slice %r, local slice %r): "
                "host-relaying over the object plane; set "
                "cross_slice_device_dma=true if the transfer plane spans "
                "these slices", pair[0], pair[1])
        info = None
    else:
        try:
            info = cw._run(client.call("device_pull_info", key,
                                       wait_s=0.0)).result(timeout)
        except Exception:
            # Owner can't stage (e.g. no transfer plane on its backend):
            # the host-bytes endpoint below still works.
            info = None
    if info is not None:
        from ray_tpu.experimental.device_plane import DevicePlane
        addr, uuid, descs = info
        try:
            arr = DevicePlane.get().pull(addr, uuid, descs)[0]
            if sharding is not None:
                arr = jax.device_put(arr, sharding)
            return arr
        except Exception as e:
            # Fall through to host bytes, but LOUDLY: a deployment whose
            # fast path never works (bad RAY_TPU_NODE_IP, backend
            # mismatch) must not silently run at host-copy speed.
            from ray_tpu.utils import get_logger
            get_logger("device_objects").warning(
                "device-plane pull from %s failed (%r); falling back to "
                "host-bytes transfer", addr, e)
    import numpy as np
    got = cw._run(client.call("fetch_device_object", key)).result(timeout)
    if got is None:
        raise KeyError(f"device object freed on owner: {ref}")
    data, _dtype, _shape = got  # pickle-5 already rebuilt the ndarray
    host = np.asarray(data)
    try:
        return jax.device_put(host, sharding) if sharding is not None \
            else jax.device_put(host)
    except Exception:
        return host


def device_ingest(ref: ObjectRef, *, sharding: Optional[Any] = None) -> Any:
    """Host-store object -> device, WITHOUT materializing intermediate
    host bytes.

    ``get(ref)`` on the graftshm plane already yields numpy arrays that
    are zero-copy READ-ONLY views into the store's shared mapping
    (pickle-5 out-of-band buffers over the sealed slab). The missing leg
    is handing those views to jax: numpy and jax both refuse
    ``__dlpack__`` on read-only arrays, so each array leaf is wrapped in
    a hand-rolled DLPack capsule (graftshm.DLPackExporter) and ingested
    with ``jax.dlpack.from_dlpack`` — the device copy (or CPU-backend
    buffer) is fed straight from the mapped pages. The view itself pins
    the mapping until every consumer's deleter runs.

    Non-array leaves pass through unchanged; arrays whose dtype or
    layout has no DLPack mapping fall back to a plain device_put."""
    import jax
    import numpy as np

    from ray_tpu import api
    from ray_tpu.core._native.graftshm import DLPackExporter

    value = api.get(ref)

    def _leaf(x):
        if not isinstance(x, np.ndarray):
            return x
        try:
            arr = jax.dlpack.from_dlpack(DLPackExporter(x))
        except (TypeError, ValueError, RuntimeError):
            # Non-contiguous slice or a dtype without a DLPack mapping:
            # the ordinary (copying) placement still works for numeric
            # arrays; truly non-device-able leaves (object dtype) stay
            # host-side untouched.
            try:
                arr = jax.device_put(np.ascontiguousarray(x))
            except (TypeError, ValueError):
                return x
        if sharding is not None:
            arr = jax.device_put(arr, sharding)
        return arr

    return jax.tree_util.tree_map(_leaf, value)


def free_ref(ref: DeviceRef) -> None:
    """Explicitly drop the owner's HBM array now (idempotent). The
    ledger entry still follows normal refcounting."""
    cw = get_core_worker()
    if ref.owner_addr is None or tuple(ref.owner_addr) == cw.address:
        cw.free_device_object(ref.key)
        return
    client = cw._client_for_worker(tuple(ref.owner_addr))
    try:
        cw._run(client.call("free_device_object_remote",
                            ref.key)).result(30)
    except Exception:
        pass
