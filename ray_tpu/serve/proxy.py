"""HTTP proxy — the ingress data plane.

Analogue of the reference's proxy (reference: serve/_private/proxy.py
HTTPProxy:706 — ASGI server resolving routes to deployment handles,
streaming responses). Minimal asyncio HTTP/1.1 server: POST/GET
/{route_prefix} with a JSON body dispatches to the deployment's handle
via the pow-2 router; generator deployments stream chunked responses.
Run one per node (reference runs one ProxyActor per node).
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.utils import get_logger

logger = get_logger("serve.proxy")


class HttpProxy:
    def __init__(self, controller_handle, host: str = "127.0.0.1",
                 port: int = 0):
        from ray_tpu.serve.routing import RouteTable
        self._controller = controller_handle
        self._host = host
        self.port = port
        self._table = RouteTable(controller_handle)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._serve_thread,
                                        daemon=True, name="http-proxy")
        self._thread.start()
        self._started.wait(30)

    # -- server plumbing -------------------------------------------------
    def _serve_thread(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        server = self._loop.run_until_complete(
            asyncio.start_server(self._on_client, self._host, self.port))
        self.port = server.sockets[0].getsockname()[1]
        self._started.set()
        try:
            self._loop.run_forever()
        finally:
            server.close()

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)

    # -- routing (table shared with the gRPC ingress: routing.py) --------
    async def _handle_for(self, path: str) -> Optional[DeploymentHandle]:
        name = self._table.match(path)
        if name is None and self._table.should_refresh():
            # Refresh OFF the event loop (a blocking controller RPC here
            # would stall every in-flight connection), rate-limited so
            # 404 scans can't DoS the ingress.
            await asyncio.get_running_loop().run_in_executor(
                None, self._table.refresh)
            name = self._table.match(path)
        if name is None:
            return None
        return self._table.handle_for(name)

    # -- request handling -------------------------------------------------
    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                request = await self._read_request(reader)
                if request is None:
                    return
                method, path, headers, body = request
                await self._dispatch(method, path, headers, body, writer)
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()

    async def _read_request(self, reader: asyncio.StreamReader):
        try:
            line = await reader.readline()
        except (ConnectionResetError, asyncio.LimitOverrunError):
            return None
        if not line:
            return None
        try:
            method, path, _version = line.decode().split()
        except ValueError:
            return None
        headers: Dict[str, str] = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        try:
            length = int(headers.get("content-length", 0))
        except ValueError:
            return None  # malformed header: drop the connection politely
        body = await reader.readexactly(length) if length > 0 else b""
        return method, path, headers, body

    @staticmethod
    def _respond(writer: asyncio.StreamWriter, status: int, payload: bytes,
                 content_type: str = "application/json") -> None:
        reason = {200: "OK", 404: "Not Found", 500: "Internal Server Error"}
        writer.write(
            f"HTTP/1.1 {status} {reason.get(status, '')}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(payload)}\r\n"
            f"Connection: keep-alive\r\n\r\n".encode() + payload)

    async def _dispatch(self, method: str, path: str, headers, body: bytes,
                        writer: asyncio.StreamWriter) -> None:
        if path == "/-/healthz":
            self._respond(writer, 200, b'{"status":"ok"}')
            await writer.drain()
            return
        if path == "/-/routes":
            await asyncio.get_running_loop().run_in_executor(
                None, self._table.refresh)
            self._respond(writer, 200,
                          json.dumps(self._table.routes).encode())
            await writer.drain()
            return
        handle = await self._handle_for(path)
        if handle is None:
            self._respond(writer, 404,
                          json.dumps({"error": f"no route for {path}"})
                          .encode())
            await writer.drain()
            return
        try:
            payload = json.loads(body) if body else None
        except json.JSONDecodeError:
            payload = body.decode(errors="replace")
        loop = asyncio.get_running_loop()
        stream = headers.get("x-serve-stream", "").lower() in ("1", "true")
        if stream:
            # Stream errors terminate the chunked body/connection; a 500
            # status after chunks were sent would corrupt the protocol.
            await self._stream_response(handle, payload, writer, loop)
            return
        try:
            response = await loop.run_in_executor(
                None, lambda: handle.remote(payload).result(timeout=120))
            self._respond(writer, 200, json.dumps(
                {"result": response}).encode())
        except Exception as e:
            self._respond(writer, 500,
                          json.dumps({"error": repr(e)}).encode())
        await writer.drain()

    async def _stream_response(self, handle, payload, writer,
                               loop) -> None:
        """Chunked transfer from a streaming deployment method — tokens
        flow as the replica yields (TTFT = first chunk)."""
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/plain\r\n"
                     b"Transfer-Encoding: chunked\r\n\r\n")
        await writer.drain()
        # Bounded: a fast producer must not buffer an entire generation
        # for a slow client (the pump blocks on put until the writer
        # drains).
        q: asyncio.Queue = asyncio.Queue(maxsize=16)
        gone = threading.Event()  # client disconnected: stop the producer

        class _ClientGone(Exception):
            pass

        def put_blocking(msg) -> None:
            # Short waits + gone polling: after a disconnect nobody drains
            # the queue, and a blind long block would pin this thread (and
            # the replica-side stream) for minutes.
            # Wait on ONE put future, polling gone between timeouts — a
            # cancel-and-resubmit loop could land the same chunk twice
            # when the cancel races a just-completed put.
            fut = asyncio.run_coroutine_threadsafe(q.put(msg), loop)
            while True:
                try:
                    fut.result(0.5)
                    return
                except TimeoutError:
                    if gone.is_set():
                        fut.cancel()
                        raise _ClientGone()

        def pump():
            it = None
            try:
                it = handle.stream(payload)
                for item in it:
                    if gone.is_set():
                        raise _ClientGone()
                    put_blocking(("item", item))
            except _ClientGone:
                pass
            except BaseException as e:  # noqa: BLE001
                try:
                    put_blocking(("err", repr(e)))
                except Exception:
                    pass
            finally:
                close = getattr(it, "close", None)
                if close:
                    close()  # releases the replica-side stream
                try:
                    put_blocking(("end", None))
                except Exception:
                    pass

        threading.Thread(target=pump, daemon=True).start()
        try:
            while True:
                kind, item = await q.get()
                if kind == "end":
                    break
                if kind == "err":
                    chunk = json.dumps({"error": item}).encode()
                else:
                    chunk = (item if isinstance(item, (bytes, bytearray))
                             else str(item).encode())
                if not chunk:
                    continue  # a 0-length chunk IS the stream terminator
                writer.write(f"{len(chunk):x}\r\n".encode() + chunk
                             + b"\r\n")
                await writer.drain()
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            gone.set()  # don't decode for a client that left
            raise
