"""Model multiplexing: many models share a pool of replicas.

Analogue of the reference's multiplexing (reference: serve/multiplex.py
_ModelMultiplexWrapper + serve/api.py @serve.multiplexed +
get_multiplexed_model_id): a replica lazily loads models on demand and
keeps an LRU of at most `max_num_models_per_replica`; the handle tags
requests with `options(multiplexed_model_id=...)`, the router sticks a
model's requests to the replica that already holds it, and the loader
inside the replica reads the id via `get_multiplexed_model_id()`.

    @serve.deployment
    class Mux:
        def __init__(self):
            self._get = serve.multiplexed(
                max_num_models_per_replica=2)(self._load)

        def _load(self, model_id: str):
            return load_weights(model_id)          # slow, cached

        def __call__(self, body):
            model = self._get(serve.get_multiplexed_model_id())
            return model.predict(body)
"""

from __future__ import annotations

import contextvars
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

_current_model_id: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """The model id the CURRENT request was tagged with
    (handle.options(multiplexed_model_id=...)); "" when untagged."""
    return _current_model_id.get()


def _set_current_model_id(model_id: str):
    return _current_model_id.set(model_id or "")


class _ModelMultiplexWrapper:
    """Per-replica LRU of loaded models keyed by model id."""

    def __init__(self, loader: Callable[[str], Any], max_models: int):
        self._loader = loader
        self._max = max_models
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = threading.Lock()

    def __call__(self, model_id: str) -> Any:
        if not model_id:
            raise ValueError(
                "no multiplexed model id on this request — call with "
                "handle.options(multiplexed_model_id=...)")
        with self._lock:
            model = self._models.get(model_id)
            if model is not None:
                self._models.move_to_end(model_id)
                return model
        # Load OUTSIDE the lock (loads are slow); a racing duplicate load
        # of the same id is wasteful but harmless (last one wins).
        model = self._loader(model_id)
        with self._lock:
            self._models[model_id] = model
            self._models.move_to_end(model_id)
            while len(self._models) > self._max:
                self._models.popitem(last=False)  # LRU eviction
        return model

    @property
    def loaded_model_ids(self):
        with self._lock:
            return list(self._models)


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator/wrapper producing a per-replica multiplexed loader
    (reference: serve/api.py multiplexed)."""
    def wrap(f: Callable) -> _ModelMultiplexWrapper:
        return _ModelMultiplexWrapper(f, max_num_models_per_replica)

    if func is not None:
        return wrap(func)
    return wrap
