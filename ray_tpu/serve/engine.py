"""Continuous-batching LLM decode engine with a PAGED KV cache.

The TPU-native answer to the reference's vLLM delegation (reference:
python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_models.py:170 —
engine_kwargs feed vLLM's continuous batcher + paged attention; here the
engine is OURS):

- **Paged KV arena** `[n_layers, n_pages, page, kv_heads, head_dim]` with
  a per-slot BLOCK TABLE `[n_slots, max_pages]` of physical page ids —
  vLLM's block-table design recast for XLA: the table is a device array,
  reads are one gather per layer (`kc[bt]`), writes are one scatter at
  each slot's position. A 50-token request holds ceil(50/page) pages, not
  a max_seq strip, so concurrency is bounded by TOKENS in flight, not by
  worst-case sequences. Page 0 is the NULL page: unused/overflow table
  entries point at it, making out-of-reservation writes harmless and
  gathers of unused pages maskable — no data-dependent control flow.
- **Reservation admission**: a request is admitted when
  ceil(min(len+max_tokens, max_seq)/page) free pages exist — growth can
  then never fail mid-decode, so there is no preemption/recompute path
  (vLLM's watermark policy, made strict). Requests queue FIFO while
  pages are short; finishing requests return their pages.
- **Sync-free dispatch loop + emitter thread**: the engine loop ONLY
  dispatches device work (prefills, decode chunks, slot pokes) — every
  host<->device sync (fetching first tokens and chunk outputs) happens
  on a separate EMITTER thread consuming a bounded FIFO. Slot/page
  control state advances deterministically on the host (token VALUES
  are the only device-dependent output), so chunks dispatch
  back-to-back and admissions slot in mid-pipeline; the tunnel/host
  round-trip is paid off the critical path. The FIFO bound (see
  `_emit_q`) is the pipeline depth. One fixed-shape XLA program serves
  every step (no recompiles).

A small fixed set of compiled programs serves all traffic: one prefill
per power-of-2 BUCKET width (a short prompt pays a short prefill — the
TTFT lever; smallest and largest warmed at startup, others on first use)
and the n-step decode chunk over all slots.
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple


def _make_prefill_core(mcfg):
    """fn(params, tokens[1, B], length) -> (first_token, ks, vs) where
    ks/vs are [L, B, KVH, hd] — the shared prefill pass used by the
    in-engine prefill AND the disaggregated PrefillServer (reference:
    llm/_internal/serve/deployments/prefill_decode_disagg/ — there the
    split is two vLLM pools; here both halves share one traced core)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.attention import flash_attention, repeat_kv
    from ray_tpu.ops.norms import apply_rope, rms_norm, rope_frequencies

    H, KVH, hd = mcfg.n_heads, mcfg.n_kv_heads, mcfg.head_dim
    dt = mcfg.dtype

    def _prefill_layer(carry, lp):
        x, cos, sin = carry
        B, Sq, _ = x.shape
        h = rms_norm(x, lp["attn_norm"], mcfg.norm_eps)
        q = jnp.einsum("bsd,dh->bsh", h, lp["wq"].astype(dt))
        k = jnp.einsum("bsd,dh->bsh", h, lp["wk"].astype(dt))
        v = jnp.einsum("bsd,dh->bsh", h, lp["wv"].astype(dt))
        q = q.reshape(B, Sq, H, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B, Sq, KVH, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, Sq, KVH, hd).transpose(0, 2, 1, 3)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = flash_attention(q, repeat_kv(k, H // KVH),
                               repeat_kv(v, H // KVH), True)
        attn = attn.transpose(0, 2, 1, 3).reshape(B, Sq, H * hd)
        x = x + jnp.einsum("bsh,hd->bsd", attn, lp["wo"].astype(dt))
        h = rms_norm(x, lp["mlp_norm"], mcfg.norm_eps)
        gate = jnp.einsum("bsd,df->bsf", h, lp["w_gate"].astype(dt))
        up = jnp.einsum("bsd,df->bsf", h, lp["w_up"].astype(dt))
        x = x + jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up,
                           lp["w_down"].astype(dt))
        # cache pre-repeat k/v: [S, KVH, hd] (B == 1 squeezed)
        return (x, cos, sin), (k[0].transpose(1, 0, 2),
                               v[0].transpose(1, 0, 2))

    def core(params, tokens, length):
        x = jnp.take(params["embed"], tokens, axis=0).astype(dt)
        cos, sin = rope_frequencies(hd, tokens.shape[1], mcfg.rope_theta)
        (x, _, _), (ks, vs) = jax.lax.scan(
            _prefill_layer, (x, cos, sin), params["layers"])
        x = rms_norm(x, params["final_norm"], mcfg.norm_eps)
        last_h = jax.lax.dynamic_index_in_dim(x, length - 1, axis=1,
                                              keepdims=False)
        logits = jnp.einsum("bd,dv->bv", last_h,
                            params["lm_head"].astype(dt))
        first = jnp.argmax(logits[0]).astype(jnp.int32)
        return first, ks, vs, logits[0].astype(jnp.float32)

    return core


# Compile-time cap on per-request top_k (jax.lax.top_k needs a static
# width; requests asking for more sample from the best TOPK_CAP).
TOPK_CAP = 64


def _sample_tokens(logits, temp, topk, keys, pos, cap=TOPK_CAP):
    """Per-slot token sampling (reference: vLLM's sampler): temperature
    + top-k via Gumbel-max over the top-`cap` logits (cap is a static
    trace-time width, min(TOPK_CAP, vocab)); temp==0 slots stay greedy.
    `keys` are per-slot base PRNG keys; folding in `pos` makes a
    request's sample stream deterministic for its (seed, position)
    regardless of slot assignment or co-tenants."""
    import jax
    import jax.numpy as jnp

    cap = min(cap, logits.shape[-1])
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    vals, idxs = jax.lax.top_k(logits.astype(jnp.float32), cap)
    k_eff = jnp.where(topk > 0, jnp.minimum(topk, cap), cap)
    mask = jnp.arange(cap)[None, :] < k_eff[:, None]
    scaled = jnp.where(mask, vals / jnp.maximum(temp, 1e-6)[:, None],
                       -1e30)

    def one_gumbel(key, p):
        return jax.random.gumbel(jax.random.fold_in(key, p), (cap,))

    g = jax.vmap(one_gumbel)(keys, pos)
    pick = jnp.argmax(scaled + g, axis=-1)
    sampled = jnp.take_along_axis(idxs, pick[:, None], axis=1)[:, 0]
    return jnp.where(temp > 0, sampled, greedy).astype(jnp.int32)


def _build_fns(mcfg, n_slots: int, chunk: int, page: int, n_pages: int):
    """Build (prefill_jit, decode_jit, adopt_jit, empty_caches)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.norms import rms_norm, rope_frequencies

    if mcfg.n_experts > 0:
        raise ValueError("the serving engine supports dense models only")

    S = mcfg.max_seq
    H, KVH, hd = mcfg.n_heads, mcfg.n_kv_heads, mcfg.head_dim
    dt = mcfg.dtype
    ns = n_slots
    maxp = -(-S // page)          # logical pages per slot
    CTX = maxp * page             # gathered context width (>= S)

    def empty_caches():
        shape = (mcfg.n_layers, n_pages, page, KVH, hd)
        return jnp.zeros(shape, dt), jnp.zeros(shape, dt)

    def _write_pages(kc, vc, pages, ks, vs):
        """Scatter prefilled [L, W, KVH, hd] k/v into physical pages.
        W is static (one program per bucket width); `pages[:wp]` entries
        of 0 route padding into the null page."""
        L, W = ks.shape[0], ks.shape[1]
        wp = -(-W // page)
        pad = wp * page - W
        ksp = jnp.pad(ks, ((0, 0), (0, pad), (0, 0), (0, 0)))
        vsp = jnp.pad(vs, ((0, 0), (0, pad), (0, 0), (0, 0)))
        ksp = ksp.reshape(L, wp, page, KVH, hd)
        vsp = vsp.reshape(L, wp, page, KVH, hd)
        kc = kc.at[:, pages[:wp]].set(ksp)
        vc = vc.at[:, pages[:wp]].set(vsp)
        return kc, vc

    # ------------------------------------------------------------------
    # prefill: full causal pass over ONE padded prompt, k/v -> pages
    # ------------------------------------------------------------------
    _core = _make_prefill_core(mcfg)

    def prefill(params, kc, vc, pages, tokens, length, temp, topk, key):
        """tokens [1, B] padded to a BUCKET width (powers of 2 up to
        max_seq — jax.jit compiles one program per bucket shape, so a
        short prompt pays a short prefill, not a max_seq one); writes
        the slot's pages, returns the first generated token (sampled,
        or greedy when temp == 0)."""
        _, ks, vs, logits_row = _core(params, tokens, length)
        kc, vc = _write_pages(kc, vc, pages, ks, vs)
        first = _sample_tokens(logits_row[None],
                               jnp.asarray(temp)[None],
                               jnp.asarray(topk)[None], key[None],
                               jnp.asarray(length - 1)[None])[0]
        return kc, vc, first

    def adopt(kc, vc, pages, ks, vs):
        """Write externally-prefilled k/v (a PrefillServer handoff) into
        the slot's pages."""
        return _write_pages(kc, vc, pages, ks, vs)

    # ------------------------------------------------------------------
    # decode: one token for every active slot per step, `chunk` steps
    # ------------------------------------------------------------------
    def _rope_one(x, c, s):
        # x [ns, heads, hd], c/s [ns, 1, hd//2]
        x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
        out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
        return out.astype(x.dtype)

    def _decode_layer(x, lp, kc_l, vc_l, bt, pos, act, cos, sin):
        # x [ns, D]; kc_l/vc_l [n_pages, page, KVH, hd]; bt [ns, maxp]
        h = rms_norm(x, lp["attn_norm"], mcfg.norm_eps)
        q = (h @ lp["wq"].astype(dt)).reshape(ns, H, hd)
        k = (h @ lp["wk"].astype(dt)).reshape(ns, KVH, hd)
        v = (h @ lp["wv"].astype(dt)).reshape(ns, KVH, hd)
        w = jnp.minimum(pos, S - 1)
        c = cos[w][:, None]
        s = sin[w][:, None]
        q = _rope_one(q, c, s)
        k = _rope_one(k, c, s)
        # Scatter k/v at each slot's (page, offset). Inactive slots (and
        # positions past a slot's reservation) route to the NULL page 0,
        # whose content is never read unmasked — the write stays a
        # fixed-shape scatter with no data-dependent branches.
        idx = jnp.arange(ns)
        pp = jnp.where(act, bt[idx, w // page], 0)
        off = jnp.where(act, w % page, 0)
        kc_l = kc_l.at[pp, off].set(k)
        vc_l = vc_l.at[pp, off].set(v)
        # Gather each slot's pages -> its logical KV history.
        kh = kc_l[bt].reshape(ns, CTX, KVH, hd)
        vh = vc_l[bt].reshape(ns, CTX, KVH, hd)
        # Grouped-query attention against the gathered history.
        qg = q.reshape(ns, KVH, H // KVH, hd).astype(jnp.float32)
        scores = jnp.einsum("nkgd,nskd->nkgs", qg,
                            kh.astype(jnp.float32)) / (hd ** 0.5)
        mask = jnp.arange(CTX)[None, :] <= w[:, None]        # [ns, CTX]
        scores = jnp.where(mask[:, None, None, :], scores, -1e30)
        wts = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("nkgs,nskd->nkgd", wts,
                          vh.astype(jnp.float32))
        attn = attn.reshape(ns, H * hd).astype(dt)
        x = x + attn @ lp["wo"].astype(dt)
        h = rms_norm(x, lp["mlp_norm"], mcfg.norm_eps)
        gate = h @ lp["w_gate"].astype(dt)
        up = h @ lp["w_up"].astype(dt)
        x = x + (jax.nn.silu(gate) * up) @ lp["w_down"].astype(dt)
        return x, kc_l, vc_l

    def _step(params, kc, vc, bt, last, pos, active, cos, sin,
              temp, topk, keys):
        act = active & (pos < S)
        x = jnp.take(params["embed"], last, axis=0).astype(dt)

        def body(carry, layer):
            x = carry
            lp, kc_l, vc_l = layer
            x, kc_l, vc_l = _decode_layer(x, lp, kc_l, vc_l, bt, pos,
                                          act, cos, sin)
            return x, (kc_l, vc_l)

        x, (kc, vc) = jax.lax.scan(body, x, (params["layers"], kc, vc))
        x = rms_norm(x, params["final_norm"], mcfg.norm_eps)
        logits = x @ params["lm_head"].astype(dt)          # [ns, V]
        nxt = _sample_tokens(logits, temp, topk, keys, pos)
        nxt = jnp.where(act, nxt, last)
        pos2 = jnp.where(act, pos + 1, pos)
        return kc, vc, nxt, pos2

    def decode(params, kc, vc, bt, last, pos, active, temp, topk, keys):
        cos, sin = rope_frequencies(hd, S, mcfg.rope_theta)
        out0 = jnp.zeros((ns, chunk), jnp.int32)

        def body(i, carry):
            kc, vc, last, pos, out = carry
            kc, vc, nxt, pos = _step(params, kc, vc, bt, last, pos,
                                     active, cos, sin, temp, topk, keys)
            out = out.at[:, i].set(nxt)
            return kc, vc, nxt, pos, out

        kc, vc, last, pos, out = jax.lax.fori_loop(
            0, chunk, body, (kc, vc, last, pos, out0))
        return kc, vc, last, pos, out

    def poke(last, pos, slot, first, length):
        """Admission bookkeeping ON DEVICE: set one slot's (last, pos).
        Keeps the decode chain free of device->host fetches — a host
        read of last/pos at admission would cost a full tunnel RTT
        before the TTFT token could be emitted."""
        return last.at[slot].set(first), pos.at[slot].set(length)

    import jax as _jax
    prefill_jit = _jax.jit(prefill, donate_argnums=(1, 2))
    decode_jit = _jax.jit(decode, donate_argnums=(1, 2, 4, 5))
    adopt_jit = _jax.jit(adopt, donate_argnums=(0, 1))
    poke_jit = _jax.jit(poke, donate_argnums=(0, 1))
    return prefill_jit, decode_jit, adopt_jit, poke_jit, empty_caches


def _seed_key(seed: int):
    """Threefry key = [hi, lo] words of the seed — host-side PRNGKey
    construction (no device round-trip at admit)."""
    import numpy as np
    return np.array([(seed >> 32) & 0xffffffff, seed & 0xffffffff],
                    np.uint32)


class _Request:
    __slots__ = ("ids", "max_tokens", "out", "produced", "slot",
                 "adopt_kv", "first", "temperature", "top_k", "seed")

    def __init__(self, ids: List[int], max_tokens: int,
                 adopt_kv: Optional[Tuple[Any, Any]] = None,
                 first: int = -1, temperature: float = 0.0,
                 top_k: int = 0, seed: int = 0):
        self.ids = ids
        self.max_tokens = max_tokens
        self.out: "queue.Queue[Optional[List[int]]]" = queue.Queue()
        self.produced = 0
        self.slot = -1
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        # Disaggregated handoff: (ks, vs) prefilled elsewhere + the first
        # generated token (already streamed to the client by the prefill
        # side, so this engine never re-emits it).
        self.adopt_kv = adopt_kv
        self.first = first


class Engine:
    """One continuous-batching decode loop over a paged KV cache.
    submit() from any thread; each request streams token chunks through
    its own queue."""

    # Smallest prefill bucket; buckets double up to max_seq.
    _MIN_BUCKET = 32

    def __init__(self, params, mcfg, *, n_slots: int = 8,
                 decode_chunk: int = 8, page_size: int = 64,
                 n_pages: Optional[int] = None):
        import jax
        import jax.numpy as jnp
        import numpy as np

        self._np = np
        self._jnp = jnp
        self.mcfg = mcfg
        self.n_slots = n_slots
        self.chunk = decode_chunk
        self.params = params
        S = mcfg.max_seq
        self.page = min(page_size, S)
        self.maxp = -(-S // self.page)
        if n_pages is None:
            # Null page + half the worst case: density comes from short
            # requests reserving only what len+max_tokens needs.
            n_pages = 1 + max(self.maxp, (n_slots * self.maxp + 1) // 2)
        if n_pages < 1 + self.maxp:
            raise ValueError(
                f"n_pages={n_pages} cannot hold one max_seq request "
                f"({self.maxp} pages of {self.page} tokens) + null page")
        self.n_pages = n_pages
        (self._prefill, self._decode, self._adopt, self._poke,
         empty) = _build_fns(mcfg, n_slots, decode_chunk, self.page,
                             n_pages)
        self._empty = empty
        self._kc, self._vc = empty()
        # Prefill shape buckets (powers of 2, capped at max_seq): a
        # 50-token prompt prefills 64 wide, not max_seq wide — the TTFT
        # lever the reference gets from vLLM's chunked prefill.
        self.buckets: List[int] = []
        b = min(self._MIN_BUCKET, mcfg.max_seq)
        while b < mcfg.max_seq:
            self.buckets.append(b)
            b *= 2
        self.buckets.append(mcfg.max_seq)
        # host-side slot + page state (control flow is host-predicted;
        # only token VALUES come back from the device)
        self._slot_req: List[Optional[_Request]] = [None] * n_slots
        self._slot_pages: List[List[int]] = [[] for _ in range(n_slots)]
        self._free: List[int] = list(range(n_pages - 1, 0, -1))
        self._bt = np.zeros((n_slots, self.maxp), np.int32)
        self._pos = np.zeros(n_slots, np.int32)
        self._active = np.zeros(n_slots, bool)
        # Per-slot sampling state (temp 0 = greedy; key seeded per
        # request so streams are reproducible wherever the slot lands).
        self._temp = np.zeros(n_slots, np.float32)
        self._topk = np.zeros(n_slots, np.int32)
        self._skeys = np.zeros((n_slots, 2), np.uint32)
        self._last_d = jnp.zeros(n_slots, jnp.int32)
        self._pos_d = jnp.zeros(n_slots, jnp.int32)
        self.peak_pages_used = 0
        self._pending: deque = deque()
        self._plock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self.error: Optional[str] = None
        # Warm the decode program + the SMALLEST and LARGEST prefill
        # buckets before serving (serve's startup grace covers the XLA
        # compiles); intermediate buckets warm in a BACKGROUND thread —
        # until one is ready, prompts round UP to the next warmed bucket,
        # so an unwarmed shape never compiles inside the engine loop
        # (which would freeze every in-flight decode stream). Warm
        # writes target the null page (pages = zeros), so they never
        # touch real KV state.
        self._warm = {self.buckets[0], self.buckets[-1]}
        null_pages = jnp.zeros(self.maxp, jnp.int32)
        null_key = jnp.zeros(2, jnp.uint32)
        for width in sorted(self._warm):
            toks = jnp.zeros((1, width), jnp.int32)
            self._kc, self._vc, first = self._prefill(
                self.params, self._kc, self._vc, null_pages, toks, 1,
                0.0, 0, null_key)
            kv = jnp.zeros((mcfg.n_layers, width, mcfg.n_kv_heads,
                            mcfg.head_dim), mcfg.dtype)
            self._kc, self._vc = self._adopt(self._kc, self._vc,
                                             null_pages, kv, kv)
        self._kc, self._vc, self._last_d, self._pos_d, out = self._decode(
            self.params, self._kc, self._vc, jnp.asarray(self._bt),
            self._last_d, self._pos_d, jnp.zeros(n_slots, bool),
            jnp.asarray(self._temp), jnp.asarray(self._topk),
            jnp.asarray(self._skeys))
        # Warm both poke variants: host-int `first` (adopt path) and
        # device-scalar `first` (prefill path).
        self._last_d, self._pos_d = self._poke(self._last_d, self._pos_d,
                                               0, 0, 0)
        self._last_d, self._pos_d = self._poke(self._last_d, self._pos_d,
                                               0, first, 0)
        self._last_d, self._pos_d = self._poke(self._last_d, self._pos_d,
                                               0, 0, 0)
        int(first)
        # Emission FIFO: the dispatch loop enqueues device arrays; the
        # emitter thread performs the host syncs. maxsize bounds how far
        # dispatch can run ahead of the device (pipeline depth): 2 keeps
        # chunks back-to-back while a newly-arrived request's prefill
        # never queues behind more than 2 chunks.
        self._emit_q: "queue.Queue" = queue.Queue(maxsize=2)
        self._emitter = threading.Thread(target=self._emit_loop,
                                         daemon=True, name="llm-emit")
        self._emitter.start()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="llm-engine")
        self._thread.start()
        self._warm_thread: Optional[threading.Thread] = None
        middles = [b for b in self.buckets if b not in self._warm]
        if middles:
            self._warm_thread = threading.Thread(
                target=self._warm_buckets, args=(middles,), daemon=True,
                name="llm-bucket-warm")
            self._warm_thread.start()

    def _warm_buckets(self, widths: List[int]) -> None:
        """Warm intermediate prefill buckets off the engine loop; each
        becomes eligible the moment its compile lands. Runs real calls
        (the only way to reliably populate jit's dispatch cache) against
        a SCRATCH kv arena — the live arenas are donated on every engine
        call and must never be touched from this thread. Costs one
        transient extra arena while warming."""
        import jax.numpy as jnp
        try:
            kc, vc = self._empty()
            m = self.mcfg
            null_pages = jnp.zeros(self.maxp, jnp.int32)
            for width in widths:
                if self._stop:
                    return
                toks = jnp.zeros((1, width), jnp.int32)
                kc, vc, first = self._prefill(
                    self.params, kc, vc, null_pages, toks, 1, 0.0, 0,
                    jnp.zeros(2, jnp.uint32))
                int(first)  # host sync: compile fully landed
                # Warm the PD adopt program for this width too (a first
                # cross-pool handoff must not compile in the loop).
                kv = jnp.zeros((m.n_layers, width, m.n_kv_heads,
                                m.head_dim), m.dtype)
                kc, vc = self._adopt(kc, vc, null_pages, kv, kv)
                self._warm.add(width)
        except Exception:
            return  # engine shutting down / compile failure: keep
            # serving via the already-warm buckets

    # ------------------------------------------------------------------
    def submit(self, ids: List[int], max_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0,
               seed: int = 0) -> "queue.Queue":
        """Enqueue a request; returns its stream of token-chunk lists
        (None terminates the stream). temperature 0 = greedy; top_k
        bounds sampling to the best k logits (capped at TOPK_CAP); seed
        makes the sample stream reproducible."""
        if self.error is not None or not self._thread.is_alive():
            raise RuntimeError(f"LLM engine died:\n{self.error}")
        req = _Request(ids[: self.mcfg.max_seq - 1], max_tokens,
                       temperature=temperature, top_k=top_k, seed=seed)
        if max_tokens <= 0:
            req.out.put(None)  # nothing to generate; skip the prefill too
            return req.out
        with self._plock:
            self._pending.append(req)
        self._wake.set()
        return req.out

    def submit_prefilled(self, ks: Any, vs: Any, length: int, first: int,
                         max_tokens: int, *, temperature: float = 0.0,
                         top_k: int = 0, seed: int = 0) -> "queue.Queue":
        """Adopt an externally-prefilled request (PD disaggregation): the
        KV [L, B, KVH, hd] was produced by a PrefillServer and handed
        over via DeviceRefs; this engine continues decoding from token
        `first` at position `length` with the given sampling params
        (`first` was chosen by the PREFILL side — sampled there with the
        same seed derivation when temperature > 0). The stream yields
        only tokens AFTER `first`."""
        if self.error is not None or not self._thread.is_alive():
            raise RuntimeError(f"LLM engine died:\n{self.error}")
        req = _Request([0] * min(length, self.mcfg.max_seq - 1),
                       max_tokens, adopt_kv=(ks, vs), first=first,
                       temperature=temperature, top_k=top_k, seed=seed)
        if max_tokens <= 1:
            req.out.put(None)  # prefill's first token was the whole ask
            return req.out
        with self._plock:
            self._pending.append(req)
        self._wake.set()
        return req.out

    def stop(self) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=10)
        try:
            self._emit_q.put(None, timeout=10)  # sentinel: drain + exit
        except queue.Full:
            pass
        self._emitter.join(timeout=30)
        # Join the background bucket warmer too: a daemon thread still
        # inside an XLA compile at interpreter shutdown aborts the
        # process (C++ exception with no Python frame to land in).
        if self._warm_thread is not None:
            self._warm_thread.join(timeout=60)

    def pages_in_use(self) -> int:
        return (self.n_pages - 1) - len(self._free)

    # ------------------------------------------------------------------
    def _admit(self) -> None:
        """Admit pending requests into free slots while their page
        reservations fit (FIFO: the head waits — for a finish to free a
        slot or pages — rather than being overtaken). Safe to call with
        chunks in flight: an in-flight chunk saw the new slot as
        inactive and never touches its freshly-allocated pages; the
        prefill + poke ops simply queue behind it on the device.
        Prefills for a BURST of admissions are all dispatched (and their
        first-token transfers started) before anything blocks, so N
        admissions cost ~one round-trip, not N."""
        np, jnp = self._np, self._jnp
        S = self.mcfg.max_seq
        emits: List[Tuple[_Request, Any, bool]] = []  # (req, first, done)
        while True:
            with self._plock:
                req = self._pending[0] if self._pending else None
            if req is None:
                break
            slot = next((i for i in range(self.n_slots)
                         if not self._active[i]
                         and self._slot_req[i] is None), None)
            need = -(-min(len(req.ids) + req.max_tokens, S) // self.page)
            if slot is None or len(self._free) < need:
                break  # head-of-line waits for a finish

            with self._plock:
                self._pending.popleft()
            pages = [self._free.pop() for _ in range(need)]
            self._slot_pages[slot] = pages
            self.peak_pages_used = max(self.peak_pages_used,
                                       self.pages_in_use())
            self._bt[slot, :] = 0
            self._bt[slot, :need] = pages
            pages_arr = np.zeros(self.maxp, np.int32)
            pages_arr[:need] = pages
            pages_arr = jnp.asarray(pages_arr)
            if req.adopt_kv is not None:
                # Disaggregated handoff: write the external KV into the
                # slot's pages; `first` was already streamed by the
                # prefill side. An UNWARMED handoff width is host-padded
                # to the next warmed bucket (a zero tail is never
                # attended — the mask stops at pos) instead of compiling
                # a fresh adopt program inside the loop.
                ks, vs = req.adopt_kv
                req.adopt_kv = None
                width = ks.shape[1]
                if width not in self._warm:
                    target = next(b for b in self.buckets
                                  if b >= width and b in self._warm)
                    pk = np.zeros((ks.shape[0], target) + ks.shape[2:],
                                  np.asarray(ks).dtype)
                    pv = np.zeros_like(pk)
                    pk[:, :width] = np.asarray(ks)
                    pv[:, :width] = np.asarray(vs)
                    ks, vs = jnp.asarray(pk), jnp.asarray(pv)
                self._kc, self._vc = self._adopt(
                    self._kc, self._vc, pages_arr, ks, vs)
                first = req.first
            else:
                # Only WARMED buckets are eligible (round up until the
                # background warm lands) — never compile in the engine
                # loop.
                width = next(b for b in self.buckets
                             if b >= len(req.ids) and b in self._warm)
                toks = np.zeros((1, width), np.int32)
                toks[0, :len(req.ids)] = req.ids
                self._kc, self._vc, first = self._prefill(
                    self.params, self._kc, self._vc, pages_arr,
                    jnp.asarray(toks), len(req.ids),
                    float(req.temperature), int(req.top_k),
                    jnp.asarray(_seed_key(req.seed)))
            req.slot = slot
            self._slot_req[slot] = req
            self._pos[slot] = len(req.ids)
            self._active[slot] = True
            # Sampling state applies on BOTH branches (a PD handoff
            # continues decoding with the request's params).
            self._temp[slot] = req.temperature
            self._topk[slot] = req.top_k
            self._skeys[slot] = _seed_key(req.seed)
            req.produced = 1
            # Device-side slot bookkeeping (async — never a host
            # round-trip; `first` stays a device scalar on the prefill
            # path).
            self._last_d, self._pos_d = self._poke(
                self._last_d, self._pos_d, slot, first,
                int(self._pos[slot]))
            done = (req.produced >= req.max_tokens
                    or self._pos[slot] >= S)
            if done:
                self._finish_state(slot)
            emits.append((req, first, done))
        # Start EVERY device->host copy first (async), THEN enqueue: a
        # burst overlaps all its transfers even when the bounded
        # _emit_q.put blocks partway through the enqueue loop.
        for _, first, _ in emits:
            try:
                first.copy_to_host_async()
            except AttributeError:
                pass  # host int (adopt path)
        for req, first, done in emits:
            # The emitter thread performs the int(first) sync — the
            # dispatch loop never blocks on the device.
            self._emit_q.put(("first", req, first, done))

    def _finish_state(self, slot: int) -> None:
        """Free the slot + pages (host control state only — the stream's
        terminating None is emitted by the emitter thread, AFTER the
        slot's final tokens)."""
        self._slot_req[slot] = None
        self._active[slot] = False
        self._free.extend(self._slot_pages[slot])
        self._slot_pages[slot] = []
        self._bt[slot, :] = 0
        self._temp[slot] = 0.0
        self._topk[slot] = 0

    def _finish(self, slot: int) -> None:
        req = self._slot_req[slot]
        self._finish_state(slot)
        if req is not None:
            req.out.put(None)

    def _run(self) -> None:
        try:
            self._run_inner()
        except BaseException:
            # A dead engine must not strand consumers on silent queues.
            import traceback
            self.error = traceback.format_exc()
            for slot in range(self.n_slots):
                self._finish(slot)
            while True:
                with self._plock:
                    req = self._pending.popleft() if self._pending else None
                if req is None:
                    break
                req.out.put(None)

    def _emit_loop(self) -> None:
        """The only place host<->device syncs happen on the serving
        path: fetch first tokens / chunk outputs and emit them to each
        request's stream, in dispatch order (per-request FIFO is
        preserved because the dispatch loop enqueues a request's "first"
        before any of its chunks)."""
        np = self._np
        while True:
            item = self._emit_q.get()
            if item is None:
                return
            try:
                if item[0] == "first":
                    _, req, first, done = item
                    if req.first < 0:
                        req.out.put([int(first)])
                    if done:
                        req.out.put(None)
                else:  # ("chunk", out_d, plan)
                    _, out_d, plan = item
                    out_h = np.asarray(out_d)
                    for slot, req, take, fin in plan:
                        toks = [int(t) for t in out_h[slot, :take]]
                        if toks:
                            req.out.put(toks)
                        if fin:
                            req.out.put(None)
            except BaseException:
                import traceback
                self.error = self.error or traceback.format_exc()
                # Terminate the affected streams rather than stranding
                # their consumers.
                if item[0] == "first":
                    item[1].out.put(None)
                else:
                    for _, req, _, _ in item[2]:
                        req.out.put(None)

    def _run_inner(self) -> None:
        np, jnp = self._np, self._jnp
        S = self.mcfg.max_seq
        while not self._stop:
            # Admission is pipeline-safe: an in-flight chunk saw the new
            # slot as inactive, and its prefill/poke queue behind that
            # chunk on the device.
            self._admit()
            if not self._active.any():
                self._wake.wait(timeout=0.5)
                self._wake.clear()
                continue
            # Predict this chunk's control outcome on the host: per-slot
            # emit counts and finishes depend only on pos/produced, never
            # on token values — so the chunk's finishes free slots/pages
            # IMMEDIATELY (the freed pages are safe to reuse: a later
            # request always writes a position before reading it, and
            # its device ops queue behind this chunk).
            plan = []
            for slot in range(self.n_slots):
                req = self._slot_req[slot]
                if req is None or not self._active[slot]:
                    continue
                valid = int(max(0, min(self.chunk, S - self._pos[slot])))
                take = int(min(valid, req.max_tokens - req.produced))
                fin = (req.produced + take >= req.max_tokens
                       or self._pos[slot] + valid >= S)
                req.produced += take
                plan.append((slot, req, take, fin))
            if not plan:  # defensive: never hot-spin
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            # COPIES, not views: jnp.asarray may alias numpy memory
            # (zero-copy on the CPU backend), and this loop mutates
            # _bt/_active in place while the dispatched chunk is still
            # queued — an aliased buffer would let those mutations reach
            # into the in-flight computation.
            self._kc, self._vc, self._last_d, self._pos_d, out_d = \
                self._decode(self.params, self._kc, self._vc,
                             jnp.asarray(self._bt.copy()), self._last_d,
                             self._pos_d,
                             jnp.asarray(self._active.copy()),
                             jnp.asarray(self._temp.copy()),
                             jnp.asarray(self._topk.copy()),
                             jnp.asarray(self._skeys.copy()))
            self._pos = np.where(
                self._active, np.minimum(self._pos + self.chunk, S),
                self._pos).astype(np.int32)
            for slot, req, take, fin in plan:
                if fin and self._slot_req[slot] is req:
                    self._finish_state(slot)
            try:
                out_d.copy_to_host_async()
            except AttributeError:
                pass
            # Blocks when the emitter is `maxsize` chunks behind — the
            # pipeline-depth bound.
            self._emit_q.put(("chunk", out_d, plan))
