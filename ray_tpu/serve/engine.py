"""Continuous-batching LLM decode engine with a slotted (paged) KV arena.

The TPU-native answer to the reference's vLLM delegation (reference:
python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_models.py:170 —
engine_kwargs feed vLLM's continuous batcher; here the engine is OURS):

- **Static KV arena** `[n_layers, n_slots, max_seq, kv_heads, head_dim]`
  — the "pages" are per-request slots of a statically-shaped arena, so
  every step is one fixed-shape XLA program (no recompiles, MXU-batched
  across requests).
- **Continuous batching**: one background decode loop per replica admits
  new requests into free slots (prefill) and evicts finished ones
  between chunks; in-flight requests never wait for each other's
  completion — aggregate tokens/s scales with occupancy.
- **Chunked decode**: `decode_chunk` tokens per host sync
  (`lax.fori_loop` on device), the same latency/throughput dial the
  single-stream path used.

A small fixed set of compiled programs serves all traffic: one prefill
per power-of-2 BUCKET width (a short prompt pays a short prefill — the
TTFT lever; smallest and largest warmed at startup, others on first use)
and the n-step decode chunk over all slots.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, List, Optional, Tuple


def _make_prefill_core(mcfg):
    """fn(params, tokens[1, B], length) -> (first_token, ks, vs) where
    ks/vs are [L, B, KVH, hd] — the shared prefill pass used by the
    in-engine prefill AND the disaggregated PrefillServer (reference:
    llm/_internal/serve/deployments/prefill_decode_disagg/ — there the
    split is two vLLM pools; here both halves share one traced core)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.attention import flash_attention, repeat_kv
    from ray_tpu.ops.norms import apply_rope, rms_norm, rope_frequencies

    H, KVH, hd = mcfg.n_heads, mcfg.n_kv_heads, mcfg.head_dim
    dt = mcfg.dtype

    def _prefill_layer(carry, lp):
        x, cos, sin = carry
        B, Sq, _ = x.shape
        h = rms_norm(x, lp["attn_norm"], mcfg.norm_eps)
        q = jnp.einsum("bsd,dh->bsh", h, lp["wq"].astype(dt))
        k = jnp.einsum("bsd,dh->bsh", h, lp["wk"].astype(dt))
        v = jnp.einsum("bsd,dh->bsh", h, lp["wv"].astype(dt))
        q = q.reshape(B, Sq, H, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B, Sq, KVH, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, Sq, KVH, hd).transpose(0, 2, 1, 3)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = flash_attention(q, repeat_kv(k, H // KVH),
                               repeat_kv(v, H // KVH), True)
        attn = attn.transpose(0, 2, 1, 3).reshape(B, Sq, H * hd)
        x = x + jnp.einsum("bsh,hd->bsd", attn, lp["wo"].astype(dt))
        h = rms_norm(x, lp["mlp_norm"], mcfg.norm_eps)
        gate = jnp.einsum("bsd,df->bsf", h, lp["w_gate"].astype(dt))
        up = jnp.einsum("bsd,df->bsf", h, lp["w_up"].astype(dt))
        x = x + jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up,
                           lp["w_down"].astype(dt))
        # cache pre-repeat k/v: [S, KVH, hd] (B == 1 squeezed)
        return (x, cos, sin), (k[0].transpose(1, 0, 2),
                               v[0].transpose(1, 0, 2))

    def core(params, tokens, length):
        x = jnp.take(params["embed"], tokens, axis=0).astype(dt)
        cos, sin = rope_frequencies(hd, tokens.shape[1], mcfg.rope_theta)
        (x, _, _), (ks, vs) = jax.lax.scan(
            _prefill_layer, (x, cos, sin), params["layers"])
        x = rms_norm(x, params["final_norm"], mcfg.norm_eps)
        last_h = jax.lax.dynamic_index_in_dim(x, length - 1, axis=1,
                                              keepdims=False)
        logits = jnp.einsum("bd,dv->bv", last_h,
                            params["lm_head"].astype(dt))
        first = jnp.argmax(logits[0]).astype(jnp.int32)
        return first, ks, vs

    return core


def _build_fns(mcfg, n_slots: int, chunk: int):
    """Build (prefill_jit, decode_jit, adopt_jit, empty_caches)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.norms import rms_norm, rope_frequencies

    if mcfg.n_experts > 0:
        raise ValueError("the serving engine supports dense models only")

    S = mcfg.max_seq
    H, KVH, hd = mcfg.n_heads, mcfg.n_kv_heads, mcfg.head_dim
    D = mcfg.d_model
    dt = mcfg.dtype
    ns = n_slots

    def empty_caches():
        shape = (mcfg.n_layers, ns, S, KVH, hd)
        return jnp.zeros(shape, dt), jnp.zeros(shape, dt)

    # ------------------------------------------------------------------
    # prefill: full causal pass over ONE padded prompt, caching k/v
    # ------------------------------------------------------------------
    _core = _make_prefill_core(mcfg)

    def prefill(params, kc, vc, slot, tokens, length):
        """tokens [1, B] padded to a BUCKET width (powers of 2 up to
        max_seq — jax.jit compiles one program per bucket shape, so a
        short prompt pays a short prefill, not a max_seq one); writes
        slot's k/v, returns the first generated token (greedy)."""
        first, ks, vs = _core(params, tokens, length)
        # ks/vs: [L, B, KVH, hd] -> arena slot (dynamic slot index)
        kc = jax.lax.dynamic_update_slice(kc, ks[:, None], (0, slot, 0, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, vs[:, None], (0, slot, 0, 0, 0))
        return kc, vc, first

    def adopt(kc, vc, slot, ks, vs):
        """Write externally-prefilled k/v (a PrefillServer handoff) into
        a slot of the arena."""
        kc = jax.lax.dynamic_update_slice(kc, ks[:, None], (0, slot, 0, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, vs[:, None], (0, slot, 0, 0, 0))
        return kc, vc

    # ------------------------------------------------------------------
    # decode: one token for every active slot per step, `chunk` steps
    # ------------------------------------------------------------------
    def _rope_one(x, c, s):
        # x [ns, heads, hd], c/s [ns, 1, hd//2]
        x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
        out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
        return out.astype(x.dtype)

    def _decode_layer(x, lp, kc_l, vc_l, pos, act, cos, sin):
        # x [ns, D]; kc_l/vc_l [ns, S, KVH, hd]; pos [ns]; act [ns] bool
        h = rms_norm(x, lp["attn_norm"], mcfg.norm_eps)
        q = (h @ lp["wq"].astype(dt)).reshape(ns, H, hd)
        k = (h @ lp["wk"].astype(dt)).reshape(ns, KVH, hd)
        v = (h @ lp["wv"].astype(dt)).reshape(ns, KVH, hd)
        w = jnp.minimum(pos, S - 1)
        c = cos[w][:, None]
        s = sin[w][:, None]
        q = _rope_one(q, c, s)
        k = _rope_one(k, c, s)
        # Write k/v at each slot's position — inactive slots keep the old
        # value (no-op write keeps the shape static).
        idx = jnp.arange(ns)
        k_eff = jnp.where(act[:, None, None], k, kc_l[idx, w])
        v_eff = jnp.where(act[:, None, None], v, vc_l[idx, w])
        kc_l = kc_l.at[idx, w].set(k_eff)
        vc_l = vc_l.at[idx, w].set(v_eff)
        # Grouped-query attention against the slot's cached history.
        qg = q.reshape(ns, KVH, H // KVH, hd).astype(jnp.float32)
        scores = jnp.einsum("nkgd,nskd->nkgs", qg,
                            kc_l.astype(jnp.float32)) / (hd ** 0.5)
        mask = jnp.arange(S)[None, :] <= w[:, None]          # [ns, S]
        scores = jnp.where(mask[:, None, None, :], scores, -1e30)
        wts = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("nkgs,nskd->nkgd", wts,
                          vc_l.astype(jnp.float32))
        attn = attn.reshape(ns, H * hd).astype(dt)
        x = x + attn @ lp["wo"].astype(dt)
        h = rms_norm(x, lp["mlp_norm"], mcfg.norm_eps)
        gate = h @ lp["w_gate"].astype(dt)
        up = h @ lp["w_up"].astype(dt)
        x = x + (jax.nn.silu(gate) * up) @ lp["w_down"].astype(dt)
        return x, kc_l, vc_l

    def _step(params, kc, vc, last, pos, active, cos, sin):
        act = active & (pos < S)
        x = jnp.take(params["embed"], last, axis=0).astype(dt)

        def body(carry, layer):
            x = carry
            lp, kc_l, vc_l = layer
            x, kc_l, vc_l = _decode_layer(x, lp, kc_l, vc_l, pos, act,
                                          cos, sin)
            return x, (kc_l, vc_l)

        x, (kc, vc) = jax.lax.scan(body, x, (params["layers"], kc, vc))
        x = rms_norm(x, params["final_norm"], mcfg.norm_eps)
        logits = x @ params["lm_head"].astype(dt)          # [ns, V]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(act, nxt, last)
        pos2 = jnp.where(act, pos + 1, pos)
        return kc, vc, nxt, pos2

    def decode(params, kc, vc, last, pos, active):
        cos, sin = rope_frequencies(hd, S, mcfg.rope_theta)
        out0 = jnp.zeros((ns, chunk), jnp.int32)

        def body(i, carry):
            kc, vc, last, pos, out = carry
            kc, vc, nxt, pos = _step(params, kc, vc, last, pos, active,
                                     cos, sin)
            out = out.at[:, i].set(nxt)
            return kc, vc, nxt, pos, out

        kc, vc, last, pos, out = jax.lax.fori_loop(
            0, chunk, body, (kc, vc, last, pos, out0))
        return kc, vc, last, pos, out

    import jax as _jax
    prefill_jit = _jax.jit(prefill, donate_argnums=(1, 2))
    decode_jit = _jax.jit(decode, donate_argnums=(1, 2))
    adopt_jit = _jax.jit(adopt, donate_argnums=(0, 1))
    return prefill_jit, decode_jit, adopt_jit, empty_caches


class _Request:
    __slots__ = ("ids", "max_tokens", "out", "produced", "slot",
                 "adopt_kv", "first")

    def __init__(self, ids: List[int], max_tokens: int,
                 adopt_kv: Optional[Tuple[Any, Any]] = None,
                 first: int = -1):
        self.ids = ids
        self.max_tokens = max_tokens
        self.out: "queue.Queue[Optional[List[int]]]" = queue.Queue()
        self.produced = 0
        self.slot = -1
        # Disaggregated handoff: (ks, vs) prefilled elsewhere + the first
        # generated token (already streamed to the client by the prefill
        # side, so this engine never re-emits it).
        self.adopt_kv = adopt_kv
        self.first = first


class Engine:
    """One continuous-batching decode loop. submit() from any thread;
    each request streams token chunks through its own queue."""

    # Smallest prefill bucket; buckets double up to max_seq.
    _MIN_BUCKET = 32

    def __init__(self, params, mcfg, *, n_slots: int = 8,
                 decode_chunk: int = 4):
        import jax
        import jax.numpy as jnp
        import numpy as np

        self._np = np
        self._jnp = jnp
        self.mcfg = mcfg
        self.n_slots = n_slots
        self.chunk = decode_chunk
        self.params = params
        self._prefill, self._decode, self._adopt, empty = _build_fns(
            mcfg, n_slots, decode_chunk)
        self._empty = empty
        self._kc, self._vc = empty()
        # Prefill shape buckets (powers of 2, capped at max_seq): a
        # 50-token prompt prefills 64 wide, not max_seq wide — the TTFT
        # lever the reference gets from vLLM's chunked prefill.
        self.buckets: List[int] = []
        b = min(self._MIN_BUCKET, mcfg.max_seq)
        while b < mcfg.max_seq:
            self.buckets.append(b)
            b *= 2
        self.buckets.append(mcfg.max_seq)
        # host-side slot state
        self._slot_req: List[Optional[_Request]] = [None] * n_slots
        self._pos = np.zeros(n_slots, np.int32)
        self._active = np.zeros(n_slots, bool)
        self._last = np.zeros(n_slots, np.int32)
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._wake = threading.Event()
        self._stop = False
        self.error: Optional[str] = None
        # Warm the decode program + the SMALLEST and LARGEST prefill
        # buckets before serving (serve's startup grace covers the XLA
        # compiles); intermediate buckets warm in a BACKGROUND thread —
        # until one is ready, prompts round UP to the next warmed bucket,
        # so an unwarmed shape never compiles inside the engine loop
        # (which would freeze every in-flight decode stream).
        self._warm = {self.buckets[0], self.buckets[-1]}
        for width in sorted(self._warm):
            toks = jnp.zeros((1, width), jnp.int32)
            self._kc, self._vc, first = self._prefill(
                self.params, self._kc, self._vc, 0, toks, 1)
            # PD adopt program for the same width (arena is all-zeros
            # here, so the slot-0 write is a no-op).
            kv = jnp.zeros((mcfg.n_layers, width, mcfg.n_kv_heads,
                            mcfg.head_dim), mcfg.dtype)
            self._kc, self._vc = self._adopt(self._kc, self._vc, 0, kv, kv)
        self._kc, self._vc, last, pos, out = self._decode(
            self.params, self._kc, self._vc,
            jnp.zeros(n_slots, jnp.int32), jnp.zeros(n_slots, jnp.int32),
            jnp.zeros(n_slots, bool))
        int(first)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="llm-engine")
        self._thread.start()
        middles = [b for b in self.buckets if b not in self._warm]
        if middles:
            threading.Thread(target=self._warm_buckets, args=(middles,),
                             daemon=True, name="llm-bucket-warm").start()

    def _warm_buckets(self, widths: List[int]) -> None:
        """Warm intermediate prefill buckets off the engine loop; each
        becomes eligible the moment its compile lands. Runs real calls
        (the only way to reliably populate jit's dispatch cache) against
        a SCRATCH kv arena — the live arenas are donated on every engine
        call and must never be touched from this thread. Costs one
        transient extra arena while warming."""
        import jax.numpy as jnp
        try:
            kc, vc = self._empty()
            m = self.mcfg
            for width in widths:
                if self._stop:
                    return
                toks = jnp.zeros((1, width), jnp.int32)
                kc, vc, first = self._prefill(self.params, kc, vc, 0,
                                              toks, 1)
                int(first)  # host sync: compile fully landed
                # Warm the PD adopt program for this width too (a first
                # cross-pool handoff must not compile in the loop).
                kv = jnp.zeros((m.n_layers, width, m.n_kv_heads,
                                m.head_dim), m.dtype)
                kc, vc = self._adopt(kc, vc, 0, kv, kv)
                self._warm.add(width)
        except Exception:
            return  # engine shutting down / compile failure: keep
            # serving via the already-warm buckets

    # ------------------------------------------------------------------
    def submit(self, ids: List[int], max_tokens: int) -> "queue.Queue":
        """Enqueue a request; returns its stream of token-chunk lists
        (None terminates the stream)."""
        if self.error is not None or not self._thread.is_alive():
            raise RuntimeError(f"LLM engine died:\n{self.error}")
        req = _Request(ids[: self.mcfg.max_seq - 1], max_tokens)
        if max_tokens <= 0:
            req.out.put(None)  # nothing to generate; skip the prefill too
            return req.out
        self._pending.put(req)
        self._wake.set()
        return req.out

    def submit_prefilled(self, ks: Any, vs: Any, length: int, first: int,
                         max_tokens: int) -> "queue.Queue":
        """Adopt an externally-prefilled request (PD disaggregation): the
        KV [L, B, KVH, hd] was produced by a PrefillServer and handed
        over via DeviceRefs; this engine continues decoding from token
        `first` at position `length`. The stream yields only tokens
        AFTER `first` (the prefill side already delivered it)."""
        if self.error is not None or not self._thread.is_alive():
            raise RuntimeError(f"LLM engine died:\n{self.error}")
        req = _Request([0] * min(length, self.mcfg.max_seq - 1),
                       max_tokens, adopt_kv=(ks, vs), first=first)
        if max_tokens <= 1:
            req.out.put(None)  # prefill's first token was the whole ask
            return req.out
        self._pending.put(req)
        self._wake.set()
        return req.out

    def stop(self) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=10)

    # ------------------------------------------------------------------
    def _admit(self) -> None:
        np, jnp = self._np, self._jnp
        for slot in range(self.n_slots):
            if self._active[slot]:
                continue
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                return
            if req.adopt_kv is not None:
                # Disaggregated handoff: write the external KV into the
                # slot; `first` was already streamed by the prefill side.
                # An UNWARMED handoff width is host-padded to the next
                # warmed bucket (a zero tail is never attended — the
                # attention mask stops at pos) instead of compiling a
                # fresh adopt program inside the loop.
                ks, vs = req.adopt_kv
                req.adopt_kv = None
                width = ks.shape[1]
                if width not in self._warm:
                    target = next(b for b in self.buckets
                                  if b >= width and b in self._warm)
                    pk = np.zeros((ks.shape[0], target) + ks.shape[2:],
                                  np.asarray(ks).dtype)
                    pv = np.zeros_like(pk)
                    pk[:, :width] = np.asarray(ks)
                    pv[:, :width] = np.asarray(vs)
                    ks, vs = jnp.asarray(pk), jnp.asarray(pv)
                self._kc, self._vc = self._adopt(
                    self._kc, self._vc, slot, ks, vs)
                first = req.first
            else:
                # Only WARMED buckets are eligible (round up until the
                # background warm lands) — never compile in the engine
                # loop.
                width = next(b for b in self.buckets
                             if b >= len(req.ids) and b in self._warm)
                toks = np.zeros((1, width), np.int32)
                toks[0, :len(req.ids)] = req.ids
                self._kc, self._vc, first = self._prefill(
                    self.params, self._kc, self._vc, slot,
                    jnp.asarray(toks), len(req.ids))
                first = int(first)
            req.slot = slot
            self._slot_req[slot] = req
            self._pos[slot] = len(req.ids)
            self._last[slot] = first
            self._active[slot] = True
            req.produced = 1
            if req.first < 0:
                req.out.put([first])             # TTFT token, immediately
            if (req.produced >= req.max_tokens
                    or self._pos[slot] >= self.mcfg.max_seq):
                self._finish(slot)

    def _finish(self, slot: int) -> None:
        req = self._slot_req[slot]
        self._slot_req[slot] = None
        self._active[slot] = False
        if req is not None:
            req.out.put(None)

    def _run(self) -> None:
        try:
            self._run_inner()
        except BaseException:
            # A dead engine must not strand consumers on silent queues.
            import traceback
            self.error = traceback.format_exc()
            for slot in range(self.n_slots):
                self._finish(slot)
            while True:
                try:
                    self._pending.get_nowait().out.put(None)
                except queue.Empty:
                    break

    def _run_inner(self) -> None:
        np, jnp = self._np, self._jnp
        while not self._stop:
            self._admit()
            if not self._active.any():
                self._wake.wait(timeout=0.5)
                self._wake.clear()
                continue
            pos_before = self._pos.copy()
            self._kc, self._vc, last, pos, out = self._decode(
                self.params, self._kc, self._vc,
                jnp.asarray(self._last), jnp.asarray(self._pos),
                jnp.asarray(self._active))
            out_h = np.asarray(out)
            # np.array copies: jax array views are read-only and the host
            # mirrors are mutated on admit.
            self._last = np.array(last)
            self._pos = np.array(pos)
            for slot in range(self.n_slots):
                req = self._slot_req[slot]
                if req is None or not self._active[slot]:
                    continue
                # A slot frozen mid-chunk (pos hit max_seq) repeats its
                # last token in `out` — only the genuinely-decoded steps
                # are real output.
                valid = max(0, min(self.chunk,
                                   self.mcfg.max_seq - pos_before[slot]))
                take = min(valid, req.max_tokens - req.produced)
                toks = [int(t) for t in out_h[slot, :take]]
                if toks:
                    req.produced += len(toks)
                    req.out.put(toks)
                if (req.produced >= req.max_tokens
                        or self._pos[slot] >= self.mcfg.max_seq):
                    self._finish(slot)
