"""Route table shared by the ingress proxies (HTTP + gRPC).

Analogue of the reference's proxy route resolution (reference:
serve/_private/proxy.py — both ingress flavors resolve route prefixes to
deployment handles off one controller-fed table)."""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import ray_tpu
from ray_tpu.serve.handle import DeploymentHandle


class RouteTable:
    """route_prefix -> deployment resolution + handle cache. Refreshes
    are rate-limited (negative cache) so unknown-path probes can't
    hammer the controller.

    Shared across the HTTP proxy's and gRPC proxy's thread pools: the
    refresh claim and the handle cache are lock-guarded (the routes dict
    itself is replaced atomically, so match() reads lock-free)."""

    _NEG_CACHE_TTL_S = 2.0

    def __init__(self, controller_handle):
        self._controller = controller_handle
        self._routes: Dict[str, str] = {}
        self._handles: Dict[str, DeploymentHandle] = {}
        self._last_refresh = 0.0
        self._lock = threading.Lock()

    @property
    def routes(self) -> Dict[str, str]:
        return self._routes

    def refresh(self) -> None:
        """Blocking controller RPC — call OFF any serving event loop."""
        table = ray_tpu.get(self._controller.list_deployments.remote(),
                            timeout=10)
        # Build fully, assign once (readers see either table, never a
        # half-cleared one).
        routes = {}
        for name, info in table.items():
            prefix = info["config"].get("route_prefix") or f"/{name}"
            routes[prefix] = name
        self._routes = routes

    def match(self, path: str) -> Optional[str]:
        """Longest-prefix route match -> deployment name (no refresh)."""
        routes = self._routes  # snapshot: refresh() swaps the dict
        best = max((p for p in routes
                    if path == p or path.startswith(p + "/")),
                   key=len, default=None)
        return routes[best] if best is not None else None

    def should_refresh(self) -> bool:
        """Atomically claim the next refresh window (at most one caller
        per TTL gets True)."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_refresh > self._NEG_CACHE_TTL_S:
                self._last_refresh = now
                return True
            return False

    def handle_for(self, deployment: str) -> DeploymentHandle:
        with self._lock:
            h = self._handles.get(deployment)
            if h is None:
                h = self._handles[deployment] = DeploymentHandle(
                    deployment, self._controller)
            return h
