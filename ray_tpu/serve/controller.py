"""ServeController — reconciles deployments to their target replica sets.

Analogue of the reference's control plane (reference:
serve/_private/controller.py ServeController:103 + deployment_state.py
replica FSMs + autoscaling_state.py). One named actor:

  * deploy(name, config) records the target; a reconcile loop creates or
    removes Replica actors to match num_replicas
  * routing table (replica handles per deployment) served to routers;
    routers refresh on a version bump (cheap poll, reference long-poll)
  * autoscaling: average ongoing requests per replica vs
    target_ongoing_requests resizes within [min_replicas, max_replicas]
  * health checks replace dead replicas
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.serve.replica import Replica


class _DeploymentState:
    def __init__(self, name: str, config: dict):
        self.name = name
        self.config = config
        self.replicas: List[Any] = []  # ActorHandles
        self.born: Dict[bytes, float] = {}     # actor_id -> creation time
        self.healthy: Dict[bytes, bool] = {}   # ever passed a health check
        self.last_scale = 0.0
        # Autoscaler's replica target, kept OUT of the user-supplied
        # config (reference keeps the autoscaled target in deployment
        # state, never mutating the submitted config).
        self.autoscale_target: Optional[int] = None


class ServeController:
    CONTROLLER_NAME = "SERVE_CONTROLLER"

    def __init__(self):
        import threading
        self._deployments: Dict[str, _DeploymentState] = {}
        self._version = 0
        self._running = True
        # One lock covers all state transitions: actor-task methods
        # (deploy/delete) and the control-loop thread (health/autoscale)
        # mutate the same _DeploymentStates.
        self._lock = threading.RLock()
        self._thread = threading.Thread(target=self._control_loop,
                                        daemon=True, name="serve-ctrl")
        self._thread.start()

    # -- API (called via actor handle) ---------------------------------
    def deploy(self, name: str, config_blob: bytes) -> None:
        config = cloudpickle.loads(config_blob)
        with self._lock:
            old = self._deployments.get(name)
            if old is not None:
                # Upsert = replace: the old replicas run the OLD class
                # blob; drain and retire them (leaking them would double
                # resident replicas per redeploy).
                for r in old.replicas:
                    self._drain_and_kill(r)
            self._deployments[name] = _DeploymentState(name, config)
            self._reconcile_one(self._deployments[name])
            self._bump_version()

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            st = self._deployments.pop(name, None)
            if st is not None:
                for r in st.replicas:
                    self._drain_and_kill(r, drain_s=5.0)
                self._bump_version()

    def _drain_and_kill(self, replica, drain_s: float = 30.0) -> None:
        """Best-effort drain: let in-flight requests finish before the
        kill (reference: replica graceful shutdown drain)."""
        import threading

        def drain():
            deadline = time.time() + drain_s
            while time.time() < deadline:
                try:
                    if ray_tpu.get(replica.queue_len.remote(),
                                   timeout=5) == 0:
                        break
                except Exception:
                    break
                time.sleep(0.25)
            try:
                ray_tpu.kill(replica)
            except Exception:
                pass

        threading.Thread(target=drain, daemon=True).start()

    def _bump_version(self) -> None:
        self._version += 1
        # Push-invalidate routers via the core pubsub hub (reference:
        # serve long_poll.py:228 LongPollHost — ours rides the runtime's
        # existing hub instead of a serve-private one).
        try:
            from ray_tpu.core.ref import get_core_worker
            cw = get_core_worker()
            cw._spawn(cw.controller.call(
                "pubsub_publish", "serve_events",
                {"version": self._version}))
        except Exception:
            pass

    def routing_table(self) -> dict:
        """{deployment: [replica handles]} + version for router caching."""
        with self._lock:
            return {
                "version": self._version,
                "deployments": {name: list(st.replicas)
                                for name, st in self._deployments.items()},
            }

    def routing_version(self) -> int:
        return self._version

    def list_deployments(self) -> dict:
        with self._lock:
            return {
                name: {"num_replicas": len(st.replicas),
                       "config": {k: v for k, v in st.config.items()
                                  if k not in ("cls_blob",
                                               "init_args_blob")}}
                for name, st in self._deployments.items()}

    def shutdown_serve(self) -> None:
        """Full teardown: kill replicas SYNCHRONOUSLY — drain threads
        would die with the controller process, leaking replicas."""
        self._running = False
        with self._lock:
            for name in list(self._deployments):
                st = self._deployments.pop(name)
                for r in st.replicas:
                    try:
                        ray_tpu.kill(r)
                    except Exception:
                        pass
            self._bump_version()

    # -- reconciliation -------------------------------------------------
    def _make_replica(self, st: _DeploymentState):
        cfg = st.config
        opts: Dict[str, Any] = {"max_restarts": 0}
        if cfg.get("num_tpus"):
            opts["num_tpus"] = cfg["num_tpus"]
        if cfg.get("num_cpus") is not None:
            opts["num_cpus"] = cfg["num_cpus"]
        actor_cls = ray_tpu.remote(Replica)
        return actor_cls.options(**opts).remote(
            cfg["cls_blob"], cfg["init_args_blob"], st.name,
            cfg.get("max_ongoing_requests", 100))

    def _reconcile_one(self, st: _DeploymentState) -> None:
        target = (st.autoscale_target if st.autoscale_target is not None
                  else int(st.config.get("num_replicas", 1)))
        changed = False
        while len(st.replicas) < target:
            r = self._make_replica(st)
            st.replicas.append(r)
            st.born[r.actor_id.binary()] = time.time()
            changed = True
        while len(st.replicas) > target:
            victim = st.replicas.pop()
            st.born.pop(victim.actor_id.binary(), None)
            st.healthy.pop(victim.actor_id.binary(), None)
            self._drain_and_kill(victim)  # don't cut in-flight requests
            changed = True
        if changed:
            self._bump_version()

    def _control_loop(self) -> None:
        """Health checks + autoscaling (runs in the controller actor)."""
        while self._running:
            time.sleep(1.0)
            try:
                with self._lock:
                    states = list(self._deployments.values())
                # One graftpulse fetch per pass, shared by every
                # deployment — and only when some deployment actually
                # scales on native latency.
                p99_ms = 0.0
                if any((st.config.get("autoscaling_config") or {})
                       .get("target_native_p99_ms")
                       for st in states):
                    p99_ms = self._native_p99_ms()
                for st in states:
                    # Probe replicas WITHOUT the lock (blocking RPCs must
                    # not starve deploy/routing_table), then mutate under
                    # it, skipping states deleted/replaced mid-pass.
                    with self._lock:
                        replicas = list(st.replicas)
                    health = self._probe(replicas, "health")
                    loads = self._probe(replicas, "queue_len")
                    with self._lock:
                        if self._deployments.get(st.name) is not st:
                            continue
                        self._health_pass(st, health)
                        self._autoscale_pass(st, loads, p99_ms)
            except Exception:
                pass

    @staticmethod
    def _probe(replicas: List[Any], method: str) -> Dict[bytes, Any]:
        """Probe all replicas CONCURRENTLY (submit everything, then
        collect against one shared deadline) — one hung replica must not
        serialize the whole control loop at 10s per probe."""
        refs = {}
        for r in replicas:
            try:
                refs[r.actor_id.binary()] = getattr(r, method).remote()
            except Exception:
                refs[r.actor_id.binary()] = None
        out: Dict[bytes, Any] = {}
        deadline = time.monotonic() + 10.0
        for aid, ref in refs.items():
            if ref is None:
                out[aid] = None
                continue
            try:
                out[aid] = ray_tpu.get(
                    ref, timeout=max(0.1, deadline - time.monotonic()))
            except Exception:
                out[aid] = None
        return out

    # Replicas doing heavy init (model load + XLA compile) must not be
    # culled before they ever come up (reference: deployment_state.py
    # initialization-timeout vs health-check distinction).
    STARTUP_GRACE_S = 300.0

    def _health_pass(self, st: _DeploymentState,
                     health: Dict[bytes, Any]) -> None:
        alive = []
        for r in st.replicas:
            aid = r.actor_id.binary()
            h = health.get(aid)
            if h is not None and h["healthy"]:
                st.healthy[aid] = True
                alive.append(r)
                continue
            if h is None and not st.healthy.get(aid) and \
                    time.time() - st.born.get(aid, 0) < \
                    self.STARTUP_GRACE_S:
                alive.append(r)  # still starting: give it time
                continue
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
            st.born.pop(aid, None)
            st.healthy.pop(aid, None)
        if len(alive) != len(st.replicas):
            st.replicas = alive
            self._bump_version()
            self._reconcile_one(st)  # replace the dead

    def ready_replicas(self, name: str) -> int:
        """Replicas that have passed a health check (serve.run blocks on
        this going positive)."""
        with self._lock:
            st = self._deployments.get(name)
            if st is None:
                return 0
            return sum(1 for r in st.replicas
                       if st.healthy.get(r.actor_id.binary()))

    def _native_p99_ms(self) -> float:
        """Cluster-wide native-op p99 from the graftpulse aggregates
        (0.0 when the pulse plane is unavailable)."""
        try:
            from ray_tpu.core.ref import get_core_worker
            cw = get_core_worker()
            st = cw._run(cw.controller.call("autoscaler_state")).result(5)
            return float(st.get("native_p99_ms") or 0.0)
        except Exception:
            return 0.0

    def _autoscale_pass(self, st: _DeploymentState,
                        load_map: Dict[bytes, Any],
                        native_p99_ms: float = 0.0) -> None:
        cfg = st.config
        auto = cfg.get("autoscaling_config")
        if not auto or not st.replicas:
            return
        loads = [load_map.get(r.actor_id.binary()) for r in st.replicas]
        loads = [v for v in loads if v is not None]
        if not loads:
            return
        avg = sum(loads) / max(1, len(loads))
        target_ongoing = auto.get("target_ongoing_requests", 2.0)
        # graftpulse latency signal: with target_native_p99_ms set, a
        # cluster-wide native-op p99 above the budget counts as upscale
        # pressure even while per-replica queue lengths (request counts)
        # look fine — replicas waiting on a saturated native plane queue
        # invisibly (reference scales on ongoing requests only;
        # ROADMAP 4c wants the native latency table as the signal).
        p99_budget = float(auto.get("target_native_p99_ms") or 0.0)
        latency_pressure = (p99_budget > 0
                            and native_p99_ms > p99_budget
                            and avg > 0)
        n = len(st.replicas)
        since_scale = time.time() - st.last_scale
        want = n
        # Upscale reacts fast; downscale waits much longer so a brief load
        # dip doesn't drop replicas (reference: upscale_delay_s=30 vs
        # downscale_delay_s=600 defaults, autoscaling_policy.py).
        if avg > target_ongoing or latency_pressure:
            if since_scale < auto.get("upscale_delay_s", 3.0):
                return
            want = min(auto.get("max_replicas", 4), n + 1)
        elif avg < target_ongoing / 2:
            if since_scale < auto.get("downscale_delay_s", 30.0):
                return
            want = max(auto.get("min_replicas", 1), n - 1)
        if want != n:
            st.autoscale_target = want
            st.last_scale = time.time()
            self._reconcile_one(st)
