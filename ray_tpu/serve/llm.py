"""LLM serving preset: Llama replicas behind an OpenAI-style endpoint.

Analogue of the reference's LLM layer (reference: python/ray/llm/ —
_internal/serve/deployments/llm/ wraps an engine as Serve deployments with
an OpenAI-compatible router, TP/PP sizes placed via PGs). TPU-native:
the engine IS this framework's Llama; decode runs in jitted device-side
chunks (one host sync per chunk — see bench_serve.py for the latency
math); replicas are serve deployments with num_tpus, streamed over the
proxy's chunked HTTP path.

Tokenization is bring-your-own (`LLMConfig.tokenizer` /`detokenizer`
callables); the default passes token-id lists through untouched — there
is no bundled vocabulary (weights here are random unless `params_path`
points at a checkpoint saved by ray_tpu.train).

    import ray_tpu.serve as serve
    from ray_tpu.serve.llm import LLMConfig, build_llm_app

    handle = serve.run(build_llm_app(LLMConfig(d_model=1024, n_layers=8)),
                       name="llm", route_prefix="/v1/completions")
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu.serve as serve


@dataclass
class LLMConfig:
    vocab_size: int = 32000
    d_model: int = 1024
    n_layers: int = 8
    max_seq: int = 512
    num_replicas: int = 1
    num_tpus: float = 1
    max_ongoing_requests: int = 8
    decode_chunk: int = 4          # tokens per device call
    params_path: str = ""          # ray_tpu.train checkpoint dir (optional)
    tokenizer: Optional[Callable[[str], List[int]]] = None
    detokenizer: Optional[Callable[[List[int]], str]] = None


class LLMServer:
    """The replica: builds the model + continuous-batching engine once
    (XLA compile in the constructor; serve's startup grace covers it),
    then serves streaming completions. Concurrent requests share ONE
    decode loop over a slotted KV arena (serve/engine.py) — aggregate
    tokens/s scales with occupancy instead of serializing."""

    def __init__(self, cfg_blob: bytes):
        import cloudpickle
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.llama import LlamaConfig, init_params
        from ray_tpu.serve.engine import Engine

        cfg: LLMConfig = cloudpickle.loads(cfg_blob)
        self.cfg = cfg
        self.mcfg = LlamaConfig(
            vocab_size=cfg.vocab_size, d_model=cfg.d_model,
            n_layers=cfg.n_layers, n_heads=max(2, cfg.d_model // 128),
            n_kv_heads=max(1, cfg.d_model // 256),
            d_ff=int(cfg.d_model * 2.75), max_seq=cfg.max_seq)
        if cfg.params_path:
            from ray_tpu.train.checkpointing import load_checkpoint_host
            host = load_checkpoint_host(cfg.params_path)
            params = jax.tree.map(jnp.asarray, _unflatten(host))
        else:
            params = init_params(self.mcfg, jax.random.PRNGKey(0))
        self.engine = Engine(jax.device_put(params), self.mcfg,
                             n_slots=cfg.max_ongoing_requests,
                             decode_chunk=cfg.decode_chunk)

    def _encode(self, prompt) -> List[int]:
        if isinstance(prompt, list):
            return [int(t) for t in prompt]
        if self.cfg.tokenizer is not None:
            return self.cfg.tokenizer(prompt)
        raise ValueError(
            "string prompts need LLMConfig.tokenizer; or pass token ids")

    def _decode_text(self, ids: List[int]):
        if self.cfg.detokenizer is not None:
            return self.cfg.detokenizer(ids)
        return ids

    def __call__(self, body: Dict[str, Any]):
        """Streaming completion: yields decoded chunks (OpenAI-ish
        request body: {"prompt": [...ids] | str, "max_tokens": N}).
        Each concurrent request is a slot of the shared decode loop."""
        ids = self._encode(body.get("prompt", [1]))
        max_new = int(body.get("max_tokens", 16))
        stream = self.engine.submit(ids, max_new)
        while True:
            toks = stream.get()
            if toks is None:
                return
            out = self._decode_text(toks)
            yield (out if isinstance(out, str)
                   else " ".join(str(t) for t in out) + " ")

    def complete(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """Non-streaming OpenAI-style response."""
        text = "".join(self(body))
        return {"object": "text_completion",
                "model": f"ray_tpu-llama-{self.cfg.d_model}",
                "choices": [{"index": 0, "text": text,
                             "finish_reason": "length"}]}


def _unflatten(host: Dict[str, Any]) -> Dict[str, Any]:
    """'a.b.c' host-checkpoint keys -> nested dict."""
    out: Dict[str, Any] = {}
    for key, value in host.items():
        parts = key.split(".")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = value
    return out


def build_llm_app(cfg: LLMConfig):
    """A bound serve application for this LLM config (reference:
    serve/llm build_openai_app)."""
    import cloudpickle

    dep = serve.deployment(
        num_replicas=cfg.num_replicas,
        num_tpus=cfg.num_tpus,
        max_ongoing_requests=cfg.max_ongoing_requests,
    )(LLMServer)
    return dep.bind(cloudpickle.dumps(cfg))
