"""LLM serving preset: Llama replicas behind an OpenAI-style endpoint.

Analogue of the reference's LLM layer (reference: python/ray/llm/ —
_internal/serve/deployments/llm/ wraps an engine as Serve deployments with
an OpenAI-compatible router, TP/PP sizes placed via PGs). TPU-native:
the engine IS this framework's Llama; decode runs in jitted device-side
chunks (one host sync per chunk — see bench_serve.py for the latency
math); replicas are serve deployments with num_tpus, streamed over the
proxy's chunked HTTP path.

Tokenization is bring-your-own (`LLMConfig.tokenizer` /`detokenizer`
callables); the default passes token-id lists through untouched — there
is no bundled vocabulary (weights here are random unless `params_path`
points at a checkpoint saved by ray_tpu.train).

    import ray_tpu.serve as serve
    from ray_tpu.serve.llm import LLMConfig, build_llm_app

    handle = serve.run(build_llm_app(LLMConfig(d_model=1024, n_layers=8)),
                       name="llm", route_prefix="/v1/completions")
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu.serve as serve


@dataclass
class LLMConfig:
    vocab_size: int = 32000
    d_model: int = 1024
    n_layers: int = 8
    max_seq: int = 512
    num_replicas: int = 1
    num_tpus: float = 1
    max_ongoing_requests: int = 16
    decode_chunk: int = 8          # tokens per device call
    page_size: int = 64            # KV page width (tokens)
    kv_pages: Optional[int] = None  # physical pages (None: engine default)
    params_path: str = ""          # ray_tpu.train checkpoint dir (optional)
    tokenizer: Optional[Callable[[str], List[int]]] = None
    detokenizer: Optional[Callable[[List[int]], str]] = None


class LLMServer:
    """The replica: builds the model + continuous-batching engine once
    (XLA compile in the constructor; serve's startup grace covers it),
    then serves streaming completions. Concurrent requests share ONE
    decode loop over a slotted KV arena (serve/engine.py) — aggregate
    tokens/s scales with occupancy instead of serializing."""

    def __init__(self, cfg_blob: bytes):
        import cloudpickle

        from ray_tpu.serve.engine import Engine

        cfg: LLMConfig = cloudpickle.loads(cfg_blob)
        self.cfg = cfg
        self.mcfg, params = _model_from_cfg(cfg)
        self.engine = Engine(params, self.mcfg,
                             n_slots=cfg.max_ongoing_requests,
                             decode_chunk=cfg.decode_chunk,
                             page_size=cfg.page_size,
                             n_pages=cfg.kv_pages)

    def _encode(self, prompt) -> List[int]:
        return _encode_prompt(self.cfg, prompt)

    def _decode_text(self, ids: List[int]):
        if self.cfg.detokenizer is not None:
            return self.cfg.detokenizer(ids)
        return ids

    def __call__(self, body: Dict[str, Any]):
        """Streaming completion: yields decoded chunks (OpenAI-ish
        request body: {"prompt": [...ids] | str, "max_tokens": N,
        "temperature": T, "top_k": K, "seed": S} — temperature 0/absent
        = greedy). Each concurrent request is a slot of the shared
        decode loop."""
        ids = self._encode(body.get("prompt", [1]))
        max_new = int(body.get("max_tokens", 16))
        seed = body.get("seed")
        if seed is None:
            # OpenAI/vLLM semantics: absent seed = fresh entropy per
            # request (a fixed default would make every client's
            # "sampled" completion identical).
            import random as _random
            seed = _random.getrandbits(62)
        stream = self.engine.submit(
            ids, max_new,
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)), seed=int(seed))
        while True:
            toks = stream.get()
            if toks is None:
                return
            out = self._decode_text(toks)
            yield (out if isinstance(out, str)
                   else " ".join(str(t) for t in out) + " ")

    def complete(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """Non-streaming OpenAI-style response."""
        text = "".join(self(body))
        return {"object": "text_completion",
                "model": f"ray_tpu-llama-{self.cfg.d_model}",
                "choices": [{"index": 0, "text": text,
                             "finish_reason": "length"}]}


def _unflatten(host: Dict[str, Any]) -> Dict[str, Any]:
    """'a.b.c' host-checkpoint keys -> nested dict."""
    out: Dict[str, Any] = {}
    for key, value in host.items():
        parts = key.split(".")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = value
    return out


def build_llm_app(cfg: LLMConfig):
    """A bound serve application for this LLM config (reference:
    serve/llm build_openai_app)."""
    import cloudpickle

    dep = serve.deployment(
        num_replicas=cfg.num_replicas,
        num_tpus=cfg.num_tpus,
        max_ongoing_requests=cfg.max_ongoing_requests,
    )(LLMServer)
    return dep.bind(cloudpickle.dumps(cfg))


def _model_from_cfg(cfg: "LLMConfig"):
    """(LlamaConfig, device params) — shared by every server flavor."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.llama import LlamaConfig, init_params

    mcfg = LlamaConfig(
        vocab_size=cfg.vocab_size, d_model=cfg.d_model,
        n_layers=cfg.n_layers, n_heads=max(2, cfg.d_model // 128),
        n_kv_heads=max(1, cfg.d_model // 256),
        d_ff=int(cfg.d_model * 2.75), max_seq=cfg.max_seq)
    if cfg.params_path:
        from ray_tpu.train.checkpointing import load_checkpoint_host
        host = load_checkpoint_host(cfg.params_path)
        params = jax.tree.map(jnp.asarray, _unflatten(host))
    else:
        params = init_params(mcfg, jax.random.PRNGKey(0))
    return mcfg, jax.device_put(params)


# ---------------------------------------------------------------------------
# Prefill/decode disaggregation (reference:
# llm/_internal/serve/deployments/prefill_decode_disagg/
# prefill_decode_disagg.py:177 build_pd_openai_app — two engine pools
# joined by a KV-cache transfer backend; here the handoff rides
# DeviceRefs over the transfer plane: DMA within a slice, host-relay
# over DCN across slices).
# ---------------------------------------------------------------------------

def _encode_prompt(cfg: "LLMConfig", prompt) -> List[int]:
    if isinstance(prompt, list):
        return [int(t) for t in prompt]
    if cfg.tokenizer is not None:
        return cfg.tokenizer(prompt)
    raise ValueError(
        "string prompts need LLMConfig.tokenizer; or pass token ids")


class PrefillServer:
    """Prefill pool replica: one full causal pass per prompt, returning
    the first token + the KV cache as DeviceRefs (the tensors stay in
    this replica's HBM until the decode side pulls them)."""

    def __init__(self, cfg_blob: bytes):
        import threading

        import cloudpickle
        import jax
        import jax.numpy as jnp

        from ray_tpu.serve.engine import Engine, _make_prefill_core

        cfg: LLMConfig = cloudpickle.loads(cfg_blob)
        self.cfg = cfg
        self.mcfg, self.params = _model_from_cfg(cfg)
        self._core = jax.jit(_make_prefill_core(self.mcfg))
        from ray_tpu.serve.engine import _sample_tokens

        def _sample_first(row, temp, topk, key, pos):
            import jax.numpy as jnp
            return _sample_tokens(row[None], jnp.asarray(temp)[None],
                                  jnp.asarray(topk)[None], key[None],
                                  jnp.asarray(pos)[None])[0]

        self._sample1 = jax.jit(_sample_first)
        # Same bucket ladder + warm policy as the engine: smallest and
        # largest warm eagerly; intermediates warm in the background and
        # requests round UP to a warmed width until then (a synchronous
        # compile inside a request would spike TTFT for everything
        # queued behind it).
        self.buckets: List[int] = []
        b = min(Engine._MIN_BUCKET, self.mcfg.max_seq)
        while b < self.mcfg.max_seq:
            self.buckets.append(b)
            b *= 2
        self.buckets.append(self.mcfg.max_seq)
        self._warm = {self.buckets[0], self.buckets[-1]}

        def warm(width: int) -> None:
            out = self._core(self.params,
                             jnp.zeros((1, width), jnp.int32), 1)
            jax.block_until_ready(out)

        for width in sorted(self._warm):
            warm(width)

        def warm_rest():
            for width in self.buckets:
                if width not in self._warm:
                    try:
                        warm(width)
                        self._warm.add(width)
                    except Exception:
                        return

        threading.Thread(target=warm_rest, daemon=True,
                         name="prefill-bucket-warm").start()

    def prefill(self, body: Dict[str, Any]) -> Dict[str, Any]:
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.device_objects import device_put_ref
        from ray_tpu.serve.engine import _seed_key

        ids = _encode_prompt(self.cfg, body.get("prompt", [1]))
        ids = ids[: self.mcfg.max_seq - 1]
        width = next(b for b in self.buckets
                     if b >= len(ids) and b in self._warm)
        toks = np.zeros((1, width), np.int32)
        toks[0, :len(ids)] = ids
        first, ks, vs, logits_row = self._core(
            self.params, jnp.asarray(toks), len(ids))
        temp = float(body.get("temperature", 0.0))
        if temp > 0:
            # Sample the FIRST token here with the same (seed, position)
            # key derivation as the monolithic engine — identical seeds
            # give identical streams across deployment topologies.
            first = self._sample1(
                logits_row, temp, int(body.get("top_k", 0)),
                jnp.asarray(_seed_key(int(body.get("seed", 0)))),
                len(ids) - 1)
        return {
            "first": int(first),
            "length": len(ids),
            "k": device_put_ref(ks),
            "v": device_put_ref(vs),
        }


class DecodeServer:
    """Decode pool replica: the continuous-batching engine, fed by
    KV handoffs from the prefill pool."""

    def __init__(self, cfg_blob: bytes):
        import cloudpickle

        from ray_tpu.serve.engine import Engine

        cfg: LLMConfig = cloudpickle.loads(cfg_blob)
        self.cfg = cfg
        self.mcfg, params = _model_from_cfg(cfg)
        self.engine = Engine(params, self.mcfg,
                             n_slots=cfg.max_ongoing_requests,
                             decode_chunk=cfg.decode_chunk,
                             page_size=cfg.page_size,
                             n_pages=cfg.kv_pages)

    def decode_stream(self, meta: Dict[str, Any]):
        """Pull the prefilled KV (device plane; slice-aware) and stream
        the remaining tokens."""
        from ray_tpu.device_objects import device_get, free_ref

        kref, vref = meta["k"], meta["v"]
        ks = device_get(kref, timeout=120.0)
        vs = device_get(vref, timeout=120.0)
        # The prefill side's HBM copy is no longer needed.
        for r in (kref, vref):
            try:
                free_ref(r)
            except Exception:
                pass
        stream = self.engine.submit_prefilled(
            ks, vs, meta["length"], meta["first"], meta["max_tokens"],
            temperature=float(meta.get("temperature", 0.0)),
            top_k=int(meta.get("top_k", 0)),
            seed=int(meta.get("seed", 0)))
        while True:
            toks = stream.get()
            if toks is None:
                return
            yield toks


class PDIngress:
    """Router deployment: prompt -> prefill pool, stream -> decode pool
    (the reference's PDProxyServer shape). The first token streams to
    the client straight from the prefill reply — decode-pool admission
    never sits in front of TTFT."""

    def __init__(self, cfg_blob: bytes, prefill_name: str,
                 decode_name: str):
        import cloudpickle

        self.cfg: LLMConfig = cloudpickle.loads(cfg_blob)
        self._prefill = serve.get_deployment_handle(prefill_name)
        self._decode = serve.get_deployment_handle(decode_name)

    def _decode_text(self, ids: List[int]):
        out = self.cfg.detokenizer(ids) if self.cfg.detokenizer \
            is not None else ids
        return out if isinstance(out, str) \
            else " ".join(str(t) for t in out) + " "

    def __call__(self, body: Dict[str, Any]):
        max_new = int(body.get("max_tokens", 16))
        body = dict(body)
        if body.get("seed") is None:
            # Resolve the seed BEFORE prefill: the prefill side samples
            # the first token with it, the decode side continues with it.
            import random as _random
            body["seed"] = _random.getrandbits(62)
        meta = self._prefill.options(method_name="prefill").remote(
            body).result(timeout=300)
        yield self._decode_text([meta["first"]])
        if max_new <= 1:
            return
        meta["max_tokens"] = max_new
        meta["temperature"] = float(body.get("temperature", 0.0))
        meta["top_k"] = int(body.get("top_k", 0))
        meta["seed"] = int(body["seed"])
        for toks in self._decode.options(
                method_name="decode_stream").stream(meta):
            yield self._decode_text(toks)

    def complete(self, body: Dict[str, Any]) -> Dict[str, Any]:
        text = "".join(self(body))
        return {"object": "text_completion",
                "model": f"ray_tpu-llama-pd-{self.cfg.d_model}",
                "choices": [{"index": 0, "text": text,
                             "finish_reason": "length"}]}


def run_pd_llm_app(cfg: LLMConfig, *, name: str = "llm-pd",
                   num_prefill_replicas: int = 1,
                   num_decode_replicas: int = 1):
    """Deploy the disaggregated app: prefill pool + decode pool +
    ingress; returns the ingress handle (reference:
    prefill_decode_disagg.py:177 build_pd_openai_app)."""
    import cloudpickle

    blob = cloudpickle.dumps(cfg)
    prefill_dep = serve.deployment(
        name=f"{name}-prefill", num_replicas=num_prefill_replicas,
        num_tpus=cfg.num_tpus,
        max_ongoing_requests=cfg.max_ongoing_requests)(PrefillServer)
    decode_dep = serve.deployment(
        name=f"{name}-decode", num_replicas=num_decode_replicas,
        num_tpus=cfg.num_tpus,
        max_ongoing_requests=cfg.max_ongoing_requests)(DecodeServer)
    ingress_dep = serve.deployment(
        name=name, num_replicas=1,
        max_ongoing_requests=4 * cfg.max_ongoing_requests)(PDIngress)
    serve.run(prefill_dep.bind(blob), name=f"{name}-prefill")
    serve.run(decode_dep.bind(blob), name=f"{name}-decode")
    return serve.run(
        ingress_dep.bind(blob, f"{name}-prefill", f"{name}-decode"),
        name=name)
