"""LLM serving preset: Llama replicas behind an OpenAI-style endpoint.

Analogue of the reference's LLM layer (reference: python/ray/llm/ —
_internal/serve/deployments/llm/ wraps an engine as Serve deployments with
an OpenAI-compatible router, TP/PP sizes placed via PGs). TPU-native:
the engine IS this framework's Llama; decode runs in jitted device-side
chunks (one host sync per chunk — see bench_serve.py for the latency
math); replicas are serve deployments with num_tpus, streamed over the
proxy's chunked HTTP path.

Tokenization is bring-your-own (`LLMConfig.tokenizer` /`detokenizer`
callables); the default passes token-id lists through untouched — there
is no bundled vocabulary (weights here are random unless `params_path`
points at a checkpoint saved by ray_tpu.train).

    import ray_tpu.serve as serve
    from ray_tpu.serve.llm import LLMConfig, build_llm_app

    handle = serve.run(build_llm_app(LLMConfig(d_model=1024, n_layers=8)),
                       name="llm", route_prefix="/v1/completions")
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu.serve as serve


@dataclass
class LLMConfig:
    vocab_size: int = 32000
    d_model: int = 1024
    n_layers: int = 8
    max_seq: int = 512
    num_replicas: int = 1
    num_tpus: float = 1
    max_ongoing_requests: int = 8
    decode_chunk: int = 4          # tokens per device call
    params_path: str = ""          # ray_tpu.train checkpoint dir (optional)
    tokenizer: Optional[Callable[[str], List[int]]] = None
    detokenizer: Optional[Callable[[List[int]], str]] = None


class LLMServer:
    """The replica: builds the model once (XLA compile in the
    constructor; serve's startup grace covers it), then serves
    streaming completions."""

    def __init__(self, cfg_blob: bytes):
        import cloudpickle
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models.llama import LlamaConfig, forward, init_params

        cfg: LLMConfig = cloudpickle.loads(cfg_blob)
        self.cfg = cfg
        self.mcfg = LlamaConfig(
            vocab_size=cfg.vocab_size, d_model=cfg.d_model,
            n_layers=cfg.n_layers, n_heads=max(2, cfg.d_model // 128),
            n_kv_heads=max(1, cfg.d_model // 256),
            d_ff=int(cfg.d_model * 2.75), max_seq=cfg.max_seq)
        if cfg.params_path:
            from ray_tpu.train.checkpointing import load_checkpoint_host
            host = load_checkpoint_host(cfg.params_path)
            params = jax.tree.map(jnp.asarray, _unflatten(host))
        else:
            params = init_params(self.mcfg, jax.random.PRNGKey(0))
        self.params = jax.device_put(params)
        mcfg = self.mcfg

        def decode_chunk(params, buf, pos, n):
            def body(_, carry):
                buf, pos = carry
                logits = forward(params, buf, mcfg, None)
                nxt = jnp.argmax(logits[0, pos]).astype(jnp.int32)
                buf = jax.lax.dynamic_update_slice(
                    buf, nxt[None, None], (0, pos + 1))
                return buf, pos + 1

            return jax.lax.fori_loop(0, n, body, (buf, pos))

        self._decode = jax.jit(decode_chunk, static_argnums=3)
        toks = jnp.zeros((1, cfg.max_seq), jnp.int32)
        # Exactly TWO compiled shapes ever run: the 1-token TTFT chunk
        # and the full decode_chunk (residuals decode the full chunk and
        # truncate the emission — a residual-sized call would recompile
        # mid-request).
        for n in (1, cfg.decode_chunk):
            b, p = self._decode(self.params, toks, 8, n)
        int(p)
        self._np = np
        self._jnp = jnp

    def _encode(self, prompt) -> List[int]:
        if isinstance(prompt, list):
            return [int(t) for t in prompt]
        if self.cfg.tokenizer is not None:
            return self.cfg.tokenizer(prompt)
        raise ValueError(
            "string prompts need LLMConfig.tokenizer; or pass token ids")

    def _decode_text(self, ids: List[int]):
        if self.cfg.detokenizer is not None:
            return self.cfg.detokenizer(ids)
        return ids

    def __call__(self, body: Dict[str, Any]):
        """Streaming completion: yields decoded chunks (OpenAI-ish
        request body: {"prompt": [...ids] | str, "max_tokens": N})."""
        jnp, np = self._jnp, self._np
        ids = self._encode(body.get("prompt", [1]))[: self.cfg.max_seq - 1]
        max_new = int(body.get("max_tokens", 16))
        toks = np.zeros((1, self.cfg.max_seq), np.int32)
        toks[0, :len(ids)] = ids
        buf = jnp.asarray(toks)
        pos = len(ids) - 1
        produced = 0
        first = True
        # Stop when fewer than a full chunk of positions remain: only the
        # 1-token and full-chunk shapes are ever compiled.
        while produced < max_new and (
                pos + 1 + (0 if first else self.cfg.decode_chunk)
                <= self.cfg.max_seq):
            n = 1 if first else self.cfg.decode_chunk
            first = False
            buf, pos2 = self._decode(self.params, buf, pos, n)
            new = [int(t) for t in np.asarray(
                buf[0, pos + 1:int(pos2) + 1])][:max_new - produced]
            pos = int(pos2)
            produced += len(new)
            out = self._decode_text(new)
            yield (out if isinstance(out, str)
                   else " ".join(str(t) for t in out) + " ")

    def complete(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """Non-streaming OpenAI-style response."""
        text = "".join(self(body))
        return {"object": "text_completion",
                "model": f"ray_tpu-llama-{self.cfg.d_model}",
                "choices": [{"index": 0, "text": text,
                             "finish_reason": "length"}]}


def _unflatten(host: Dict[str, Any]) -> Dict[str, Any]:
    """'a.b.c' host-checkpoint keys -> nested dict."""
    out: Dict[str, Any] = {}
    for key, value in host.items():
        parts = key.split(".")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = value
    return out


def build_llm_app(cfg: LLMConfig):
    """A bound serve application for this LLM config (reference:
    serve/llm build_openai_app)."""
    import cloudpickle

    dep = serve.deployment(
        num_replicas=cfg.num_replicas,
        num_tpus=cfg.num_tpus,
        max_ongoing_requests=cfg.max_ongoing_requests,
    )(LLMServer)
    return dep.bind(cloudpickle.dumps(cfg))
