"""DeploymentHandle + Router — the data-plane client.

Analogue of the reference's handle/router (reference: serve/handle.py
DeploymentHandle, serve/_private/router.py Router:433, request_router/
pow_2_router.py PowerOfTwoChoicesRequestRouter:27): each handle owns a
router that picks a replica per request by power-of-two-choices — probe
two random replicas' queue lengths, send to the shorter — with a local
routing-table cache refreshed on version bumps and on replica failure.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu


class DeploymentResponse:
    """Future-like result of handle.remote() (reference: serve/handle.py
    DeploymentResponse). Replica death surfaces here (actor submission is
    async), so result() re-routes the request once through the router."""

    def __init__(self, ref, retry=None):
        self._ref = ref
        self._retry = retry

    def result(self, timeout: Optional[float] = None):
        from ray_tpu.core.common import (ActorDiedError, ObjectLostError,
                                         WorkerCrashedError)
        try:
            return ray_tpu.get(self._ref, timeout=timeout)
        except (ActorDiedError, WorkerCrashedError, ObjectLostError):
            if self._retry is None:
                raise
            self._ref = self._retry()
            self._retry = None  # one re-route per request
            return ray_tpu.get(self._ref, timeout=timeout)

    @property
    def ref(self):
        return self._ref


# ONE pubsub subscription per CORE WORKER invalidates every live router
# (weakly referenced, so handles still GC); per-router subscriptions
# would leak a perpetual poll loop per handle. Keyed by the worker, not
# a process-lifetime boolean: a shutdown + re-init gets a fresh
# subscription on the new worker's loop.
_routers: "Any" = None
_sub_cw: "Any" = None  # weakref to the core worker currently subscribed


def _ttl_warning() -> None:
    from ray_tpu.utils import get_logger
    get_logger("serve").warning(
        "serve router push-invalidation unavailable; falling back "
        "to the %ss table TTL", Router._TABLE_TTL_S)


def _register_router(router: "Router") -> None:
    global _routers, _sub_cw
    import weakref

    if _routers is None:
        _routers = weakref.WeakSet()
    _routers.add(router)
    try:
        from ray_tpu.core.pubsub import Subscription
        from ray_tpu.core.ref import get_core_worker
        cw = get_core_worker()
    except Exception:
        _ttl_warning()  # no runtime (unit tests): TTL still refreshes
        return
    if _sub_cw is not None and _sub_cw() is cw:
        return  # this worker already runs the subscription

    def _invalidate(_event):
        for r in list(_routers):
            r._checked = 0.0  # next choose re-reads the table

    async def _start():
        global _sub_cw
        try:
            Subscription(cw.controller, "serve_events", _invalidate,
                         from_latest=True).start()
        except Exception:
            _sub_cw = None  # a later router retries
            _ttl_warning()

    _sub_cw = weakref.ref(cw)
    cw._spawn(_start())


class Router:
    """Pow-2 replica chooser with a push-invalidated routing table.

    The serve controller publishes every version bump on the runtime's
    pubsub hub (channel "serve_events"); the router subscribes and drops
    its cache the moment a deploy/scale lands — the TTL below is only a
    safety net against a lost push (reference:
    serve/_private/long_poll.py:228 LongPollHost push updates)."""

    _TABLE_TTL_S = 30.0  # fallback only; pushes invalidate immediately

    _QLEN_TTL_S = 0.1  # probe cache: bounds probe RPCs to ~20/s per pair

    def __init__(self, deployment: str, controller_handle):
        self._deployment = deployment
        self._controller = controller_handle
        self._replicas: List[Any] = []
        self._version = -1
        self._checked = 0.0
        self._lock = threading.Lock()
        self._qlen_cache: Dict[bytes, tuple] = {}  # aid -> (qlen, ts)
        # model_id -> replica actor_id: sticky multiplexing affinity
        # (reference: serve/multiplex.py routes to replicas holding the
        # model; ours is client-side stickiness with pow-2 fallback).
        self._model_affinity: Dict[str, bytes] = {}
        _register_router(self)

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not force and now - self._checked < self._TABLE_TTL_S \
                    and self._replicas:
                return
            self._checked = now
            table = ray_tpu.get(self._controller.routing_table.remote(),
                                timeout=30)
            if table["version"] != self._version:
                self._version = table["version"]
                self._replicas = table["deployments"].get(
                    self._deployment, [])

    def choose_replica(self, model_id: str = ""):
        """Power-of-two-choices over live queue lengths (reference:
        pow_2_router.py:52 choose_replicas); multiplexed requests stick
        to the replica that last served their model id."""
        self._refresh()
        replicas = self._replicas
        if not replicas:
            raise RuntimeError(
                f"deployment {self._deployment!r} has no replicas")
        if model_id:
            aid = self._model_affinity.get(model_id)
            if aid is not None:
                for r in replicas:
                    if r.actor_id.binary() == aid:
                        return r
            chosen = self._choose_pow2(replicas)
            self._model_affinity[model_id] = chosen.actor_id.binary()
            return chosen
        return self._choose_pow2(replicas)

    def _choose_pow2(self, replicas):
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        try:
            qa = self._queue_len(a)
            qb = self._queue_len(b)
        except Exception:
            self._refresh(force=True)
            return random.choice(self._replicas or replicas)
        return a if qa <= qb else b

    def _queue_len(self, replica) -> int:
        """Cached queue-length probe: a hot request path must not pay two
        RPC round trips per request (reference routers cache replica
        load similarly)."""
        aid = replica.actor_id.binary()
        now = time.monotonic()
        hit = self._qlen_cache.get(aid)
        if hit is not None and now - hit[1] < self._QLEN_TTL_S:
            return hit[0]
        q = ray_tpu.get(replica.queue_len.remote(), timeout=5)
        self._qlen_cache[aid] = (q, now)
        return q

    def on_replica_error(self) -> None:
        # Sticky affinity must not outlive a failure: retries have to be
        # free to fail over to a healthy replica.
        self._model_affinity.clear()
        self._refresh(force=True)


class DeploymentHandle:
    def __init__(self, deployment: str, controller_handle,
                 method: str = "__call__", multiplexed_model_id: str = "",
                 _router: Optional[Router] = None):
        self._deployment = deployment
        self._controller = controller_handle
        self._method = method
        self._model_id = multiplexed_model_id
        # A Router owns a live pubsub subscription: options() MUST share
        # the parent's instead of constructing a throwaway one.
        self._router = _router or Router(deployment, controller_handle)

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None
                ) -> "DeploymentHandle":
        return DeploymentHandle(
            self._deployment, self._controller,
            method_name if method_name is not None else self._method,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._model_id,
            _router=self._router)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        blob = cloudpickle.dumps((args, kwargs))

        def dispatch():
            # Synchronous submission failures (stale table, dead handle)
            # refresh the router and retry a couple of times; deaths that
            # surface later are covered by the result()-side re-route.
            last: Optional[Exception] = None
            for _ in range(3):
                try:
                    replica = self._router.choose_replica(self._model_id)
                    return replica.handle_request.remote(
                        self._method, blob, self._model_id)
                except Exception as e:
                    last = e
                    self._router.on_replica_error()
            raise RuntimeError(
                f"could not route request to {self._deployment!r}: "
                f"{last!r}")

        def re_route():
            # Replica died after dispatch: refresh the table and resend.
            self._router.on_replica_error()
            return dispatch()

        return DeploymentResponse(dispatch(), retry=re_route)

    def stream(self, *args, **kwargs):
        """Streaming call: the deployment method must be a generator;
        yields values as the replica produces them (reference: Serve
        streaming responses over ObjectRefGenerator)."""
        blob = cloudpickle.dumps((args, kwargs))
        replica = self._router.choose_replica(self._model_id)
        gen = replica.handle_request_streaming.options(
            num_returns="streaming").remote(self._method, blob,
                                            self._model_id)
        for ref in gen:
            yield ray_tpu.get(ref)
