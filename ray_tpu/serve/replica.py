"""ReplicaActor — hosts one copy of the user's deployment.

Analogue of the reference's replica (reference: serve/_private/replica.py
ReplicaActor:1095 — user callable wrapping, concurrent request handling,
health checks, ongoing-request metrics for the router and autoscaler).
Async actor: requests run concurrently on the io loop up to
max_ongoing_requests; queue_len() answers router probes instantly even
while requests are in flight.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any, Dict, Optional

import cloudpickle


class Replica:
    """One deployment copy (created via the actor runtime)."""

    def __init__(self, cls_blob: bytes, init_args_blob: bytes,
                 deployment_name: str, max_ongoing: int = 100):
        cls = cloudpickle.loads(cls_blob)
        args, kwargs = cloudpickle.loads(init_args_blob)
        # App composition (reference: serve/handle.py model composition):
        # bound child deployments arrive as markers; resolve each to a
        # live DeploymentHandle here, in the replica process.
        from ray_tpu.serve.api import _resolve_handle_markers
        args, kwargs = _resolve_handle_markers(args, kwargs)
        self._user = cls(*args, **kwargs)
        self._name = deployment_name
        self._max_ongoing = max_ongoing
        self._ongoing = 0
        self._total = 0
        self._sem = asyncio.Semaphore(max_ongoing)
        self._started = time.time()

    async def handle_request(self, method: str, args_blob: bytes,
                             model_id: str = ""):
        """Run one request through the user callable (async-concurrent).
        Sync callables go to a thread pool — running them on the io loop
        would stall health checks and queue probes, and the controller
        would kill a merely-busy replica."""
        import contextvars

        from ray_tpu.serve.multiplex import _set_current_model_id

        args, kwargs = cloudpickle.loads(args_blob)
        fn = getattr(self._user, method)
        self._ongoing += 1
        self._total += 1
        try:
            async with self._sem:
                _set_current_model_id(model_id)
                if inspect.iscoroutinefunction(fn):
                    return await fn(*args, **kwargs)
                loop = asyncio.get_running_loop()
                # copy_context: run_in_executor does NOT propagate
                # contextvars, and get_multiplexed_model_id must work
                # inside sync callables too.
                ctx = contextvars.copy_context()
                return await loop.run_in_executor(
                    None, lambda: ctx.run(fn, *args, **kwargs))
        finally:
            self._ongoing -= 1

    def handle_request_streaming(self, method: str, args_blob: bytes,
                                 model_id: str = ""):
        """Streaming variant: the user method is a (sync) generator; items
        stream back through the runtime's ObjectRefGenerator."""
        from ray_tpu.serve.multiplex import _set_current_model_id

        args, kwargs = cloudpickle.loads(args_blob)
        fn = getattr(self._user, method)
        self._ongoing += 1
        self._total += 1
        try:
            _set_current_model_id(model_id)
            yield from fn(*args, **kwargs)
        finally:
            self._ongoing -= 1

    async def queue_len(self) -> int:
        """Router probe (reference: pow_2_router queue-length probes)."""
        return self._ongoing

    async def health(self) -> dict:
        ok = True
        check = getattr(self._user, "check_health", None)
        if check is not None:
            try:
                res = check()
                if inspect.isawaitable(res):
                    await res
            except Exception:
                ok = False
        return {"healthy": ok, "ongoing": self._ongoing,
                "total": self._total, "uptime_s": time.time() - self._started}
