"""gRPC ingress — the second data-plane flavor.

Analogue of the reference's gRPC proxy (reference:
serve/_private/proxy.py:530 gRPCProxy — a grpc.aio server routing
user-proto RPCs to deployment handles). Redesigned proto-less: one
generic byte service, so applications don't compile protos to reach
their deployments —

    service raytpu.serve.ServeAPI {
      rpc Call   (bytes) returns (bytes);          // unary
      rpc Stream (bytes) returns (stream bytes);   // server-streaming
      rpc Routes (bytes) returns (bytes);          // route table / health
    }

Requests are JSON: {"app": name | "route": prefix, "method": optional
replica method, "payload": body}. Call replies {"result": ...} JSON;
Stream yields each item as a bytes frame (text encodes utf-8). gRPC
status codes carry errors (NOT_FOUND for unroutable, INTERNAL for
application failures).
"""

from __future__ import annotations

import json
from concurrent import futures
from typing import Optional

from ray_tpu.serve.routing import RouteTable
from ray_tpu.utils import get_logger

logger = get_logger("serve.grpc")

SERVICE = "raytpu.serve.ServeAPI"


class _Identity:
    """bytes-through (de)serializer for the generic service."""

    @staticmethod
    def passthrough(b):
        return b


class GrpcProxy:
    """One per node, like the HTTP proxy (reference runs both ingress
    flavors off the same ProxyActor)."""

    def __init__(self, controller_handle, host: str = "127.0.0.1",
                 port: int = 0, max_workers: int = 16):
        import grpc

        self._table = RouteTable(controller_handle)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max_workers,
                thread_name_prefix="grpc-proxy"))
        self._server.add_generic_rpc_handlers((_Handler(self._table),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=2).wait(timeout=10)


def _make_handler_class():
    """Defer the grpc import to proxy construction (serve without gRPC
    never pays for it)."""
    import grpc

    class Handler(grpc.GenericRpcHandler):
        def __init__(self, table: RouteTable):
            self._table = table

        def service(self, call_details):
            method = call_details.method
            if method == f"/{SERVICE}/Call":
                return grpc.unary_unary_rpc_method_handler(
                    self._call, request_deserializer=_Identity.passthrough,
                    response_serializer=_Identity.passthrough)
            if method == f"/{SERVICE}/Stream":
                return grpc.unary_stream_rpc_method_handler(
                    self._stream,
                    request_deserializer=_Identity.passthrough,
                    response_serializer=_Identity.passthrough)
            if method == f"/{SERVICE}/Routes":
                return grpc.unary_unary_rpc_method_handler(
                    self._routes,
                    request_deserializer=_Identity.passthrough,
                    response_serializer=_Identity.passthrough)
            return None

        # -- helpers ---------------------------------------------------
        def _resolve(self, request: bytes, context):
            try:
                req = json.loads(request) if request else {}
            except json.JSONDecodeError:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              "request must be JSON")
            if not isinstance(req, dict):
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              "request must be a JSON object")
            name = req.get("app")
            if name is not None:
                # Validate against the table so an unknown app aborts
                # NOT_FOUND here, not INTERNAL deep in dispatch. The
                # refresh is rate-limited: unknown-app probe storms must
                # not become controller RPC storms.
                if name not in self._table.routes.values() \
                        and self._table.should_refresh():
                    self._table.refresh()
                if name not in self._table.routes.values():
                    name = None
            elif req.get("route"):
                name = self._table.match(req["route"])
                if name is None and self._table.should_refresh():
                    self._table.refresh()
                    name = self._table.match(req["route"])
            if name is None:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"no deployment for {req!r}")
            handle = self._table.handle_for(name)
            if req.get("method"):
                handle = handle.options(method_name=req["method"])
            return handle, req.get("payload")

        # -- RPCs ------------------------------------------------------
        def _call(self, request: bytes, context) -> bytes:
            handle, payload = self._resolve(request, context)
            try:
                result = handle.remote(payload).result(timeout=120)
                return json.dumps({"result": result}).encode()
            except Exception as e:
                # Covers non-JSON-serializable results too: the status
                # contract says application failures are INTERNAL.
                context.abort(grpc.StatusCode.INTERNAL, repr(e))

        def _stream(self, request: bytes, context):
            handle, payload = self._resolve(request, context)
            it = handle.stream(payload)
            try:
                for item in it:
                    if not context.is_active():
                        return  # client left: release the replica stream
                    yield (item if isinstance(item, (bytes, bytearray))
                           else str(item).encode())
            except Exception as e:
                context.abort(grpc.StatusCode.INTERNAL, repr(e))
            finally:
                close = getattr(it, "close", None)
                if close:
                    close()

        def _routes(self, request: bytes, context) -> bytes:
            self._table.refresh()
            return json.dumps(self._table.routes).encode()

    return Handler


_handler_cls: Optional[type] = None


def _Handler(table: RouteTable):
    global _handler_cls
    if _handler_cls is None:
        _handler_cls = _make_handler_class()
    return _handler_cls(table)
