"""ray_tpu.serve — model serving on the actor runtime.

Analogue of Ray Serve (reference: python/ray/serve/ — ServeController
controller.py:103, HTTPProxy proxy.py:706, Router router.py:433 +
pow_2_router.py:27, ReplicaActor replica.py:1095, @serve.batch
batching.py), rebuilt TPU-first on async actors: replicas handle requests
concurrently on their io loop, routers pick replicas by
power-of-two-choices over live queue lengths, and JAX model replicas batch
via @serve.batch so the MXU sees full batches.

    import ray_tpu.serve as serve

    @serve.deployment(num_replicas=2)
    class Echo:
        async def __call__(self, request):
            return request

    serve.run(Echo.bind(), name="echo")
    handle = serve.get_deployment_handle("echo")
    out = handle.remote({"x": 1}).result()
"""

from ray_tpu.serve.api import (Application, Deployment, batch, delete,
                               deployment, get_deployment_handle,
                               get_grpc_proxy, get_proxy, run, shutdown,
                               start)
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed

__all__ = [
    "Application", "Deployment", "DeploymentHandle", "DeploymentResponse",
    "batch", "delete", "deployment", "get_deployment_handle",
    "get_grpc_proxy", "get_proxy",
    "get_multiplexed_model_id", "multiplexed", "run", "shutdown", "start",
]
