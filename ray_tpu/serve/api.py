"""Public Serve API: @deployment, bind, run, handles, @batch.

Analogue of the reference's surface (reference: serve/api.py serve.run:685,
serve/deployment.py Deployment/@serve.deployment, serve/batching.py
@serve.batch). The controller is a named detached-style actor; deploys are
idempotent upserts.
"""

from __future__ import annotations

import asyncio
import functools
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.serve.controller import ServeController
from ray_tpu.serve.handle import DeploymentHandle


class Application:
    """A bound deployment (class + init args), deployable via serve.run
    (reference: Application returned by Deployment.bind)."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.init_args = args
        self.init_kwargs = kwargs


class Deployment:
    def __init__(self, cls: type, name: str, config: Dict[str, Any]):
        self._cls = cls
        self.name = name
        self._config = config

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def options(self, **overrides) -> "Deployment":
        cfg = dict(self._config)
        name = overrides.pop("name", self.name)
        cfg.update(overrides)
        return Deployment(self._cls, name, cfg)


def deployment(cls: Optional[type] = None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 100,
               num_cpus: Optional[float] = None, num_tpus: float = 0,
               autoscaling_config: Optional[dict] = None):
    """@serve.deployment decorator (reference: serve/deployment.py)."""

    def wrap(c: type) -> Deployment:
        return Deployment(c, name or c.__name__, {
            "num_replicas": num_replicas,
            "max_ongoing_requests": max_ongoing_requests,
            "num_cpus": num_cpus,
            "num_tpus": num_tpus,
            "autoscaling_config": autoscaling_config,
        })

    return wrap(cls) if cls is not None else wrap


# ---------------------------------------------------------------------------
# controller lifecycle
# ---------------------------------------------------------------------------

_controller_handle = None
_proxy = None
_grpc_proxy = None


def start(*, http: bool = False, http_port: int = 0,
          http_host: str = "127.0.0.1", grpc: bool = False,
          grpc_port: int = 0):
    """Ensure the Serve controller (and optionally the HTTP and/or gRPC
    ingress proxies) is up (reference: serve.start + proxies per node,
    serve/_private/proxy.py HTTPProxy:706 / gRPCProxy:530)."""
    global _controller_handle, _proxy, _grpc_proxy
    if _controller_handle is None:
        try:
            _controller_handle = ray_tpu.get_actor(
                ServeController.CONTROLLER_NAME)
        except ValueError:
            _controller_handle = ray_tpu.remote(ServeController).options(
                name=ServeController.CONTROLLER_NAME,
                max_restarts=1).remote()
            # Wait until it answers.
            ray_tpu.get(_controller_handle.routing_version.remote(),
                        timeout=60)
    if http and _proxy is None:
        from ray_tpu.serve.proxy import HttpProxy
        _proxy = HttpProxy(_controller_handle, http_host, http_port)
    if grpc and _grpc_proxy is None:
        from ray_tpu.serve.grpc_proxy import GrpcProxy
        _grpc_proxy = GrpcProxy(_controller_handle, http_host, grpc_port)
    return _controller_handle


def get_proxy():
    """The in-process HTTP proxy started by serve.start(http=True)."""
    return _proxy


def get_grpc_proxy():
    """The in-process gRPC proxy started by serve.start(grpc=True)."""
    return _grpc_proxy


class _HandleMarker:
    """Serialization-safe stand-in for a bound child deployment inside a
    parent's init args; replicas resolve it to a DeploymentHandle at
    construction (reference: serve model composition —
    Deployment.bind(child.bind()) wires handles through init args)."""

    def __init__(self, deployment_name: str):
        self.deployment_name = deployment_name


def _map_nested(convert, v):
    """Apply convert through lists/tuples/dicts (init args commonly
    carry children inside containers)."""
    out = convert(v)
    if out is not v:
        return out
    if isinstance(v, list):
        return [_map_nested(convert, x) for x in v]
    if isinstance(v, tuple):
        return tuple(_map_nested(convert, x) for x in v)
    if isinstance(v, dict):
        return {k: _map_nested(convert, x) for k, x in v.items()}
    return v


def _resolve_handle_markers(args: tuple, kwargs: dict):
    """Replica-side: markers -> live DeploymentHandles."""
    def convert(v):
        if isinstance(v, _HandleMarker):
            return get_deployment_handle(v.deployment_name)
        return v

    return tuple(_map_nested(convert, a) for a in args), \
        {k: _map_nested(convert, v) for k, v in kwargs.items()}


def _deploy_children(args: tuple, kwargs: dict):
    """Driver-side: deploy every bound child Application found in the
    parent's init args (recursing through containers) and substitute
    markers."""
    def convert(v):
        if isinstance(v, Application):
            child_handle = run(v)
            return _HandleMarker(child_handle._deployment)
        if isinstance(v, Deployment):
            child_handle = run(v.bind())
            return _HandleMarker(child_handle._deployment)
        return v

    return tuple(_map_nested(convert, a) for a in args), \
        {k: _map_nested(convert, v) for k, v in kwargs.items()}


def run(app: "Application | Deployment", *, name: Optional[str] = None,
        route_prefix: Optional[str] = None) -> DeploymentHandle:
    """Deploy (upsert) an application; blocks until replicas are live
    (reference: serve.run, serve/api.py:685). Bound child deployments in
    the init args deploy first and arrive in the constructor as
    DeploymentHandles (app composition)."""
    controller = start()
    if isinstance(app, Deployment):
        app = app.bind()
    dep = app.deployment
    dep_name = name or dep.name
    init_args, init_kwargs = _deploy_children(app.init_args,
                                              app.init_kwargs)
    config = dict(dep._config)
    config["cls_blob"] = cloudpickle.dumps(dep._cls)
    config["init_args_blob"] = cloudpickle.dumps(
        (init_args, init_kwargs))
    config["route_prefix"] = route_prefix or f"/{dep_name}"
    ray_tpu.get(controller.deploy.remote(dep_name,
                                         cloudpickle.dumps(config)),
                timeout=120)
    handle = DeploymentHandle(dep_name, controller)
    # Block until at least one replica has PASSED a health check (heavy
    # init — model load + XLA compile — happens in the constructor).
    deadline = time.monotonic() + 600
    while time.monotonic() < deadline:
        try:
            if ray_tpu.get(controller.ready_replicas.remote(dep_name),
                           timeout=30) > 0:
                handle._router._refresh(force=True)
                return handle
        except Exception:
            pass
        time.sleep(0.5)
    raise TimeoutError(f"deployment {dep_name!r} never became ready")


def get_deployment_handle(name: str) -> DeploymentHandle:
    controller = start()
    return DeploymentHandle(name, controller)


def delete(name: str) -> None:
    controller = start()
    ray_tpu.get(controller.delete_deployment.remote(name), timeout=60)


def shutdown() -> None:
    global _controller_handle, _proxy, _grpc_proxy
    if _proxy is not None:
        _proxy.stop()
        _proxy = None
    if _grpc_proxy is not None:
        try:
            _grpc_proxy.stop()
        except Exception:
            pass
        _grpc_proxy = None
    if _controller_handle is not None:
        try:
            ray_tpu.get(_controller_handle.shutdown_serve.remote(),
                        timeout=30)
            ray_tpu.kill(_controller_handle)
        except Exception:
            pass
        _controller_handle = None


# ---------------------------------------------------------------------------
# @serve.batch (reference: serve/batching.py)
# ---------------------------------------------------------------------------

def batch(fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Coalesce concurrent single calls into one batched call: the wrapped
    async method receives a LIST of inputs and must return a list of
    outputs in order. Essential for JAX replicas — the MXU wants full
    batches, and XLA recompiles per batch size, so sizes are capped at
    max_batch_size (padding to fixed shapes is the model's concern)."""

    def wrap(f: Callable):
        # Per-instance queue stored ON the instance (a closure-level lock
        # would make the deployment class unpicklable; and replica async
        # methods all run on one io loop, so no lock is needed).
        attr = f"__serve_batch_queue_{f.__name__}"

        async def flush(self_obj):
            batch_items = getattr(self_obj, attr, None)
            if not batch_items:
                return
            setattr(self_obj, attr, [])
            inputs = [i for i, _ in batch_items]
            try:
                outputs = await f(self_obj, inputs)
                assert len(outputs) == len(inputs), \
                    "@batch fn must return one output per input"
                for (_, fut), out in zip(batch_items, outputs):
                    if not fut.done():
                        fut.set_result(out)
            except BaseException as e:  # noqa: BLE001
                for _, fut in batch_items:
                    if not fut.done():
                        fut.set_exception(e)

        @functools.wraps(f)
        async def wrapper(self_obj, item):
            fut = asyncio.get_running_loop().create_future()
            q = getattr(self_obj, attr, None)
            if q is None:
                q = []
                setattr(self_obj, attr, q)
            q.append((item, fut))
            if len(q) >= max_batch_size:
                await flush(self_obj)
            else:
                from ray_tpu.utils.aio import spawn

                async def delayed():
                    await asyncio.sleep(batch_wait_timeout_s)
                    await flush(self_obj)
                spawn(delayed())
            return await fut

        return wrapper

    return wrap(fn) if fn is not None else wrap
