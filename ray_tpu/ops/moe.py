"""Mixture-of-experts routing and dispatch for the expert-parallel (``ep``)
mesh axis.

The reference framework only passes expert-parallel sizes through to vLLM
(SURVEY.md §2.3 — EP row: "Not in Ray"); here MoE is a native layer. Round-1
implementation uses dense one-hot dispatch (einsum against a one-hot combine
tensor) — fully static shapes, MXU-friendly, correct under any sharding; the
experts' weight leading axis carries the logical "expert" axis which the
sharding rules map onto ``ep``. A ragged all-to-all Pallas dispatch is the
planned optimization.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def top_k_routing(gate_logits: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """gate_logits: [tokens, n_experts] -> (weights [tokens, k], idx [tokens, k]).

    Weights are softmaxed over the selected k (Mixtral-style).
    """
    vals, idx = jax.lax.top_k(gate_logits, k)
    weights = jax.nn.softmax(vals.astype(jnp.float32), axis=-1)
    return weights, idx


def moe_ffn(x: jax.Array, gate_w: jax.Array, w_up: jax.Array, w_gate: jax.Array,
            w_down: jax.Array, *, top_k: int = 2) -> Tuple[jax.Array, jax.Array]:
    """SwiGLU MoE feed-forward with dense dispatch.

    x: [tokens, d_model]
    gate_w: [d_model, n_experts] router weights
    w_up/w_gate: [n_experts, d_model, d_ff]; w_down: [n_experts, d_ff, d_model]
    Returns (out [tokens, d_model], aux_loss scalar).
    """
    n_experts = gate_w.shape[-1]
    logits = jnp.einsum("td,de->te", x, gate_w,
                        preferred_element_type=jnp.float32)
    weights, idx = top_k_routing(logits, top_k)
    # combine[t, e] = routing weight of token t for expert e (0 if unselected)
    one_hot = jax.nn.one_hot(idx, n_experts, dtype=jnp.float32)  # [t, k, e]
    combine = jnp.einsum("tk,tke->te", weights, one_hot)

    # Dense dispatch: every expert sees every token, masked by combine weight.
    # Static shapes; the "expert" (leading) axis shards over ep so each device
    # computes only its local experts and psums the combine below via GSPMD.
    h_up = jnp.einsum("td,edf->etf", x, w_up)
    h_gate = jnp.einsum("td,edf->etf", x, w_gate)
    h = jax.nn.silu(h_gate) * h_up
    expert_out = jnp.einsum("etf,efd->etd", h, w_down)
    out = jnp.einsum("etd,te->td", expert_out.astype(jnp.float32), combine)

    # Load-balancing aux loss (Switch-style): mean prob * mean assignment frac.
    probs = jax.nn.softmax(logits, axis=-1)
    frac_tokens = jnp.mean(one_hot.sum(axis=1), axis=0)  # [e]
    frac_prob = jnp.mean(probs, axis=0)
    aux = n_experts * jnp.sum(frac_tokens * frac_prob)
    return out.astype(x.dtype), aux
