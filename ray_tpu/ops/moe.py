"""Mixture-of-experts routing and dispatch for the expert-parallel (``ep``)
mesh axis.

The reference framework only passes expert-parallel sizes through to vLLM
(SURVEY.md §2.3 — EP row: "Not in Ray"); here MoE is a native layer.
Dispatch is CAPACITY-BASED gather/scatter (GShard/Switch style): each
expert processes at most ``capacity = tokens*top_k*capacity_factor/E``
tokens, so compute is O(tokens * top_k * capacity_factor * d * f) instead
of the round-1 dense dispatch's O(tokens * n_experts * d * f) — an
E/(k*cf) FLOPs saving — while every shape stays static for XLA. The
experts' weight leading axis carries the logical "expert" axis which the
sharding rules map onto ``ep``; the scatter/gather lowers to the
expert-parallel all-to-all under GSPMD.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp


def top_k_routing(gate_logits: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """gate_logits: [tokens, n_experts] -> (weights [tokens, k], idx [tokens, k]).

    Weights are softmaxed over the selected k (Mixtral-style).
    """
    vals, idx = jax.lax.top_k(gate_logits, k)
    weights = jax.nn.softmax(vals.astype(jnp.float32), axis=-1)
    return weights, idx


def moe_ffn(x: jax.Array, gate_w: jax.Array, w_up: jax.Array, w_gate: jax.Array,
            w_down: jax.Array, *, top_k: int = 2,
            capacity_factor: float = 1.25
            ) -> Tuple[jax.Array, jax.Array]:
    """SwiGLU MoE feed-forward with capacity-based dispatch.

    x: [tokens, d_model]
    gate_w: [d_model, n_experts] router weights
    w_up/w_gate: [n_experts, d_model, d_ff]; w_down: [n_experts, d_ff, d_model]
    Returns (out [tokens, d_model], aux_loss scalar). Tokens routed to an
    expert already at capacity are dropped for that expert (standard
    Switch/GShard overflow semantics; raise capacity_factor to avoid).
    """
    tokens, d_model = x.shape
    n_experts = gate_w.shape[-1]
    logits = jnp.einsum("td,de->te", x, gate_w,
                        preferred_element_type=jnp.float32)
    weights, idx = top_k_routing(logits, top_k)          # [t,k], [t,k]
    one_hot = jax.nn.one_hot(idx, n_experts, dtype=jnp.float32)  # [t,k,e]

    capacity = max(1, math.ceil(tokens * top_k * capacity_factor
                                / n_experts))

    # Flatten assignments token-major: slot position of each assignment
    # within its expert via a running count (no sort needed).
    flat_expert = idx.reshape(-1)                        # [t*k]
    flat_weight = weights.reshape(-1)                    # [t*k]
    flat_token = jnp.repeat(jnp.arange(tokens), top_k)   # [t*k]
    # int32 cumsum: float32 counting loses exactness past 2^24 assignments
    # (slot collisions would silently corrupt dispatch at large batches).
    flat_oh_i = one_hot.reshape(tokens * top_k, n_experts).astype(jnp.int32)
    pos_in_expert = jnp.cumsum(flat_oh_i, axis=0) - flat_oh_i  # [t*k, e]
    pos = jnp.sum(pos_in_expert * flat_oh_i, axis=-1).astype(jnp.int32)
    keep = pos < capacity
    # Overflow assignments land in a trash slot past the real buffer.
    slot = jnp.where(keep, flat_expert * capacity + pos,
                     n_experts * capacity).astype(jnp.int32)

    # Dispatch: gather tokens into [e*c(+trash), d], compute experts on
    # static [e, c, d] shapes (leading axis shards over ep), combine back.
    buf = jnp.zeros((n_experts * capacity + 1, d_model), x.dtype)
    buf = buf.at[slot].set(x[flat_token])
    xe = buf[:n_experts * capacity].reshape(n_experts, capacity, d_model)
    h_up = jnp.einsum("ecd,edf->ecf", xe, w_up)
    h_gate = jnp.einsum("ecd,edf->ecf", xe, w_gate)
    h = jax.nn.silu(h_gate) * h_up
    expert_out = jnp.einsum("ecf,efd->ecd", h, w_down)   # [e, c, d]

    flat_out = jnp.concatenate(
        [expert_out.reshape(n_experts * capacity, d_model),
         jnp.zeros((1, d_model), expert_out.dtype)])     # trash slot -> 0
    gathered = flat_out[slot].astype(jnp.float32)        # [t*k, d]
    contrib = gathered * (flat_weight * keep)[:, None]
    out = jnp.zeros((tokens, d_model), jnp.float32).at[flat_token].add(
        contrib)

    # Load-balancing aux loss (Switch-style): mean prob * mean assignment frac.
    probs = jax.nn.softmax(logits, axis=-1)
    frac_tokens = jnp.mean(one_hot.sum(axis=1), axis=0)  # [e]
    frac_prob = jnp.mean(probs, axis=0)
    aux = n_experts * jnp.sum(frac_tokens * frac_prob)
    return out.astype(x.dtype), aux
