from ray_tpu.ops.attention import attention_reference, flash_attention, repeat_kv
from ray_tpu.ops.moe import moe_ffn, top_k_routing
from ray_tpu.ops.norms import apply_rope, rms_norm, rope_frequencies
from ray_tpu.ops.ring_attention import ring_attention

__all__ = ["attention_reference", "flash_attention", "repeat_kv", "moe_ffn",
           "top_k_routing", "apply_rope", "rms_norm", "rope_frequencies",
           "ring_attention"]
