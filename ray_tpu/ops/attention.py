"""Attention ops: reference softmax attention, Pallas TPU flash attention.

The reference framework has no attention kernels of its own (it orchestrates
engines like vLLM — reference: python/ray/llm/_internal/serve/deployments/llm/
vllm/vllm_models.py); in the TPU-native rebuild the compute path is first-class,
so the framework ships its own kernels.

Design:
  * ``attention_reference`` — pure jnp, fp32 softmax; ground truth for tests
    and the CPU path.
  * ``_flash_fwd_pallas`` — Pallas TPU forward kernel, online-softmax over KV
    blocks with VMEM accumulators (MXU-aligned 128-multiple block shapes).
  * ``flash_attention`` — custom_vjp: Pallas forward on TPU (reference forward
    elsewhere); backward is the standard two-kernel Pallas flash backward
    (dK/dV pass + dQ pass, bf16 MXU matmuls with f32 accumulation), with a
    blockwise XLA fallback off-TPU / for unaligned shapes.

Layout: [batch, num_heads, seq, head_dim] (BHSD).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-only module; import guarded so CPU test envs can load this file.
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _pick_block(seq: int, pref: int) -> int:
    """Largest 128-multiple block <= pref that divides seq (seq % 128 == 0
    is guaranteed by the dispatch gate, so 128 always works)."""
    b = min(pref, seq)
    while b > 128 and seq % b != 0:
        b //= 2
    return b if seq % b == 0 else 128


# ---------------------------------------------------------------------------
# Reference implementation
# ---------------------------------------------------------------------------

def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        causal: bool = True,
                        sm_scale: Optional[float] = None,
                        q_offset: int = 0,
                        kv_offset: int = 0) -> jax.Array:
    """Plain softmax attention with fp32 accumulation.

    ``q_offset``/``kv_offset`` give the global positions of the local q/kv
    shards — needed by ring attention where each sp shard sees rotated K/V.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[2])[:, None]
        k_pos = kv_offset + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas TPU forward kernel
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *, sm_scale: float, causal: bool,
                      block_q: int, block_k: int, kv_seq_len: int):
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)

    @pl.when(kv_idx == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _body(masked: bool):
        q = q_ref[0]  # [block_q, d]
        k = k_ref[0]  # [block_k, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if masked:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        m_prev = m_ref[:]
        l_prev = l_ref[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new
        l_ref[:] = l_new

    if causal:
        # Three block classes: fully masked (skip entirely), fully visible
        # (no mask arithmetic — the bulk below the diagonal), diagonal
        # (per-element mask).
        visible = kv_idx * block_k <= q_idx * block_q + (block_q - 1)
        full = kv_idx * block_k + (block_k - 1) <= q_idx * block_q
        pl.when(visible & jnp.logical_not(full))(
            functools.partial(_body, True))
        pl.when(full)(functools.partial(_body, False))
    else:
        _body(False)

    @pl.when(kv_idx == (kv_seq_len // block_k) - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_ref[:] + jnp.log(l))[:, 0]


def _flash_fwd_pallas(q, k, v, *, causal, sm_scale, block_q=1024,
                      block_k=1024):
    b, h, sq, d = q.shape
    skv = k.shape[2]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(skv, block_k)
    grid = (b * h, sq // block_q, skv // block_k)
    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, skv, d)
    vr = v.reshape(b * h, skv, d)
    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_seq_len=skv)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            # lse kept 3-D [bh, 1, sq]: TPU needs the trailing two block dims
            # tileable (1 == full middle dim, block_q % 128 == 0).
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qr, kr, vr)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq)


def _fwd_with_lse_reference(q, k, v, *, causal, sm_scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        q_pos = jnp.arange(q.shape[2])[:, None]
        k_pos = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", (p / l).astype(v.dtype), v)
    lse = (m + jnp.log(l))[..., 0]
    return out, lse


# ---------------------------------------------------------------------------
# Pallas TPU backward kernels (standard two-kernel flash backward:
# one pass producing dK/dV with q innermost, one producing dQ with kv
# innermost; all MXU matmuls in bf16 with f32 accumulation)
# ---------------------------------------------------------------------------

def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *,
                          sm_scale: float, causal: bool, block_q: int,
                          block_k: int, q_seq_len: int):
    kv_idx = pl.program_id(1)
    q_idx = pl.program_id(2)

    @pl.when(q_idx == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _body(masked: bool):
        q = q_ref[0]          # [bq, d]
        k = k_ref[0]          # [bk, d]
        v = v_ref[0]          # [bk, d]
        do = do_ref[0]        # [bq, d]
        lse = lse_ref[0, 0][:, None]     # [bq, 1]
        delta = delta_ref[0, 0][:, None]  # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        if masked:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)  # [bq, bk] f32
        pb = p.astype(v.dtype)
        # dv += p^T @ do   (contract over bq)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            pb, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp = do @ v^T    [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        # dk += ds^T @ q   (contract over bq)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        visible = q_idx * block_q + (block_q - 1) >= kv_idx * block_k
        full = q_idx * block_q >= kv_idx * block_k + (block_k - 1)
        pl.when(visible & jnp.logical_not(full))(
            functools.partial(_body, True))
        pl.when(full)(functools.partial(_body, False))
    else:
        _body(False)

    @pl.when(q_idx == (q_seq_len // block_q) - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc, *, sm_scale: float, causal: bool,
                         block_q: int, block_k: int, kv_seq_len: int):
    q_idx = pl.program_id(1)
    kv_idx = pl.program_id(2)

    @pl.when(kv_idx == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _body(masked: bool):
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if masked:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        # dq += ds @ k
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        visible = kv_idx * block_k <= q_idx * block_q + (block_q - 1)
        full = kv_idx * block_k + (block_k - 1) <= q_idx * block_q
        pl.when(visible & jnp.logical_not(full))(
            functools.partial(_body, True))
        pl.when(full)(functools.partial(_body, False))
    else:
        _body(False)

    @pl.when(kv_idx == (kv_seq_len // block_k) - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, dout, *, causal, sm_scale,
                      block_q=1024, block_k=512):
    b, h, sq, d = q.shape
    skv = k.shape[2]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(skv, block_k)
    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, skv, d)
    vr = v.reshape(b * h, skv, d)
    dor = dout.astype(q.dtype).reshape(b * h, sq, d)
    lse_r = lse.reshape(b * h, 1, sq)
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(b * h, 1, sq)

    dkv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          q_seq_len=sq),
        grid=(b * h, skv // block_k, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, ki, qi: (bh, 0, qi)),
            pl.BlockSpec((1, 1, block_q), lambda bh, ki, qi: (bh, 0, qi)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, skv, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, skv, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qr, kr, vr, dor, lse_r, delta)
    dk, dv = dkv

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          kv_seq_len=skv),
        grid=(b * h, sq // block_q, skv // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((b * h, sq, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qr, kr, vr, dor, lse_r, delta)[0]

    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, skv, d),
            dv.reshape(b, h, skv, d))


# ---------------------------------------------------------------------------
# custom_vjp wrapper with blockwise XLA backward
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_k_bwd: int = 512):
    out, _ = _flash_fwd(q, k, v, causal, sm_scale)
    return out


def _flash_fwd(q, k, v, causal, sm_scale):
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    if _on_tpu() and q.shape[2] % 128 == 0 and k.shape[2] % 128 == 0 \
            and q.shape[-1] % 128 == 0:
        return _flash_fwd_pallas(q, k, v, causal=causal, sm_scale=scale)
    return _fwd_with_lse_reference(q, k, v, causal=causal, sm_scale=scale)


def _flash_vjp_fwd(q, k, v, causal, sm_scale, block_k_bwd):
    out, lse = _flash_fwd(q, k, v, causal, sm_scale)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, sm_scale, block_k_bwd, res, dout):
    q, k, v, out, lse = res
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    if _on_tpu() and q.shape[2] % 128 == 0 and k.shape[2] % 128 == 0 \
            and q.shape[-1] % 128 == 0:
        return _flash_bwd_pallas(q, k, v, out, lse, dout, causal=causal,
                                 sm_scale=scale)
    skv = k.shape[2]
    block = min(block_k_bwd, skv)
    n_blocks = skv // block if skv % block == 0 else 1
    if skv % block != 0:
        block = skv
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)  # [b,h,sq]
    q_pos = jnp.arange(q.shape[2])[:, None]

    def kv_block(carry, idx):
        dq_acc = carry
        kb = jax.lax.dynamic_slice_in_dim(k, idx * block, block, axis=2)
        vb = jax.lax.dynamic_slice_in_dim(v, idx * block, block, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kb,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = idx * block + jnp.arange(block)[None, :]
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse[..., None])  # [b,h,q,block]
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, dout.astype(jnp.float32))
        dp = jnp.einsum("bhqd,bhkd->bhqk", dout.astype(jnp.float32),
                        vb.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds,
                                     kb.astype(jnp.float32))
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
        return dq_acc, (dk, dv)

    # (q * 0) rather than zeros: inherits q's varying-manual-axes type so the
    # scan carry is consistent when this runs inside a shard_map (e.g. pp).
    dq0 = (q * 0).astype(jnp.float32)
    dq, (dks, dvs) = jax.lax.scan(kv_block, dq0, jnp.arange(n_blocks))
    dk = jnp.moveaxis(dks, 0, 2).reshape(k.shape)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(v.shape)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """Expand KV heads for grouped-query attention: [b, kvh, s, d] -> [b, kvh*n_rep, s, d]."""
    if n_rep == 1:
        return x
    b, kvh, s, d = x.shape
    return jnp.broadcast_to(x[:, :, None], (b, kvh, n_rep, s, d)).reshape(
        b, kvh * n_rep, s, d)
