"""Ring attention: blockwise causal attention over a sequence-parallel mesh axis.

The reference has no sequence/context parallelism of its own (verified absent —
see SURVEY.md §5.7; it delegates to engines like vLLM). Here it is first-class:
sequences are sharded over the ``sp`` mesh axis; each device holds a Q/K/V
shard, K/V shards rotate around the ICI ring via ``lax.ppermute`` while an
online-softmax accumulator folds in one block per step (Ring Attention,
blockwise-parallel pattern from the public literature — see PAPERS.md).

Call **inside** shard_map with q, k, v already sharded on the sp axis:
shapes [batch_local, heads_local, seq_local, head_dim].

Differentiable: the scan + ppermute composition is transparent to jax.grad
(ppermute's transpose is the inverse rotation), so the backward pass is itself
a ring schedule.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import DEFAULT_MASK_VALUE


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str = "sp",
                   causal: bool = True,
                   sm_scale: Optional[float] = None) -> jax.Array:
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]
    q_pos = my_idx * s_local + jnp.arange(s_local)[:, None]  # global q positions
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def accumulate(block, i):
        k_cur, v_cur, acc, m, l = block
        kv_idx = (my_idx - i) % axis_size  # which global shard we hold at step i
        k_pos = kv_idx * s_local + jnp.arange(s_local)[None, :]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(q_pos >= k_pos, s, DEFAULT_MASK_VALUE)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        return acc_new, m_new, l_new

    def step(carry, i):
        # Rotate K/V one hop around the ring (rides ICI neighbours), then fold
        # in the received block. The local (step-0) block is folded in before
        # the scan, so exactly axis_size-1 hops are issued.
        k_cur, v_cur, acc, m, l = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        acc, m, l = accumulate((k_cur, v_cur, acc, m, l), i)
        return (k_cur, v_cur, acc, m, l), None

    # Accumulators derived from q (times zero) so they inherit q's full
    # varying-manual-axes type — works no matter which enclosing shard_map
    # axes (sp, pp, ...) are manual here.
    qf = q.astype(jnp.float32)
    acc0 = qf * 0
    m0 = qf[..., :1] * 0 - jnp.inf
    l0 = qf[..., :1] * 0
    acc0, m0, l0 = accumulate((k, v, acc0, m0, l0), 0)
    (_, _, acc, m, l), _ = jax.lax.scan(
        step, (k, v, acc0, m0, l0), jnp.arange(1, axis_size))
    l = jnp.maximum(l, 1e-30)
    return (acc / l).astype(q.dtype)
