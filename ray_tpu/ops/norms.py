"""Normalization and rotary-embedding ops (pure jnp — XLA fuses these into
adjacent matmuls on TPU; a Pallas version is only warranted if profiles show
fusion misses)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32)).astype(dtype)


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 10000.0):
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                                / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [max_seq, head_dim//2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               positions: jax.Array | None = None) -> jax.Array:
    """x: [b, h, s, d]; cos/sin: [max_seq, d//2]; positions: [s] global positions."""
    s = x.shape[2]
    if positions is None:
        positions = jnp.arange(s)
    c = cos[positions][None, None]  # [1,1,s,d//2]
    si = sin[positions][None, None]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * si, x1 * si + x2 * c], axis=-1)
    return out.astype(x.dtype)
