"""Environment API + a built-in CartPole.

Analogue of the reference's env layer (reference: rllib/env/ — gymnasium
Env wrapping; SingleAgentEnvRunner steps vectorized gym envs). The API is
gymnasium-shaped (reset/step with terminated/truncated) so user gym envs
drop in via a thunk; CartPole ships built-in so the stack tests without
the gymnasium dependency.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np


class Env:
    """Minimal gymnasium-compatible interface."""

    observation_size: int
    num_actions: int

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int
             ) -> Tuple[np.ndarray, float, bool, bool, Dict[str, Any]]:
        raise NotImplementedError


class CartPole(Env):
    """Classic cart-pole balancing (standard Barto-Sutton dynamics)."""

    observation_size = 4
    num_actions = 2

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE = 10.0
    DT = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    def __init__(self):
        self._rng = np.random.RandomState(0)
        self._state = np.zeros(4, np.float64)
        self._steps = 0

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._state = self._rng.uniform(-0.05, 0.05, 4)
        self._steps = 0
        return self._state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE if action == 1 else -self.FORCE
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        cos_t, sin_t = np.cos(theta), np.sin(theta)
        temp = (force + pole_ml * theta_dot ** 2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_LEN * (4.0 / 3.0
                                  - self.POLE_MASS * cos_t ** 2 / total_mass))
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass
        x += self.DT * x_dot
        x_dot += self.DT * x_acc
        theta += self.DT * theta_dot
        theta_dot += self.DT * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._steps += 1
        terminated = bool(abs(x) > self.X_LIMIT
                          or abs(theta) > self.THETA_LIMIT)
        truncated = self._steps >= self.MAX_STEPS
        return (self._state.astype(np.float32), 1.0, terminated, truncated,
                {})
