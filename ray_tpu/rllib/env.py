"""Environment API + a built-in CartPole.

Analogue of the reference's env layer (reference: rllib/env/ — gymnasium
Env wrapping; SingleAgentEnvRunner steps vectorized gym envs). The API is
gymnasium-shaped (reset/step with terminated/truncated) so user gym envs
drop in via a thunk; CartPole ships built-in so the stack tests without
the gymnasium dependency.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np


class Env:
    """Minimal gymnasium-compatible interface."""

    observation_size: int
    num_actions: int

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int
             ) -> Tuple[np.ndarray, float, bool, bool, Dict[str, Any]]:
        raise NotImplementedError


class CartPole(Env):
    """Classic cart-pole balancing (standard Barto-Sutton dynamics)."""

    observation_size = 4
    num_actions = 2

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE = 10.0
    DT = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    def __init__(self):
        self._rng = np.random.RandomState(0)
        self._state = np.zeros(4, np.float64)
        self._steps = 0

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._state = self._rng.uniform(-0.05, 0.05, 4)
        self._steps = 0
        return self._state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE if action == 1 else -self.FORCE
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        cos_t, sin_t = np.cos(theta), np.sin(theta)
        temp = (force + pole_ml * theta_dot ** 2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_LEN * (4.0 / 3.0
                                  - self.POLE_MASS * cos_t ** 2 / total_mass))
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass
        x += self.DT * x_dot
        x_dot += self.DT * x_acc
        theta += self.DT * theta_dot
        theta_dot += self.DT * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._steps += 1
        terminated = bool(abs(x) > self.X_LIMIT
                          or abs(theta) > self.THETA_LIMIT)
        truncated = self._steps >= self.MAX_STEPS
        return (self._state.astype(np.float32), 1.0, terminated, truncated,
                {})


class ContinuousEnv:
    """Continuous-action interface (reference: gymnasium Box spaces as
    consumed by rllib/algorithms/sac): actions are float vectors in
    [action_low, action_high]^action_size."""

    observation_size: int
    action_size: int
    action_low: float
    action_high: float

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: np.ndarray
             ) -> Tuple[np.ndarray, float, bool, bool, Dict[str, Any]]:
        raise NotImplementedError


class Pendulum(ContinuousEnv):
    """Classic underactuated pendulum swing-up (standard gym dynamics):
    obs [cos th, sin th, th_dot], torque in [-2, 2], reward
    -(th^2 + 0.1 th_dot^2 + 0.001 u^2), 200-step episodes (truncation
    only — the task never terminates)."""

    observation_size = 3
    action_size = 1
    action_low = -2.0
    action_high = 2.0

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0
    MAX_STEPS = 200

    def __init__(self):
        self._rng = np.random.RandomState(0)
        self._th = 0.0
        self._thdot = 0.0
        self._steps = 0

    def _obs(self) -> np.ndarray:
        return np.array([np.cos(self._th), np.sin(self._th), self._thdot],
                        np.float32)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._th = self._rng.uniform(-np.pi, np.pi)
        self._thdot = self._rng.uniform(-1.0, 1.0)
        self._steps = 0
        return self._obs()

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          -self.MAX_TORQUE, self.MAX_TORQUE))
        th_norm = ((self._th + np.pi) % (2 * np.pi)) - np.pi
        cost = th_norm ** 2 + 0.1 * self._thdot ** 2 + 0.001 * u ** 2
        thdot = self._thdot + (3 * self.G / (2 * self.L) * np.sin(self._th)
                               + 3.0 / (self.M * self.L ** 2) * u) * self.DT
        thdot = float(np.clip(thdot, -self.MAX_SPEED, self.MAX_SPEED))
        self._th = self._th + thdot * self.DT
        self._thdot = thdot
        self._steps += 1
        truncated = self._steps >= self.MAX_STEPS
        return self._obs(), -float(cost), False, truncated, {}


class MultiAgentEnv:
    """Multi-agent interface (reference: rllib/env/multi_agent_env.py):
    dict-keyed obs/action/reward per agent id; terminateds/truncateds
    carry the "__all__" episode-end key."""

    agent_ids: Tuple[str, ...]
    observation_sizes: Dict[str, int]
    num_actions_per_agent: Dict[str, int]

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, int]):
        """-> (obs, rewards, terminateds, truncateds, infos) dicts; the
        terminateds/truncateds dicts include "__all__"."""
        raise NotImplementedError


class CooperativeMatch(MultiAgentEnv):
    """Two-agent coordination game: both agents see a one-hot context
    and (as the second half of the obs) a one-hot of the OTHER agent's
    previous action. Reward each step: +1 to both when both actions
    match the context, +0.25 when exactly one does. Solvable only when
    both policies learn the mapping — the cooperative sanity task."""

    agent_ids = ("a0", "a1")
    N_CONTEXTS = 4
    EP_LEN = 16

    def __init__(self):
        n = self.N_CONTEXTS
        self.observation_sizes = {a: 2 * n for a in self.agent_ids}
        self.num_actions_per_agent = {a: n for a in self.agent_ids}
        self._rng = np.random.RandomState(0)
        self._ctx = 0
        self._steps = 0
        self._prev = {a: 0 for a in self.agent_ids}

    def _obs_for(self, me: str) -> np.ndarray:
        n = self.N_CONTEXTS
        other = [a for a in self.agent_ids if a != me][0]
        obs = np.zeros(2 * n, np.float32)
        obs[self._ctx] = 1.0
        obs[n + self._prev[other]] = 1.0
        return obs

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._ctx = int(self._rng.randint(self.N_CONTEXTS))
        self._steps = 0
        self._prev = {a: 0 for a in self.agent_ids}
        return {a: self._obs_for(a) for a in self.agent_ids}

    def step(self, actions: Dict[str, int]):
        hits = sum(int(actions[a] == self._ctx) for a in self.agent_ids)
        reward = 1.0 if hits == 2 else (0.25 if hits == 1 else 0.0)
        self._prev = dict(actions)
        self._ctx = int(self._rng.randint(self.N_CONTEXTS))
        self._steps += 1
        done = self._steps >= self.EP_LEN
        obs = {a: self._obs_for(a) for a in self.agent_ids}
        rewards = {a: reward for a in self.agent_ids}
        terms = {a: False for a in self.agent_ids}
        terms["__all__"] = False
        truncs = {a: done for a in self.agent_ids}
        truncs["__all__"] = done
        return obs, rewards, terms, truncs, {}
