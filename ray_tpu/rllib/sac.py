"""SAC — off-policy soft actor-critic for continuous control.

Analogue of the reference's SAC (reference: rllib/algorithms/sac/sac.py
training_step — env runners feed a replay buffer; the learner performs
twin-Q + squashed-Gaussian policy + temperature updates with polyak
target sync). Same always-in-flight rollout pipeline as DQN/IMPALA; the
jitted SAC update runs on the driver's default device.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.learner import SACLearner
from ray_tpu.rllib.replay import ReplayBuffer


@dataclass
class SACConfig:
    """Builder-style config (reference: SACConfig)."""

    env_maker: Optional[Callable[[], Any]] = None
    num_env_runners: int = 2
    rollout_fragment_length: int = 200
    buffer_capacity: int = 100_000
    train_batch_size: int = 128
    updates_per_iteration: int = 64
    fragments_per_iteration: int = 2
    learning_starts: int = 500
    gamma: float = 0.99
    tau: float = 0.005
    lr: float = 3e-4
    init_alpha: float = 0.1
    hidden: tuple = (64, 64)
    seed: int = 0

    def environment(self, env_maker: Callable[[], Any]) -> "SACConfig":
        self.env_maker = env_maker
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "SACConfig":
        self.num_env_runners = num_env_runners
        if rollout_fragment_length:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw) -> "SACConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown SAC option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "SAC":
        return SAC(self)


class SAC:
    """Collection -> replay -> twin-Q soft updates."""

    def __init__(self, config: SACConfig):
        assert config.env_maker is not None, "config.environment(...) first"
        self.config = config
        probe = config.env_maker()
        self._learner = SACLearner(
            probe.observation_size, probe.action_size,
            action_scale=(float(probe.action_high)
                          - float(probe.action_low)) / 2.0,
            action_shift=(float(probe.action_high)
                          + float(probe.action_low)) / 2.0,
            hidden=tuple(config.hidden), lr=config.lr,
            gamma=config.gamma, tau=config.tau,
            init_alpha=config.init_alpha, seed=config.seed)
        self._buffer = ReplayBuffer(config.buffer_capacity,
                                    seed=config.seed)
        maker_blob = cloudpickle.dumps(config.env_maker)
        runner_cls = ray_tpu.remote(EnvRunner)
        self._runners = [
            runner_cls.remote(maker_blob, seed=config.seed + 1000 * (i + 1))
            for i in range(config.num_env_runners)]
        weights = self._learner.get_weights()
        ray_tpu.get([r.set_weights.remote(weights)
                     for r in self._runners], timeout=300)
        self.total_env_steps = 0
        self.total_updates = 0
        self.iteration = 0
        self._recent_returns: List[float] = []
        self._inflight: Dict[Any, Any] = {
            r.sample_continuous.remote(config.rollout_fragment_length): r
            for r in self._runners}

    def _collect(self, n: int) -> int:
        steps = 0
        weights = self._learner.get_weights()
        for _ in range(n):
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=600)
            if not ready:
                raise TimeoutError("env runners produced no fragments")
            ref = ready[0]
            runner = self._inflight.pop(ref)
            frag = ray_tpu.get(ref)
            self._recent_returns.extend(
                frag.pop("episode_returns").tolist())
            n_rows = len(frag["obs"])
            steps += n_rows
            self.total_env_steps += n_rows
            self._buffer.add(frag)
            runner.set_weights.remote(weights)
            self._inflight[runner.sample_continuous.remote(
                self.config.rollout_fragment_length)] = runner
        return steps

    def train(self) -> Dict[str, Any]:
        t0 = time.monotonic()
        cfg = self.config
        env_steps = self._collect(cfg.fragments_per_iteration)
        losses: Dict[str, float] = {}
        updates = 0
        if len(self._buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iteration):
                batch = self._buffer.sample(cfg.train_batch_size)
                batch.pop("indices", None)
                losses = self._learner.update(batch)
                self.total_updates += 1
                updates += 1
        self.iteration += 1
        self._recent_returns = self._recent_returns[-100:]
        mean_return = (float(np.mean(self._recent_returns))
                       if self._recent_returns else 0.0)
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_return,
            "env_steps_this_iter": env_steps,
            "updates_this_iter": updates,
            "total_env_steps": self.total_env_steps,
            "buffer_size": len(self._buffer),
            "time_this_iter_s": time.monotonic() - t0,
            **losses,
        }

    def get_weights(self):
        return self._learner.get_weights()

    def stop(self) -> None:
        for r in self._runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    def as_trainable(self, num_iterations: int) -> Callable[[dict], None]:
        """Adapter for ray_tpu.tune (reference: Algorithm as Trainable)."""
        config = self.config

        def trainable(overrides: dict):
            import dataclasses

            from ray_tpu import tune
            cfg = dataclasses.replace(config, **overrides)
            algo = SAC(cfg)
            try:
                for _ in range(num_iterations):
                    tune.report(algo.train())
            finally:
                algo.stop()

        return trainable
