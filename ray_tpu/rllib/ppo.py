"""PPO Algorithm + AlgorithmConfig — the training driver.

Analogue of the reference's algorithm layer (reference:
rllib/algorithms/algorithm.py Algorithm:207 + algorithm_config.py builder,
ppo/ppo.py training_step:388: sync weights -> parallel rollouts via the
EnvRunnerGroup -> learner update). The learner's jitted update runs on the
driver's default device (TPU when present); env runners are CPU actors.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.learner import PPOLearner
from ray_tpu.utils import get_logger

logger = get_logger("rllib")


@dataclass
class PPOConfig:
    """Builder-style config (reference: AlgorithmConfig)."""

    env_maker: Optional[Callable[[], Any]] = None
    num_env_runners: int = 2
    rollout_fragment_length: int = 512
    gamma: float = 0.99
    gae_lambda: float = 0.95
    lr: float = 3e-4
    clip_param: float = 0.2
    num_epochs: int = 4
    minibatch_size: int = 128
    entropy_coeff: float = 0.01
    vf_loss_coeff: float = 0.5
    hidden: tuple = (64, 64)
    seed: int = 0

    def environment(self, env_maker: Callable[[], Any]) -> "PPOConfig":
        self.env_maker = env_maker
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "PPOConfig":
        self.num_env_runners = num_env_runners
        if rollout_fragment_length:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw) -> "PPOConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown PPO option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "PPO":
        return PPO(self)


class PPO:
    """The algorithm: owns the learner + env-runner actor group."""

    def __init__(self, config: PPOConfig):
        assert config.env_maker is not None, "config.environment(...) first"
        self.config = config
        probe = config.env_maker()
        self._learner = PPOLearner(
            probe.observation_size, probe.num_actions,
            hidden=tuple(config.hidden), lr=config.lr,
            clip=config.clip_param, vf_coeff=config.vf_loss_coeff,
            entropy_coeff=config.entropy_coeff, seed=config.seed)
        maker_blob = cloudpickle.dumps(config.env_maker)
        runner_cls = ray_tpu.remote(EnvRunner)
        self._runners = [
            runner_cls.remote(maker_blob, seed=config.seed + 1000 * (i + 1))
            for i in range(config.num_env_runners)]
        self.iteration = 0
        self._recent_returns: List[float] = []

    def train(self) -> Dict[str, Any]:
        """One training iteration (reference: ppo.py training_step)."""
        t0 = time.monotonic()
        cfg = self.config
        weights = self._learner.get_weights()
        ray_tpu.get([r.set_weights.remote(weights)
                     for r in self._runners], timeout=300)
        batches = ray_tpu.get([
            r.sample.remote(cfg.rollout_fragment_length, cfg.gamma,
                            cfg.gae_lambda)
            for r in self._runners], timeout=600)
        episode_returns = np.concatenate(
            [b.pop("episode_returns") for b in batches])
        batch = {k: np.concatenate([b[k] for b in batches])
                 for k in batches[0]}
        losses = self._learner.update_minibatches(
            batch, num_epochs=cfg.num_epochs,
            minibatch_size=cfg.minibatch_size)
        self.iteration += 1
        self._recent_returns.extend(episode_returns.tolist())
        self._recent_returns = self._recent_returns[-100:]
        mean_return = (float(np.mean(self._recent_returns))
                       if self._recent_returns else 0.0)
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_return,
            "episodes_this_iter": int(len(episode_returns)),
            "env_steps_this_iter": int(len(batch["obs"])),
            "time_this_iter_s": time.monotonic() - t0,
            **losses,
        }

    def get_weights(self):
        return self._learner.get_weights()

    def stop(self) -> None:
        for r in self._runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    def as_trainable(self, num_iterations: int) -> Callable[[dict], None]:
        """Adapter: run this algorithm under ray_tpu.tune (reference:
        Algorithm subclasses Tune's Trainable)."""
        config = self.config

        def trainable(overrides: dict):
            import dataclasses

            from ray_tpu import tune
            cfg = dataclasses.replace(config, **overrides)
            algo = PPO(cfg)
            try:
                for _ in range(num_iterations):
                    tune.report(algo.train())
            finally:
                algo.stop()

        return trainable
