"""DQN — off-policy Q-learning with prioritized replay.

Analogue of the reference's DQN (reference: rllib/algorithms/dqn/dqn.py
training_step — env runners feed a (prioritized) replay buffer, the
learner samples batches, TD errors write back as priorities, the target
net syncs on a cadence). Redesign for this runtime: the same always-in-
flight rollout pipeline as IMPALA (the in-flight refs ARE the sample
queue), with epsilon-greedy collection annealed by total env steps.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.learner import DQNLearner
from ray_tpu.rllib.replay import PrioritizedReplayBuffer, ReplayBuffer


@dataclass
class DQNConfig:
    """Builder-style config (reference: DQNConfig)."""

    env_maker: Optional[Callable[[], Any]] = None
    num_env_runners: int = 2
    rollout_fragment_length: int = 64
    buffer_capacity: int = 50_000
    prioritized_replay: bool = True
    replay_alpha: float = 0.6
    replay_beta: float = 0.4
    train_batch_size: int = 64
    updates_per_iteration: int = 32
    fragments_per_iteration: int = 4
    learning_starts: int = 500         # env steps before the first update
    target_update_freq: int = 100      # updates between target syncs
    gamma: float = 0.99
    lr: float = 1e-3
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_anneal_steps: int = 4_000
    hidden: tuple = (64, 64)
    seed: int = 0

    def environment(self, env_maker: Callable[[], Any]) -> "DQNConfig":
        self.env_maker = env_maker
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "DQNConfig":
        self.num_env_runners = num_env_runners
        if rollout_fragment_length:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw) -> "DQNConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown DQN option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "DQN":
        return DQN(self)


class DQN:
    """The algorithm: epsilon-greedy collection -> replay -> double-DQN
    updates with priority write-back."""

    def __init__(self, config: DQNConfig):
        assert config.env_maker is not None, "config.environment(...) first"
        self.config = config
        probe = config.env_maker()
        self._learner = DQNLearner(
            probe.observation_size, probe.num_actions,
            hidden=tuple(config.hidden), lr=config.lr,
            gamma=config.gamma, seed=config.seed)
        if config.prioritized_replay:
            self._buffer: ReplayBuffer = PrioritizedReplayBuffer(
                config.buffer_capacity, alpha=config.replay_alpha,
                beta=config.replay_beta, seed=config.seed)
        else:
            self._buffer = ReplayBuffer(config.buffer_capacity,
                                        seed=config.seed)
        maker_blob = cloudpickle.dumps(config.env_maker)
        runner_cls = ray_tpu.remote(EnvRunner)
        self._runners = [
            runner_cls.remote(maker_blob, seed=config.seed + 1000 * (i + 1))
            for i in range(config.num_env_runners)]
        weights = self._learner.get_weights()
        ray_tpu.get([r.set_weights.remote(weights)
                     for r in self._runners], timeout=300)
        self.total_env_steps = 0
        self.total_updates = 0
        self.iteration = 0
        self._recent_returns: List[float] = []
        # Arm the pipeline: one fragment perpetually in flight per runner.
        self._inflight: Dict[Any, Any] = {
            r.sample_transitions.remote(config.rollout_fragment_length,
                                        self._epsilon()): r
            for r in self._runners}

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.total_env_steps
                   / max(1, cfg.epsilon_anneal_steps))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def _collect(self, n: int) -> int:
        """Consume n first-finished fragments into the replay buffer;
        re-arm each producer with fresh weights + the annealed epsilon."""
        steps = 0
        weights = self._learner.get_weights()
        for _ in range(n):
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=600)
            if not ready:
                raise TimeoutError("env runners produced no fragments")
            ref = ready[0]
            runner = self._inflight.pop(ref)
            frag = ray_tpu.get(ref)
            self._recent_returns.extend(
                frag.pop("episode_returns").tolist())
            n_rows = len(frag["obs"])
            steps += n_rows
            self.total_env_steps += n_rows
            self._buffer.add(frag)
            runner.set_weights.remote(weights)
            self._inflight[runner.sample_transitions.remote(
                self.config.rollout_fragment_length,
                self._epsilon())] = runner
        return steps

    def train(self) -> Dict[str, Any]:
        """One iteration = collect fragments_per_iteration rollouts +
        updates_per_iteration replay updates (after learning_starts)."""
        t0 = time.monotonic()
        cfg = self.config
        env_steps = self._collect(cfg.fragments_per_iteration)
        losses: Dict[str, float] = {}
        updates = 0
        if len(self._buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iteration):
                batch = self._buffer.sample(cfg.train_batch_size)
                indices = batch.get("indices")
                losses, td_abs = self._learner.update(batch)
                if indices is not None and isinstance(
                        self._buffer, PrioritizedReplayBuffer):
                    self._buffer.update_priorities(indices, td_abs)
                self.total_updates += 1
                updates += 1
                if self.total_updates % cfg.target_update_freq == 0:
                    self._learner.sync_target()
        self.iteration += 1
        self._recent_returns = self._recent_returns[-100:]
        mean_return = (float(np.mean(self._recent_returns))
                       if self._recent_returns else 0.0)
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_return,
            "env_steps_this_iter": env_steps,
            "updates_this_iter": updates,
            "total_env_steps": self.total_env_steps,
            "epsilon": self._epsilon(),
            "buffer_size": len(self._buffer),
            "time_this_iter_s": time.monotonic() - t0,
            **losses,
        }

    def get_weights(self):
        return self._learner.get_weights()

    def stop(self) -> None:
        for r in self._runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    def as_trainable(self, num_iterations: int) -> Callable[[dict], None]:
        """Adapter for ray_tpu.tune (reference: Algorithm as Trainable)."""
        config = self.config

        def trainable(overrides: dict):
            import dataclasses

            from ray_tpu import tune
            cfg = dataclasses.replace(config, **overrides)
            algo = DQN(cfg)
            try:
                for _ in range(num_iterations):
                    tune.report(algo.train())
            finally:
                algo.stop()

        return trainable
