"""PPO learner — pure-JAX policy/value nets + clipped surrogate update.

Analogue of the reference's learner stack (reference: rllib/core/learner/
learner.py + algorithms/ppo/ppo_torch_learner.py loss; RLModule forward),
TPU-first: one jitted update over the whole rollout batch (minibatch loop
as a lax.scan-free python loop over jitted steps — batch sizes are static),
bf16-friendly MLPs on the default device (TPU when present).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import numpy as np


def _mlp_init(key, sizes):
    import jax
    import jax.numpy as jnp

    params = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        w = jax.random.normal(sub, (fan_in, fan_out)) * np.sqrt(
            2.0 / fan_in)
        params.append({"w": w.astype(jnp.float32),
                       "b": jnp.zeros(fan_out, jnp.float32)})
    return params


def _mlp_apply(params, x):
    import jax.numpy as jnp

    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


class PPOLearner:
    """Holds policy+value params and performs PPO updates."""

    def __init__(self, obs_size: int, num_actions: int, *,
                 hidden: Tuple[int, ...] = (64, 64), lr: float = 3e-4,
                 clip: float = 0.2, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.01, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        key = jax.random.PRNGKey(seed)
        k1, k2 = jax.random.split(key)
        self.params = {
            "pi": _mlp_init(k1, (obs_size, *hidden, num_actions)),
            "vf": _mlp_init(k2, (obs_size, *hidden, 1)),
        }
        self._opt = optax.adam(lr)
        self._opt_state = self._opt.init(self.params)

        def loss_fn(params, batch):
            logits = _mlp_apply(params["pi"], batch["obs"])
            values = _mlp_apply(params["vf"], batch["obs"])[:, 0]
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1)[:, 0]
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["advantages"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            unclipped = ratio * adv
            clipped = jnp.clip(ratio, 1 - clip, 1 + clip) * adv
            pi_loss = -jnp.mean(jnp.minimum(unclipped, clipped))
            vf_loss = jnp.mean((values - batch["returns"]) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        @jax.jit
        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            return params, opt_state, aux

        self._update = update

    def get_weights(self) -> Any:
        import jax
        return jax.tree.map(np.asarray, self.params)

    def update_minibatches(self, batch: Dict[str, np.ndarray], *,
                           num_epochs: int = 4,
                           minibatch_size: int = 128) -> Dict[str, float]:
        import jax.numpy as jnp

        n = len(batch["obs"])
        # Static minibatch shapes: truncate to a multiple (XLA recompiles
        # per shape otherwise).
        assert num_epochs >= 1
        num_mb = max(1, n // minibatch_size)
        usable = num_mb * minibatch_size
        rng = np.random.RandomState(0)
        for _ in range(num_epochs):
            perm = rng.permutation(n)[:usable]
            for i in range(num_mb):
                idx = perm[i * minibatch_size:(i + 1) * minibatch_size]
                mb = {k: jnp.asarray(v[idx]) for k, v in batch.items()}
                self.params, self._opt_state, aux = self._update(
                    self.params, self._opt_state, mb)
        return {k: float(v) for k, v in aux.items()}


class IMPALALearner:
    """V-trace actor-critic updates on [B, T] trajectory fragments
    (reference: rllib/algorithms/impala/impala.py:599 training_step +
    vtrace torch/tf implementations; Espeholt et al. 2018). Off-policy
    correction lets rollouts be a few updates stale — the async pipeline
    never waits for the learner."""

    def __init__(self, obs_size: int, num_actions: int, *,
                 hidden: Tuple[int, ...] = (64, 64), lr: float = 5e-4,
                 gamma: float = 0.99, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.01,
                 rho_bar: float = 1.0, c_bar: float = 1.0, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        key = jax.random.PRNGKey(seed)
        k1, k2 = jax.random.split(key)
        self.params = {
            "pi": _mlp_init(k1, (obs_size, *hidden, num_actions)),
            "vf": _mlp_init(k2, (obs_size, *hidden, 1)),
        }
        self._opt = optax.adam(lr)
        self._opt_state = self._opt.init(self.params)

        def loss_fn(params, batch):
            # batch leaves: obs [B,T,D], actions [B,T], rewards [B,T],
            # terms/truncs [B,T], trunc_obs [B,T,D],
            # behavior_logp [B,T], bootstrap_obs [B,D]
            logits = _mlp_apply(params["pi"], batch["obs"])     # [B,T,A]
            values = _mlp_apply(params["vf"], batch["obs"])[..., 0]
            v_boot = _mlp_apply(params["vf"],
                                batch["bootstrap_obs"])[..., 0]  # [B]
            v_trunc = _mlp_apply(params["vf"],
                                 batch["trunc_obs"])[..., 0]    # [B,T]
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][..., None], axis=-1)[..., 0]
            rho = jnp.exp(logp - batch["behavior_logp"])
            rho_clip = jnp.minimum(rho_bar, rho)
            c_clip = jnp.minimum(c_bar, rho)
            terms, truncs = batch["terms"], batch["truncs"]
            # Termination zeroes the bootstrap; truncation bootstraps
            # from the final pre-reset obs. BOTH cut the backward carry
            # (the recursion must not cross episode boundaries).
            discounts = gamma * (1.0 - terms)                   # [B,T]
            boundary = jnp.maximum(terms, truncs)
            v_next = jnp.concatenate(
                [values[:, 1:], v_boot[:, None]], axis=1)       # [B,T]
            v_next = jnp.where(truncs > 0, v_trunc, v_next)
            deltas = rho_clip * (batch["rewards"]
                                 + discounts * v_next - values)

            # vs_t - V_t recursion, scanned backwards over T.
            def back(carry, xs):
                delta_t, carry_disc_t, c_t = xs
                acc = delta_t + carry_disc_t * c_t * carry
                return acc, acc

            carry_disc = discounts * (1.0 - boundary)
            xs = (deltas.T, carry_disc.T, c_clip.T)             # [T,B]
            _, acc = jax.lax.scan(back, jnp.zeros(values.shape[0]),
                                  xs, reverse=True)
            vs = acc.T + values                                 # [B,T]
            vs_next = jnp.concatenate(
                [vs[:, 1:], v_boot[:, None]], axis=1)
            vs_next = jnp.where(truncs > 0, v_trunc, vs_next)
            pg_adv = rho_clip * (batch["rewards"]
                                 + discounts * vs_next - values)
            pi_loss = -jnp.mean(logp * jax.lax.stop_gradient(pg_adv))
            vf_loss = 0.5 * jnp.mean(
                (values - jax.lax.stop_gradient(vs)) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            return params, opt_state, aux

        self._update = update

    def get_weights(self) -> Any:
        import jax
        return jax.tree.map(np.asarray, self.params)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One V-trace update on a stacked [B, T] fragment batch."""
        import jax.numpy as jnp

        dev = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self._opt_state, aux = self._update(
            self.params, self._opt_state, dev)
        return {k: float(v) for k, v in aux.items()}


class DQNLearner:
    """Double-DQN with a target network and per-sample TD errors for
    prioritized replay (reference: rllib/algorithms/dqn/
    dqn_rainbow_torch_learner.py loss — double-Q action selection from
    the ONLINE net, evaluation from the TARGET net; Huber TD loss
    weighted by importance-sampling weights)."""

    def __init__(self, obs_size: int, num_actions: int, *,
                 hidden: Tuple[int, ...] = (64, 64), lr: float = 1e-3,
                 gamma: float = 0.99, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        key = jax.random.PRNGKey(seed)
        self.params = {"q": _mlp_init(key, (obs_size, *hidden,
                                            num_actions))}
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self._opt = optax.adam(lr)
        self._opt_state = self._opt.init(self.params)

        def loss_fn(params, target_params, batch):
            q = _mlp_apply(params["q"], batch["obs"])
            q_sa = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=1)[:, 0]
            # Double DQN: the ONLINE net picks the argmax action, the
            # TARGET net evaluates it.
            q_next_online = _mlp_apply(params["q"], batch["next_obs"])
            best = jnp.argmax(q_next_online, axis=-1)
            q_next_target = _mlp_apply(target_params["q"],
                                       batch["next_obs"])
            q_next = jnp.take_along_axis(q_next_target, best[:, None],
                                         axis=1)[:, 0]
            target = batch["rewards"] + gamma * (1.0 - batch["dones"]) \
                * q_next
            td = q_sa - jax.lax.stop_gradient(target)
            # Huber: quadratic near 0, linear past 1 (stable with the
            # occasional large TD error).
            abs_td = jnp.abs(td)
            huber = jnp.where(abs_td <= 1.0, 0.5 * td ** 2,
                              abs_td - 0.5)
            weights = batch.get("weights", jnp.ones_like(huber))
            loss = jnp.mean(weights * huber)
            return loss, {"td_abs": abs_td, "q_mean": jnp.mean(q_sa)}

        # NO donation: target_params aliases params right after a sync
        # (both point at the same buffers) and XLA rejects donating a
        # buffer that another argument still uses.
        @jax.jit
        def update(params, opt_state, target_params, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["loss"] = loss
            return params, opt_state, aux

        self._update = update

    def get_weights(self) -> Any:
        import jax
        return jax.tree.map(np.asarray, self.params)

    def sync_target(self) -> None:
        import jax
        self.target_params = jax.tree.map(lambda x: x, self.params)

    def update(self, batch: Dict[str, np.ndarray]
               ) -> Tuple[Dict[str, float], np.ndarray]:
        """One update; returns (metrics, per-sample |TD| for priority
        writes)."""
        import jax.numpy as jnp

        dev = {k: jnp.asarray(v) for k, v in batch.items()
               if k != "indices"}
        self.params, self._opt_state, aux = self._update(
            self.params, self._opt_state, self.target_params, dev)
        td_abs = np.asarray(aux.pop("td_abs"))
        return {k: float(v) for k, v in aux.items()}, td_abs


class SACLearner:
    """Soft Actor-Critic for continuous control (reference:
    rllib/algorithms/sac/sac.py + torch learner losses; Haarnoja et al.
    2018): squashed-Gaussian policy, twin Q critics with a polyak-
    averaged target pair, and automatic entropy-temperature tuning
    against target_entropy = -action_size. One jitted update performs
    critic + actor + alpha steps and the soft target sync."""

    def __init__(self, obs_size: int, action_size: int, *,
                 action_scale: float = 1.0, action_shift: float = 0.0,
                 hidden: Tuple[int, ...] = (64, 64), lr: float = 3e-4,
                 gamma: float = 0.99, tau: float = 0.005,
                 init_alpha: float = 0.1, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        key = jax.random.PRNGKey(seed)
        kp, k1, k2 = jax.random.split(key, 3)
        self.params = {
            "pi": _mlp_init(kp, (obs_size, *hidden, 2 * action_size)),
            "q1": _mlp_init(k1, (obs_size + action_size, *hidden, 1)),
            "q2": _mlp_init(k2, (obs_size + action_size, *hidden, 1)),
            "log_alpha": jnp.asarray(float(np.log(init_alpha))),
        }
        self.target_params = {
            "q1": jax.tree.map(lambda x: x, self.params["q1"]),
            "q2": jax.tree.map(lambda x: x, self.params["q2"]),
        }
        # Affine squash: action = shift + scale * tanh(.), covering
        # asymmetric [low, high] boxes (scale=(high-low)/2,
        # shift=(high+low)/2).
        self.action_scale = float(action_scale)
        self.action_shift = float(action_shift)
        target_entropy = -float(action_size)
        self._opt = optax.adam(lr)
        self._opt_state = self._opt.init(self.params)
        LOG_STD_MIN, LOG_STD_MAX = -5.0, 2.0

        def pi_sample(pi_params, obs, key):
            out = _mlp_apply(pi_params, obs)
            mean, log_std = jnp.split(out, 2, axis=-1)
            log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
            std = jnp.exp(log_std)
            eps = jax.random.normal(key, mean.shape)
            pre = mean + std * eps
            act = jnp.tanh(pre)
            # log-prob with tanh change-of-variables (SAC appendix C).
            logp = (-0.5 * (eps ** 2 + 2 * log_std
                            + jnp.log(2 * jnp.pi))).sum(-1)
            logp -= jnp.log(1 - act ** 2 + 1e-6).sum(-1)
            return self.action_shift + act * self.action_scale, logp

        def q_apply(q_params, obs, act):
            return _mlp_apply(q_params,
                              jnp.concatenate([obs, act], -1))[..., 0]

        def losses(params, target, batch, key):
            ka, kb = jax.random.split(key)
            alpha = jnp.exp(params["log_alpha"])
            # ---- critic ----
            a_next, logp_next = pi_sample(params["pi"],
                                          batch["next_obs"], ka)
            q_next = jnp.minimum(
                q_apply(target["q1"], batch["next_obs"], a_next),
                q_apply(target["q2"], batch["next_obs"], a_next))
            backup = batch["rewards"] + gamma * (1 - batch["dones"]) * (
                q_next - jax.lax.stop_gradient(alpha) * logp_next)
            backup = jax.lax.stop_gradient(backup)
            q1 = q_apply(params["q1"], batch["obs"], batch["actions"])
            q2 = q_apply(params["q2"], batch["obs"], batch["actions"])
            critic_loss = jnp.mean((q1 - backup) ** 2
                                   + (q2 - backup) ** 2)
            # ---- actor ----
            a_new, logp_new = pi_sample(params["pi"], batch["obs"], kb)
            q_new = jnp.minimum(
                q_apply(jax.lax.stop_gradient(params["q1"]),
                        batch["obs"], a_new),
                q_apply(jax.lax.stop_gradient(params["q2"]),
                        batch["obs"], a_new))
            actor_loss = jnp.mean(
                jax.lax.stop_gradient(alpha) * logp_new - q_new)
            # ---- temperature ----
            alpha_loss = -jnp.mean(
                params["log_alpha"]
                * jax.lax.stop_gradient(logp_new + target_entropy))
            total = critic_loss + actor_loss + alpha_loss
            return total, {"critic_loss": critic_loss,
                           "actor_loss": actor_loss,
                           "alpha": alpha,
                           "entropy": -jnp.mean(logp_new)}

        @jax.jit
        def update(params, opt_state, target, batch, key):
            (loss, aux), grads = jax.value_and_grad(
                losses, has_aux=True)(params, target, batch, key)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target = jax.tree.map(lambda t, p: (1 - tau) * t + tau * p,
                                  target, {"q1": params["q1"],
                                           "q2": params["q2"]})
            aux["loss"] = loss
            return params, opt_state, target, aux

        self._update_fn = update
        self._key = jax.random.PRNGKey(seed + 17)

    def get_weights(self) -> Any:
        import jax
        return jax.tree.map(np.asarray,
                            {"pi": self.params["pi"],
                             "action_scale": self.action_scale,
                             "action_shift": self.action_shift})

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        self._key, sub = jax.random.split(self._key)
        dev = {k: jnp.asarray(v) for k, v in batch.items()
               if k != "indices"}
        self.params, self._opt_state, self.target_params, aux = \
            self._update_fn(self.params, self._opt_state,
                            self.target_params, dev, sub)
        return {k: float(v) for k, v in aux.items()}
