"""ray_tpu.rllib — reinforcement learning on the actor runtime.

Analogue of RLlib's core loop (reference: rllib/ — Algorithm/
AlgorithmConfig, EnvRunnerGroup of rollout actors, Learner with the PPO
clipped-surrogate loss), minimum slice: PPO with parallel env-runner
actors and a jitted JAX learner.

    from ray_tpu.rllib import PPOConfig
    from ray_tpu.rllib.env import CartPole

    algo = (PPOConfig().environment(CartPole)
            .env_runners(4, rollout_fragment_length=512).build())
    for _ in range(20):
        print(algo.train()["episode_return_mean"])
"""

from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.env import (CartPole, ContinuousEnv, CooperativeMatch,
                               Env, MultiAgentEnv, Pendulum)
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.learner import (DQNLearner, IMPALALearner, PPOLearner,
                                   SACLearner)
from ray_tpu.rllib.multi_agent import (MultiAgentEnvRunner, MultiAgentPPO,
                                       MultiAgentPPOConfig)
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.replay import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rllib.sac import SAC, SACConfig

__all__ = ["CartPole", "ContinuousEnv", "CooperativeMatch", "DQN",
           "DQNConfig", "DQNLearner", "Env", "IMPALA", "IMPALAConfig",
           "IMPALALearner", "MultiAgentEnv", "MultiAgentEnvRunner",
           "MultiAgentPPO", "MultiAgentPPOConfig", "PPO", "PPOConfig",
           "PPOLearner", "Pendulum", "PrioritizedReplayBuffer",
           "ReplayBuffer", "SAC", "SACConfig", "SACLearner"]
