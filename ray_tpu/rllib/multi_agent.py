"""Multi-agent training: env runner mapping agents to policies + a
per-policy PPO trainer.

Analogue of the reference's multi-agent stack (reference:
rllib/env/multi_agent_env_runner.py — one env, many agents, a
policy_mapping_fn routing each agent to a module; multi_agent_episode
bookkeeping; algorithms train one RLModule per policy id). TPU-first
shape: each runner steps ALL agents simultaneously, slices the stream
into per-policy PPO batches (GAE computed per agent stream), and the
driver updates one PPOLearner per policy.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import _log_softmax, _np_forward
from ray_tpu.rllib.learner import PPOLearner


class MultiAgentEnvRunner:
    """Steps one MultiAgentEnv with per-policy weights; emits per-policy
    PPO batches (obs/actions/logp_old/advantages/returns)."""

    def __init__(self, env_maker_blob: bytes, mapping_blob: bytes,
                 seed: int = 0):
        self._env = cloudpickle.loads(env_maker_blob)()
        self._map: Callable[[str], str] = cloudpickle.loads(mapping_blob)
        self._rng = np.random.RandomState(seed)
        self._weights: Dict[str, Any] = {}   # policy_id -> params
        self._obs = self._env.reset(seed=seed)
        self._episode_return = 0.0
        self._completed: List[float] = []

    def set_weights(self, weights: Dict[str, Any]) -> bool:
        self._weights = weights
        return True

    def _act(self, agent: str, obs: np.ndarray) -> tuple:
        w = self._weights[self._map(agent)]
        logp = _log_softmax(_np_forward(w["pi"], obs[None, :]))[0]
        action = int(self._rng.choice(len(logp), p=np.exp(logp)))
        return action, float(logp[action])

    def sample(self, num_steps: int, gamma: float = 0.99,
               gae_lambda: float = 0.95) -> Dict[str, Dict[str, Any]]:
        """num_steps ENV steps -> {policy_id: ppo_batch}. Every agent
        stream contributes to its policy's batch; episode boundaries
        ("__all__") cut the GAE recursion."""
        env = self._env
        agents = list(env.agent_ids)
        traj = {a: {"obs": [], "actions": [], "logp": [], "rewards": [],
                    "dones": []} for a in agents}
        obs = self._obs
        for _ in range(num_steps):
            acts, logps = {}, {}
            for a in agents:
                acts[a], logps[a] = self._act(a, obs[a])
            nxt, rews, terms, truncs, _ = env.step(acts)
            done = bool(terms.get("__all__") or truncs.get("__all__"))
            for a in agents:
                t = traj[a]
                t["obs"].append(obs[a])
                t["actions"].append(acts[a])
                t["logp"].append(logps[a])
                t["rewards"].append(rews.get(a, 0.0))
                t["dones"].append(float(done))
            self._episode_return += float(np.mean(
                [rews.get(a, 0.0) for a in agents]))
            if done:
                self._completed.append(self._episode_return)
                self._episode_return = 0.0
                obs = env.reset(seed=int(self._rng.randint(0, 2 ** 31)))
            else:
                obs = nxt
        self._obs = obs

        out: Dict[str, Dict[str, Any]] = {}
        for a in agents:
            pid = self._map(a)
            t = traj[a]
            obs_a = np.asarray(t["obs"], np.float32)
            rew_a = np.asarray(t["rewards"], np.float32)
            done_a = np.asarray(t["dones"], np.float32)
            w = self._weights[pid]
            values = _np_forward(w["vf"], obs_a)[:, 0]
            v_boot = float(_np_forward(
                w["vf"], obs[a][None, :].astype(np.float32))[0, 0])
            adv = np.zeros(num_steps, np.float32)
            last = 0.0
            for i in reversed(range(num_steps)):
                if done_a[i] > 0:  # episode cut (cooperative envs end
                    v_next, carry = 0.0, 0.0   # together via __all__)
                else:
                    v_next = v_boot if i == num_steps - 1 \
                        else float(values[i + 1])
                    carry = 1.0
                delta = rew_a[i] + gamma * v_next - values[i]
                last = delta + gamma * gae_lambda * carry * last
                adv[i] = last
            batch = {
                "obs": obs_a,
                "actions": np.asarray(t["actions"], np.int32),
                "logp_old": np.asarray(t["logp"], np.float32),
                "advantages": adv,
                "returns": (adv + values).astype(np.float32),
            }
            agg = out.setdefault(pid, {k: [] for k in batch})
            for k, v in batch.items():
                agg[k].append(v)
        result = {pid: {k: np.concatenate(v) for k, v in agg.items()}
                  for pid, agg in out.items()}
        result["__episode_returns__"] = np.asarray(
            self._completed, np.float32)
        self._completed = []
        return result


@dataclass
class MultiAgentPPOConfig:
    """reference: AlgorithmConfig.multi_agent(policies=...,
    policy_mapping_fn=...)."""

    env_maker: Optional[Callable[[], Any]] = None
    policy_mapping_fn: Callable[[str], str] = lambda agent_id: agent_id
    policies: Optional[List[str]] = None  # None: one policy per agent
    num_env_runners: int = 2
    rollout_fragment_length: int = 256
    gamma: float = 0.99
    gae_lambda: float = 0.95
    lr: float = 3e-4
    clip_param: float = 0.2
    num_epochs: int = 4
    minibatch_size: int = 128
    entropy_coeff: float = 0.01
    vf_loss_coeff: float = 0.5
    hidden: tuple = (64, 64)
    seed: int = 0

    def environment(self, env_maker) -> "MultiAgentPPOConfig":
        self.env_maker = env_maker
        return self

    def multi_agent(self, *, policies: Optional[List[str]] = None,
                    policy_mapping_fn: Optional[Callable] = None
                    ) -> "MultiAgentPPOConfig":
        if policies is not None:
            self.policies = list(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "MultiAgentPPOConfig":
        self.num_env_runners = num_env_runners
        if rollout_fragment_length:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw) -> "MultiAgentPPOConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO:
    """One PPOLearner per policy id; shared rollouts."""

    def __init__(self, config: MultiAgentPPOConfig):
        assert config.env_maker is not None
        self.config = config
        probe = config.env_maker()
        mapping = config.policy_mapping_fn
        policies = config.policies or sorted(
            {mapping(a) for a in probe.agent_ids})
        # Per-policy obs/action sizes from any agent mapped to it.
        sizes: Dict[str, tuple] = {}
        for a in probe.agent_ids:
            pid = mapping(a)
            size = (probe.observation_sizes[a],
                    probe.num_actions_per_agent[a])
            if pid in sizes and sizes[pid] != size:
                raise ValueError(
                    f"policy {pid!r} maps agents with different spaces")
            sizes[pid] = size
        self._learners: Dict[str, PPOLearner] = {
            pid: PPOLearner(*sizes[pid], hidden=tuple(config.hidden),
                            lr=config.lr, clip=config.clip_param,
                            vf_coeff=config.vf_loss_coeff,
                            entropy_coeff=config.entropy_coeff,
                            seed=config.seed + i)
            for i, pid in enumerate(policies)}
        maker_blob = cloudpickle.dumps(config.env_maker)
        map_blob = cloudpickle.dumps(mapping)
        runner_cls = ray_tpu.remote(MultiAgentEnvRunner)
        self._runners = [
            runner_cls.remote(maker_blob, map_blob,
                              seed=config.seed + 1000 * (i + 1))
            for i in range(config.num_env_runners)]
        self.iteration = 0
        self._recent_returns: List[float] = []

    def train(self) -> Dict[str, Any]:
        t0 = time.monotonic()
        cfg = self.config
        weights = {pid: ln.get_weights()
                   for pid, ln in self._learners.items()}
        ray_tpu.get([r.set_weights.remote(weights)
                     for r in self._runners], timeout=300)
        results = ray_tpu.get([
            r.sample.remote(cfg.rollout_fragment_length, cfg.gamma,
                            cfg.gae_lambda)
            for r in self._runners], timeout=600)
        for res in results:
            self._recent_returns.extend(
                res.pop("__episode_returns__").tolist())
        losses: Dict[str, float] = {}
        env_steps = 0
        for pid, learner in self._learners.items():
            per = [res[pid] for res in results if pid in res]
            if not per:
                continue
            batch = {k: np.concatenate([p[k] for p in per])
                     for k in per[0]}
            env_steps += len(batch["obs"])
            out = learner.update_minibatches(
                batch, num_epochs=cfg.num_epochs,
                minibatch_size=cfg.minibatch_size)
            losses.update({f"{pid}/{k}": v for k, v in out.items()})
        self.iteration += 1
        self._recent_returns = self._recent_returns[-100:]
        mean_return = (float(np.mean(self._recent_returns))
                       if self._recent_returns else 0.0)
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_return,
            "env_steps_this_iter": env_steps,
            "time_this_iter_s": time.monotonic() - t0,
            **losses,
        }

    def get_weights(self) -> Dict[str, Any]:
        return {pid: ln.get_weights()
                for pid, ln in self._learners.items()}

    def stop(self) -> None:
        for r in self._runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
