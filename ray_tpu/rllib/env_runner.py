"""EnvRunner — rollout-collecting actor.

Analogue of the reference's env runners (reference: rllib/env/
single_agent_env_runner.py — step envs with the current policy, return
sample batches; env_runner_group.py fans N of them out as actors). The
policy forward runs on the runner's host devices (numpy MLP mirror of the
learner net — env stepping is host work; shipping obs to the TPU per step
would be all latency).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import cloudpickle
import numpy as np


def _np_forward(layers: List[dict], x: np.ndarray) -> np.ndarray:
    for i, layer in enumerate(layers):
        x = x @ layer["w"] + layer["b"]
        if i < len(layers) - 1:
            x = np.tanh(x)
    return x


def _log_softmax(z: np.ndarray) -> np.ndarray:
    z = z - z.max(axis=-1, keepdims=True)
    return z - np.log(np.exp(z).sum(axis=-1, keepdims=True))


class EnvRunner:
    def __init__(self, env_maker_blob: bytes, seed: int = 0):
        self._env = cloudpickle.loads(env_maker_blob)()
        self._rng = np.random.RandomState(seed)
        self._seed = seed
        self._weights: Dict[str, Any] = {}
        self._obs = self._env.reset(seed=seed)
        self._episode_return = 0.0
        self._completed_returns: List[float] = []

    def set_weights(self, weights: Dict[str, Any]) -> bool:
        self._weights = weights
        return True

    def _policy_action(self, obs: np.ndarray) -> tuple:
        """Default behavior: sample from the softmax policy head."""
        logp = _log_softmax(_np_forward(self._weights["pi"],
                                        obs[None, :]))[0]
        action = int(self._rng.choice(len(logp), p=np.exp(logp)))
        return action, float(logp[action])

    def _rollout(self, num_steps: int,
                 select_action=None) -> Dict[str, np.ndarray]:
        """Shared stepping loop: behavior-policy transitions with explicit
        term/trunc flags, per-step next obs, and the final pre-reset obs
        at truncations — using the next episode's reset obs would leak
        value estimates across episode boundaries (GAE, V-trace, and TD
        targets all need this). `select_action(obs) -> (action, logp)`
        swaps the behavior policy (epsilon-greedy Q for DQN)."""
        select_action = select_action or self._policy_action
        obs_buf = np.zeros((num_steps, self._env.observation_size),
                           np.float32)
        next_buf = np.zeros((num_steps, self._env.observation_size),
                            np.float32)
        act_buf = np.zeros(num_steps, np.int32)
        rew_buf = np.zeros(num_steps, np.float32)
        term_buf = np.zeros(num_steps, np.float32)
        trunc_buf = np.zeros(num_steps, np.float32)
        logp_buf = np.zeros(num_steps, np.float32)
        trunc_obs = np.zeros((num_steps, self._env.observation_size),
                             np.float32)

        self._completed_returns = []
        obs = self._obs
        for t in range(num_steps):
            action, logp_a = select_action(obs)
            nxt, rew, term, trunc, _ = self._env.step(action)
            obs_buf[t] = obs
            next_buf[t] = nxt
            act_buf[t] = action
            rew_buf[t] = rew
            logp_buf[t] = logp_a
            term_buf[t] = float(term)
            trunc_buf[t] = float(trunc and not term)
            if trunc and not term:
                trunc_obs[t] = nxt
            self._episode_return += rew
            if term or trunc:
                self._completed_returns.append(self._episode_return)
                self._episode_return = 0.0
                obs = self._env.reset(
                    seed=int(self._rng.randint(0, 2 ** 31)))
            else:
                obs = nxt
        self._obs = obs
        return {
            "obs": obs_buf, "actions": act_buf, "rewards": rew_buf,
            "next_obs": next_buf,
            "terms": term_buf, "truncs": trunc_buf,
            "trunc_obs": trunc_obs, "behavior_logp": logp_buf,
            "bootstrap_obs": obs.astype(np.float32),
            "episode_returns": np.asarray(self._completed_returns,
                                          np.float32),
        }

    def sample(self, num_steps: int, gamma: float = 0.99,
               gae_lambda: float = 0.95) -> Dict[str, np.ndarray]:
        """Collect num_steps transitions; returns the PPO batch with GAE
        advantages computed runner-side (reference: ConnectorV2 GAE)."""
        roll = self._rollout(num_steps)
        vf = self._weights["vf"]
        values = _np_forward(vf, roll["obs"])[:, 0]
        v_boot = float(_np_forward(vf, roll["bootstrap_obs"][None, :])
                       [0, 0])
        # V only at actual truncation rows (usually none or a handful).
        trunc_vals = np.zeros(num_steps, np.float32)
        idx = np.nonzero(roll["truncs"] > 0)[0]
        if len(idx):
            trunc_vals[idx] = _np_forward(vf, roll["trunc_obs"][idx])[:, 0]

        # GAE(lambda) advantages + returns. The recursion resets across
        # episode boundaries (term OR trunc); truncation bootstraps from
        # V(final pre-reset obs).
        adv = np.zeros(num_steps, np.float32)
        last = 0.0
        for t in reversed(range(num_steps)):
            if roll["terms"][t] > 0:
                v_next, nonterminal, carry = 0.0, 0.0, 0.0
            elif roll["truncs"][t] > 0:
                v_next, nonterminal, carry = float(trunc_vals[t]), 1.0, 0.0
            else:
                v_next = v_boot if t == num_steps - 1 else \
                    float(values[t + 1])
                nonterminal, carry = 1.0, 1.0
            delta = roll["rewards"][t] + gamma * v_next * nonterminal \
                - values[t]
            last = delta + gamma * gae_lambda * carry * last
            adv[t] = last
        returns = adv + values
        return {
            "obs": roll["obs"], "actions": roll["actions"],
            "logp_old": roll["behavior_logp"],
            "advantages": adv, "returns": returns.astype(np.float32),
            "episode_returns": roll["episode_returns"],
        }

    def sample_fragment(self, num_steps: int) -> Dict[str, np.ndarray]:
        """IMPALA-style trajectory fragment: raw transitions + behavior
        log-probs, NO advantage computation (the learner applies V-trace
        off-policy correction; reference:
        rllib/algorithms/impala/impala.py async sample batches)."""
        roll = self._rollout(num_steps)
        roll.pop("next_obs", None)  # V-trace never reads per-step next
        return roll

    def sample_transitions(self, num_steps: int,
                           epsilon: float) -> Dict[str, np.ndarray]:
        """Off-policy transition collection with epsilon-greedy Q actions
        (reference: DQN env runners + EpsilonGreedy exploration).
        Truncations count as NON-terminal (the TD target bootstraps
        through them); `next_obs` at a boundary is the final pre-reset
        obs (the shared _rollout loop guarantees this)."""
        q = self._weights["q"]

        def select(obs):
            if self._rng.random_sample() < epsilon:
                return int(self._rng.randint(self._env.num_actions)), 0.0
            return int(np.argmax(_np_forward(q, obs[None, :])[0])), 0.0

        roll = self._rollout(num_steps, select)
        return {
            "obs": roll["obs"], "actions": roll["actions"],
            "rewards": roll["rewards"], "next_obs": roll["next_obs"],
            "dones": roll["terms"],
            "episode_returns": roll["episode_returns"],
        }

    def sample_continuous(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Off-policy continuous-action collection with the squashed-
        Gaussian behavior policy (SAC; reference: rllib/algorithms/sac
        env-runner sampling). Own stepping loop — the shared _rollout
        stores int actions. Truncations bootstrap (non-terminal dones);
        `next_obs` at a boundary is the final pre-reset obs."""
        pi = self._weights["pi"]
        scale = float(self._weights.get("action_scale", 1.0))
        shift = float(self._weights.get("action_shift", 0.0))
        env = self._env
        asize = env.action_size
        obs_buf = np.zeros((num_steps, env.observation_size), np.float32)
        next_buf = np.zeros_like(obs_buf)
        act_buf = np.zeros((num_steps, asize), np.float32)
        rew_buf = np.zeros(num_steps, np.float32)
        done_buf = np.zeros(num_steps, np.float32)

        self._completed_returns = []
        obs = self._obs
        for t in range(num_steps):
            out = _np_forward(pi, obs[None, :])[0]
            mean, log_std = out[:asize], np.clip(out[asize:], -5.0, 2.0)
            action = shift + np.tanh(
                mean + np.exp(log_std)
                * self._rng.standard_normal(asize)) * scale
            nxt, rew, term, trunc, _ = env.step(action.astype(np.float32))
            obs_buf[t] = obs
            next_buf[t] = nxt
            act_buf[t] = action
            rew_buf[t] = rew
            done_buf[t] = float(term)  # truncation bootstraps
            self._episode_return += rew
            if term or trunc:
                self._completed_returns.append(self._episode_return)
                self._episode_return = 0.0
                obs = env.reset(seed=int(self._rng.randint(0, 2 ** 31)))
            else:
                obs = nxt
        self._obs = obs
        return {
            "obs": obs_buf, "actions": act_buf, "rewards": rew_buf,
            "next_obs": next_buf, "dones": done_buf,
            "episode_returns": np.asarray(self._completed_returns,
                                          np.float32),
        }
