"""Replay buffers for off-policy algorithms.

Analogue of the reference's replay stack (reference:
rllib/utils/replay_buffers/replay_buffer.py ReplayBuffer +
prioritized_episode_buffer.py). Columnar numpy storage: batches of
transitions append into preallocated rings, sampling gathers by index —
the TPU-friendly shape (static dtypes, contiguous slices for
device_put).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform FIFO-ring transition buffer."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = int(capacity)
        self._cols: Dict[str, np.ndarray] = {}
        self._size = 0
        self._head = 0
        self._rng = np.random.RandomState(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        """Append a columnar batch of transitions (first axis = time)."""
        n = len(next(iter(batch.values())))
        if self._cols and set(batch) != set(self._cols):
            # A key-set mismatch would silently pair columns from
            # different transitions at the same index.
            raise ValueError(
                f"replay batch keys {sorted(batch)} != buffer keys "
                f"{sorted(self._cols)}")
        for k, v in batch.items():
            v = np.asarray(v)
            col = self._cols.get(k)
            if col is None:
                col = self._cols[k] = np.zeros(
                    (self.capacity, *v.shape[1:]), v.dtype)
            if len(v) != n:
                raise ValueError("ragged replay batch")
        if n >= self.capacity:  # keep only the newest capacity rows
            for k, v in batch.items():
                self._cols[k][:] = np.asarray(v)[-self.capacity:]
            self._head = 0
            self._size = self.capacity
            return
        end = self._head + n
        for k, v in batch.items():
            v = np.asarray(v)
            if end <= self.capacity:
                self._cols[k][self._head:end] = v
            else:  # wrap
                first = self.capacity - self._head
                self._cols[k][self._head:] = v[:first]
                self._cols[k][:end - self.capacity] = v[first:]
        self._head = end % self.capacity
        self._size = min(self.capacity, self._size + n)

    def sample(self, num: int) -> Dict[str, np.ndarray]:
        """Uniform sample with replacement."""
        if self._size == 0:
            raise ValueError("sampling from an empty replay buffer")
        idx = self._rng.randint(0, self._size, size=num)
        return {k: col[idx] for k, col in self._cols.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference:
    prioritized_replay_buffer.py; Schaul et al. 2016) with importance
    weights and post-update priority writes."""

    def __init__(self, capacity: int, *, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed)
        self._alpha = alpha
        self._beta = beta
        self._prio = np.zeros(capacity, np.float64)
        self._max_prio = 1.0

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        start = self._head
        super().add(batch)
        # New transitions get max priority so they are seen at least once.
        idx = (start + np.arange(min(n, self.capacity))) % self.capacity
        self._prio[idx] = self._max_prio

    def sample(self, num: int) -> Dict[str, np.ndarray]:
        if self._size == 0:
            raise ValueError("sampling from an empty replay buffer")
        p = self._prio[:self._size] ** self._alpha
        p = p / p.sum()
        idx = self._rng.choice(self._size, size=num, p=p)
        weights = (self._size * p[idx]) ** (-self._beta)
        weights = weights / weights.max()
        out = {k: col[idx] for k, col in self._cols.items()}
        out["weights"] = weights.astype(np.float32)
        out["indices"] = idx.astype(np.int64)
        return out

    def update_priorities(self, indices: np.ndarray,
                          priorities: np.ndarray) -> None:
        priorities = np.abs(np.asarray(priorities, np.float64)) + 1e-6
        self._prio[indices] = priorities
        self._max_prio = max(self._max_prio, float(priorities.max()))
