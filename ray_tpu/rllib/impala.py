"""IMPALA — asynchronous actor-critic with V-trace correction.

Analogue of the reference's IMPALA (reference:
rllib/algorithms/impala/impala.py:599 training_step — async sample
queue, learner thread, V-trace). Redesign for this runtime: every env
runner always has a sample_fragment call IN FLIGHT; the learner waits
for whichever finishes first, stacks fragments into a [B, T] batch, and
V-trace corrects the staleness. A runner is re-armed with the CURRENT
weights the moment its fragment is consumed — rollout collection never
blocks on the learner and vice versa (the in-flight refs are the queue).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import cloudpickle
import numpy as np

import ray_tpu
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.learner import IMPALALearner


@dataclass
class IMPALAConfig:
    """Builder-style config (reference: IMPALAConfig)."""

    env_maker: Optional[Callable[[], Any]] = None
    num_env_runners: int = 2
    rollout_fragment_length: int = 64
    train_batch_fragments: int = 4     # fragments stacked per update
    updates_per_iteration: int = 8
    gamma: float = 0.99
    lr: float = 5e-4
    entropy_coeff: float = 0.01
    vf_loss_coeff: float = 0.5
    vtrace_rho_bar: float = 1.0
    vtrace_c_bar: float = 1.0
    hidden: tuple = (64, 64)
    seed: int = 0

    def environment(self, env_maker: Callable[[], Any]) -> "IMPALAConfig":
        self.env_maker = env_maker
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "IMPALAConfig":
        self.num_env_runners = num_env_runners
        if rollout_fragment_length:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw) -> "IMPALAConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown IMPALA option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "IMPALA":
        return IMPALA(self)


class IMPALA:
    """The algorithm: async rollout pipeline + V-trace learner."""

    def __init__(self, config: IMPALAConfig):
        assert config.env_maker is not None, "config.environment(...) first"
        self.config = config
        probe = config.env_maker()
        self._learner = IMPALALearner(
            probe.observation_size, probe.num_actions,
            hidden=tuple(config.hidden), lr=config.lr,
            gamma=config.gamma, vf_coeff=config.vf_loss_coeff,
            entropy_coeff=config.entropy_coeff,
            rho_bar=config.vtrace_rho_bar, c_bar=config.vtrace_c_bar,
            seed=config.seed)
        maker_blob = cloudpickle.dumps(config.env_maker)
        runner_cls = ray_tpu.remote(EnvRunner)
        self._runners = [
            runner_cls.remote(maker_blob, seed=config.seed + 1000 * (i + 1))
            for i in range(config.num_env_runners)]
        weights = self._learner.get_weights()
        ray_tpu.get([r.set_weights.remote(weights)
                     for r in self._runners], timeout=300)
        # Arm the pipeline: one fragment perpetually in flight per runner.
        self._inflight: Dict[Any, Any] = {
            r.sample_fragment.remote(config.rollout_fragment_length): r
            for r in self._runners}
        self.iteration = 0
        self._recent_returns: List[float] = []

    def _next_fragments(self, n: int) -> List[Dict[str, np.ndarray]]:
        """Consume the n first-finished fragments; re-arm each producer
        with the freshest weights immediately."""
        out = []
        weights = self._learner.get_weights()  # one D2H copy per batch
        while len(out) < n:
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=600)
            if not ready:
                raise TimeoutError("env runners produced no fragments")
            ref = ready[0]
            runner = self._inflight.pop(ref)
            out.append(ray_tpu.get(ref))
            runner.set_weights.remote(weights)
            self._inflight[runner.sample_fragment.remote(
                self.config.rollout_fragment_length)] = runner
        return out

    def train(self) -> Dict[str, Any]:
        """One iteration = updates_per_iteration V-trace updates."""
        t0 = time.monotonic()
        cfg = self.config
        env_steps = 0
        episodes = 0
        losses: Dict[str, float] = {}
        for _ in range(cfg.updates_per_iteration):
            frags = self._next_fragments(cfg.train_batch_fragments)
            for f in frags:
                finished = f.pop("episode_returns").tolist()
                self._recent_returns.extend(finished)
                episodes += len(finished)
            batch = {k: np.stack([f[k] for f in frags])
                     for k in frags[0]}
            env_steps += batch["obs"].shape[0] * batch["obs"].shape[1]
            losses = self._learner.update(batch)
        self.iteration += 1
        self._recent_returns = self._recent_returns[-100:]
        mean_return = (float(np.mean(self._recent_returns))
                       if self._recent_returns else 0.0)
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_return,
            "episodes_this_iter": episodes,
            "env_steps_this_iter": env_steps,
            "time_this_iter_s": time.monotonic() - t0,
            **losses,
        }

    def get_weights(self):
        return self._learner.get_weights()

    def stop(self) -> None:
        for r in self._runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    def as_trainable(self, num_iterations: int) -> Callable[[dict], None]:
        """Adapter for ray_tpu.tune (reference: Algorithm as Trainable)."""
        config = self.config

        def trainable(overrides: dict):
            import dataclasses

            from ray_tpu import tune
            cfg = dataclasses.replace(config, **overrides)
            algo = IMPALA(cfg)
            try:
                for _ in range(num_iterations):
                    tune.report(algo.train())
            finally:
                algo.stop()

        return trainable
