"""State API: programmatic cluster introspection.

Analogue of the reference's state API (reference: python/ray/util/state/
api.py list_nodes/list_actors/list_tasks + dashboard/state_aggregator.py;
`ray list ...` CLI). Sources: controller tables + per-agent stats RPCs.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from ray_tpu import api as _api


def _ctl(method: str, *args, timeout: float = 30.0):
    cw = _api._cw()
    return cw._run(cw.controller.call(method, *args)).result(timeout)


def list_nodes() -> List[dict]:
    out = []
    for n in _ctl("get_nodes"):
        out.append({
            "node_id": n["node_id"].hex()[:12],
            "state": n["state"],
            "addr": f"{n['addr'][0]}:{n['addr'][1]}",
            "resources_total": n["resources_total"],
            "resources_available": n["resources_available"],
            "labels": n["labels"],
        })
    return out


def list_actors() -> List[dict]:
    return [{
        "actor_id": a["actor_id"].hex()[:12],
        "name": a["name"],
        "state": a["state"],
        "node_id": a["node_id"].hex()[:12] if a["node_id"] else "",
        "restarts": a["restarts"],
    } for a in _ctl("list_actors")]


def list_tasks(state: Optional[str] = None, node: Optional[str] = None,
               name: Optional[str] = None, actor: Optional[str] = None,
               limit: int = 100) -> List[dict]:
    """grafttrail task records (one row per task, newest first), filtered
    by FSM state (SUBMITTED/LEASED/RUNNING/FINISHED/FAILED/CANCELLED),
    home node (hex12), function name, or actor id — index intersections
    on the controller, not scans (reference: `ray list tasks`)."""
    return _ctl("trail_tasks", state, node, name, actor, limit)


def list_task_events(limit: int = 1000) -> List[dict]:
    """The raw legacy event stream (submitted/finished/... rows) the
    timeline and event export are derived from."""
    return _ctl("list_task_events", limit)


def get_task(task_id: str) -> Optional[dict]:
    """One task's full trail: attempt chain (per-attempt state, node,
    worker, transition timestamps), root-cause error across retries,
    trace linkage. Accepts a unique task-id hex prefix."""
    return _ctl("trail_task", task_id)


def summary_tasks() -> List[dict]:
    """Per-function rollup: totals, attempts, and per-state counts
    (reference: `ray summary tasks`)."""
    return _ctl("trail_summary")


def list_objects(node: Optional[str] = None, plane: Optional[str] = None,
                 live: Optional[bool] = None,
                 limit: int = 100) -> List[dict]:
    """grafttrail object records with provenance: plane (shm/copy/
    fallback), home node, owner, created/sealed/freed timestamps and
    the freed reason (reference: `ray memory`)."""
    return _ctl("trail_objects", node, plane, live, limit)


def audit(grace_s: Optional[float] = None) -> dict:
    """Machine-checked conservation audit over the trail ledger: every
    non-terminal task live on an alive node, every sealed object freed
    or still resident where the ledger says. Returns {"ok", "lost_tasks",
    "leaked_objects", "complete", "stats"} with per-finding provenance."""
    return _ctl("trail_audit", grace_s)


def list_workers() -> List[dict]:
    """Per-node agent stats (workers, store, spill, event stats). A node
    whose agent can't be reached yields an {"node_id", "error"} row
    instead of silently vanishing from the listing."""
    cw = _api._cw()
    out = []
    for n in _ctl("get_nodes"):
        if n["state"] != "ALIVE":
            continue
        try:
            stats = cw._run(cw._client_for_worker(
                tuple(n["addr"])).call("agent_stats")).result(15)
            stats["node_id"] = stats["node_id"].hex()[:12]
            out.append(stats)
        except Exception as e:
            out.append({"node_id": n["node_id"].hex()[:12],
                        "error": repr(e)})
    return out


def cluster_summary() -> dict:
    res = _ctl("cluster_resources")
    nodes = list_nodes()
    actors = list_actors()
    return {
        "nodes_alive": sum(1 for n in nodes if n["state"] == "ALIVE"),
        "nodes_total": len(nodes),
        "resources_total": res["total"],
        "resources_available": res["available"],
        "actors": sum(1 for a in actors if a["state"] == "ALIVE"),
        "actors_total": len(actors),
    }


def metrics_text() -> str:
    return _ctl("metrics_text")


def cluster_telemetry(window: int = 30) -> dict:
    """The graftpulse cluster SLO view: per-op p50/p99 + throughput
    folded over every node's recent pulses, per-node occupancy and
    pulse health (alive/suspect/no-pulse), resident totals, and the
    controller's membership/actor counts. `window` bounds how many
    recent pulses per node feed the aggregates."""
    return _ctl("cluster_telemetry", window)


def meta_snapshot(window: int = 60) -> dict:
    """The graftmeta self-telemetry view: per-plane ingest records/s +
    bytes/s and fold-latency p50/p99 over the last `window` meta ticks,
    controller event-loop lag, controller RSS, and per-store occupancy
    (caps, evictions, dedup hits). {"enabled": False} when the meter is
    off (RAY_TPU_GRAFTMETA=0)."""
    return _ctl("meta_snapshot", window)


def report_soak(status: dict) -> None:
    """Push a running soak's status blob to the controller (graftload's
    1 Hz reporter). Shows up as `soak` in cluster_telemetry() / the
    dashboard /api/cluster view while fresh."""
    _ctl("report_soak", status)


def cluster_metrics_text() -> str:
    """Federated Prometheus exposition: every node's registry plus the
    pulse-derived raytpu_cluster_* aggregates (served at
    /metrics/cluster on the dashboard)."""
    return _ctl("cluster_metrics_text")


def native_latency() -> List[dict]:
    """Hot-path latency rollup over the graftscope native spans the
    controller retains: per span name (rpc.wire, sidecar.put, ...),
    count / mean µs / max µs."""
    return _ctl("native_latency")


def timeline(filename: Optional[str] = None,
             native: bool = True, fmt: str = "events") -> List[dict]:
    """Chrome-trace events for every recorded task — plus, with
    ``native`` (default), the graftscope native-plane spans (dispatch,
    wire, sidecar service, copy) nested under the submitting task. Pass
    filename to dump JSON loadable in chrome://tracing / Perfetto
    (reference: `ray timeline`). The dump is atomic (tmp + rename): a
    crash or concurrent reader never sees a torn file.

    fmt="chrome" writes the Chrome trace-event FORMAT object
    ({"traceEvents": [...]} with integer pid/tid plus process_name/
    thread_name metadata) instead of the raw event array — the shape
    Perfetto's UI ingests directly. The returned value is always the
    raw event list."""
    trace = _ctl("timeline", native)
    if filename:
        payload = to_chrome_trace(trace) if fmt == "chrome" else trace
        tmp = filename + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, filename)
    return trace


def to_chrome_trace(events: List[dict]) -> dict:
    """Convert the raw timeline event array to Chrome trace-event
    format: integer pid/tid (the controller emits string track names),
    "M" metadata events naming each process/thread, and the
    {"traceEvents": ...} envelope chrome://tracing and Perfetto expect.
    Pure function — unit-testable without a cluster."""
    pids: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}
    out: List[dict] = []
    meta: List[dict] = []
    for ev in events:
        pname, tname = str(ev.get("pid", "?")), str(ev.get("tid", "?"))
        if pname not in pids:
            pids[pname] = len(pids) + 1
            meta.append({"name": "process_name", "ph": "M",
                         "pid": pids[pname], "tid": 0,
                         "args": {"name": pname}})
        pid = pids[pname]
        tkey = (pname, tname)
        if tkey not in tids:
            tids[tkey] = len(tids) + 1
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": pid, "tid": tids[tkey],
                         "args": {"name": tname}})
        row = dict(ev)
        row["pid"] = pid
        row["tid"] = tids[tkey]
        out.append(row)
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def stack(node_id: Optional[str] = None,
          profile_s: float = 0.0) -> dict:
    """Python stack traces of every worker on every (or one) node — the
    hung-worker debugger (reference: `ray stack`, scripts.py:2706 via
    py-spy; here the worker's own stacks RPC with a SIGUSR1/faulthandler
    fallback for wedged event loops). Returns
    {node_id_hex: {pid: {stacks, via, worker_id, actor}}}.

    profile_s > 0 folds that many seconds of graftprof samples per
    worker instead of taking a single snapshot (`ray_tpu stack
    --profile N`) and attaches per-thread native CPU times (the
    sidecar threads included)."""
    from ray_tpu import api
    cw = api._cw()
    profile_s = min(max(0.0, float(profile_s or 0.0)), 30.0)
    out = {}
    for n in list_nodes():
        nid = n["node_id"]
        if node_id and not nid.startswith(node_id):
            continue
        if n.get("state") != "ALIVE":
            continue
        host, port = n["addr"].rsplit(":", 1)
        try:
            agent = cw._client_for_worker((host, int(port)))
            out[nid] = cw._run(agent.call(
                "dump_stacks", profile_s)).result(30 + profile_s)
        except Exception as e:
            out[nid] = {"error": repr(e)}
    return out


# ---------------------------------------------------------------------------
# graftprof (continuous profiling)
# ---------------------------------------------------------------------------

def prof_top(task: Optional[str] = None, actor: Optional[str] = None,
             node: Optional[str] = None, seconds: Optional[float] = None,
             limit: int = 30) -> dict:
    """Hottest frames from the always-on graftprof plane: per frame,
    self samples (leaf) and cumulative samples (anywhere on stack).
    Filters: task id prefix OR exact task name, actor id prefix, node
    hex12; `seconds` restricts to recent windows instead of the merged
    per-task folds (reference contrast: Ray attaches py-spy on demand;
    here profiles are already on the controller)."""
    return _ctl("prof_top", task, actor, node, seconds, limit)


def prof_flame(task: Optional[str] = None, actor: Optional[str] = None,
               node: Optional[str] = None,
               seconds: Optional[float] = None) -> dict:
    """d3-flamegraph nested JSON ({name, value, children}) for the
    selected profiles (same filters as prof_top)."""
    return _ctl("prof_flame", task, actor, node, seconds)


def prof_collapsed(task: Optional[str] = None,
                   actor: Optional[str] = None,
                   node: Optional[str] = None,
                   seconds: Optional[float] = None) -> List[str]:
    """Brendan-Gregg collapsed stacks ("a;b;c N" lines) — feed to any
    external flamegraph.pl-compatible tool."""
    return _ctl("prof_collapsed", task, actor, node, seconds)


def prof_task_stats(task_id: str) -> Optional[dict]:
    """One task's profile accounting: samples, on-CPU ns, GIL-wait ns
    (the `ray_tpu get task` join). Accepts a task-id hex prefix."""
    return _ctl("prof_task_stats", task_id)


def prof_stats() -> dict:
    """ProfStore occupancy: nodes, tracked tasks, total samples,
    drops reported by worker rings."""
    return _ctl("prof_stats")


def list_logs(task: Optional[str] = None, actor: Optional[str] = None,
              node: Optional[str] = None, level: int = 0,
              since_ns: int = 0, after_id: int = 0,
              limit: int = 100) -> List[dict]:
    """Cluster log records from the graftlog plane, time-ordered.
    Filters: task id hex prefix, actor id prefix, node hex12, minimum
    logging level (e.g. 30 for WARNING+), wall-clock floor (ns).
    ``after_id`` is the follow cursor: pass the last row's ``id`` to
    fetch only newer records (the `ray_tpu logs -f` loop). Salvaged
    rows (``salvaged: true``) are a dead worker's final lines,
    recovered from its crash-persistent ring."""
    return _ctl("list_logs", task, actor, node, level, since_ns,
                after_id, limit)


def log_stats() -> dict:
    """LogStore occupancy and storm-control counters: records, cap,
    ingested/suppressed/deduped/evicted/salvaged, per-level mix."""
    return _ctl("log_stats")
