"""Structured event export — lifecycle events to a JSONL sink.

Analogue of the reference's export-API pipeline (reference:
src/ray/observability/ray_event_recorder.cc structured lifecycle events +
dashboard/modules/aggregator/aggregator_agent.py shipping export_*.proto
events to external sinks). Slimmed to the durable core: every control-
plane event (node/actor/job/serve lifecycle via the pubsub hub, plus
task state transitions) appends as one JSON line to
``event_export_path`` — the integration seam log shippers tail.

Enable with RAY_TPU_EVENT_EXPORT_PATH=/path/events.jsonl (or the
event_export_path config flag).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Optional


class EventExporter:
    """Buffered JSONL appender (thread-safe; best-effort — an export
    failure must never take down the control plane)."""

    _FLUSH_EVERY = 64

    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()
        self._buf: list = []
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def emit(self, source: str, event: Any) -> None:
        rec = {"ts": time.time(), "source": source,
               "event": _jsonable(event)}
        with self._lock:
            self._buf.append(json.dumps(rec))
            if len(self._buf) >= self._FLUSH_EVERY:
                self._flush_locked()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buf:
            return
        lines, self._buf = self._buf, []
        try:
            with open(self._path, "a") as f:
                f.write("\n".join(lines) + "\n")
        except OSError:
            pass  # best-effort: never fail the control plane


def _jsonable(v: Any) -> Any:
    if isinstance(v, dict):
        return {str(_jsonable(k)): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)


def exporter_from_config() -> Optional[EventExporter]:
    from ray_tpu.utils.config import GlobalConfig
    path = GlobalConfig.event_export_path
    return EventExporter(path) if path else None
