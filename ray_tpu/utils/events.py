"""Structured event export — lifecycle events to a JSONL sink.

Analogue of the reference's export-API pipeline (reference:
src/ray/observability/ray_event_recorder.cc structured lifecycle events +
dashboard/modules/aggregator/aggregator_agent.py shipping export_*.proto
events to external sinks). Slimmed to the durable core: every control-
plane event (node/actor/job/serve lifecycle via the pubsub hub, plus
task state transitions) appends as one JSON line to
``event_export_path`` — the integration seam log shippers tail.

Every event carries both clocks: ``ts`` (wall, for humans and log
shippers) and ``mono_ns`` (CLOCK_MONOTONIC, the clock graftpulse ticks
and graftscope records use) so events and pulses merge onto one
timeline without wall-clock skew artifacts.

The buffer is bounded (``event_buffer_max``): when a sink stalls or the
path is unwritable, the oldest unflushed events are dropped rather than
growing without bound, and the drop count is exposed both as a module
total (``dropped_total`` — stamped into each node's pulse) and as the
``raytpu_events_dropped`` gauge.

Enable with RAY_TPU_EVENT_EXPORT_PATH=/path/events.jsonl (or the
event_export_path config flag).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Optional

# Events dropped across every exporter in this process (drop-oldest on
# buffer overflow + lines lost to sink write failures).
_dropped = 0
_dropped_lock = threading.Lock()
_dropped_gauge = None


def dropped_total() -> int:
    """Process-wide count of events lost to buffer bounds or sink
    failures (rides in the node pulse as ``events_dropped``)."""
    return _dropped


def _count_dropped(n: int) -> None:
    global _dropped, _dropped_gauge
    if n <= 0:
        return
    with _dropped_lock:
        _dropped += n
        try:
            if _dropped_gauge is None:
                from ray_tpu.utils import metrics as M
                _dropped_gauge = M.Gauge(
                    "raytpu_events_dropped",
                    "Lifecycle events lost to the bounded export buffer "
                    "or sink write failures.")
            _dropped_gauge.set(_dropped)
        except Exception:
            pass  # metrics are best-effort here too


class EventExporter:
    """Buffered JSONL appender (thread-safe; best-effort — an export
    failure must never take down the control plane, and a stalled sink
    must never grow the buffer without bound)."""

    _FLUSH_EVERY = 64

    def __init__(self, path: str, max_buffered: Optional[int] = None):
        self._path = path
        self._lock = threading.Lock()
        self._buf: list = []
        if max_buffered is None:
            try:
                from ray_tpu.utils.config import GlobalConfig
                max_buffered = int(GlobalConfig.event_buffer_max)
            except Exception:
                max_buffered = 4096
        self._max = max(1, max_buffered)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # Interpreter exit must not strand a partial batch in the buffer
        # (events below _FLUSH_EVERY would otherwise never hit the sink).
        import atexit
        atexit.register(self.flush)

    def emit(self, source: str, event: Any) -> None:
        rec = {"ts": time.time(), "mono_ns": time.monotonic_ns(),
               "source": source, "event": _jsonable(event)}
        overflow = 0
        with self._lock:
            self._buf.append(json.dumps(rec))
            if len(self._buf) > self._max:
                # Drop-oldest: the newest events are the ones a post-
                # mortem needs most.
                overflow = len(self._buf) - self._max
                del self._buf[:overflow]
            if len(self._buf) >= min(self._FLUSH_EVERY, self._max):
                self._flush_locked()
        if overflow:
            _count_dropped(overflow)

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buf:
            return
        lines, self._buf = self._buf, []
        try:
            with open(self._path, "a") as f:
                f.write("\n".join(lines) + "\n")
        except OSError:
            # best-effort: never fail the control plane — but do count
            # what the sink lost.
            _count_dropped(len(lines))


def _jsonable(v: Any) -> Any:
    if isinstance(v, dict):
        return {str(_jsonable(k)): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)


def exporter_from_config() -> Optional[EventExporter]:
    from ray_tpu.utils.config import GlobalConfig
    path = GlobalConfig.event_export_path
    return EventExporter(path) if path else None
