from ray_tpu.utils.config import GlobalConfig
from ray_tpu.utils.logging import get_logger

__all__ = ["GlobalConfig", "get_logger"]
