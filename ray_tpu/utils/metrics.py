"""Metrics: Counter/Gauge/Histogram with a process-local registry.

Analogue of the reference's metrics stack (reference: src/ray/stats/
metric.cc + python/ray/util/metrics.py user-defined metrics; export via
the per-node agent to Prometheus). Here: components record into the
process registry; node agents push snapshots to the controller every
``metrics_report_period_ms``; the controller aggregates and renders a
Prometheus-style text exposition for scraping/CLI.

Locking: the module lock guards only the registry map (create/list);
every metric carries its own lock for value updates, so two components
recording different metrics never contend — the reference's stats layer
makes the same split between metric registration and recording.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Tuple

_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}


class Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._values: Dict[Tuple, float] = {}
        self._mlock = threading.Lock()
        with _lock:
            _registry[name] = self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        tags = tags or {}
        return tuple(tags.get(k, "") for k in self.tag_keys)

    def snapshot(self) -> List[Tuple[Tuple, float]]:
        with self._mlock:
            return list(self._values.items())


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        k = self._key(tags)
        with self._mlock:
            self._values[k] = self._values.get(k, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._mlock:
            self._values[self._key(tags)] = float(value)


class Histogram(Metric):
    """Fixed-boundary histogram (counts per bucket + sum/count)."""

    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or
                                 [0.001, 0.01, 0.1, 1, 10, 100])
        self._buckets: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._counts: Dict[Tuple, int] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        k = self._key(tags)
        with self._mlock:
            b = self._buckets.setdefault(
                k, [0] * (len(self.boundaries) + 1))
            # Bucket = count of boundaries strictly below value, i.e. the
            # first bucket whose upper bound (inclusive) admits it.
            b[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1

    def snapshot(self):
        with self._mlock:
            return [(k, {"buckets": list(v),
                         "boundaries": list(self.boundaries),
                         "sum": self._sums.get(k, 0.0),
                         "count": self._counts.get(k, 0)})
                    for k, v in self._buckets.items()]


def snapshot_all() -> Dict[str, dict]:
    """Serializable registry snapshot (pushed to the controller)."""
    with _lock:
        metrics = list(_registry.values())
    return {m.name: {"kind": m.kind, "description": m.description,
                     "tag_keys": m.tag_keys, "values": m.snapshot()}
            for m in metrics}


def _escape_label(v) -> str:
    """Prometheus exposition label-value escaping: backslash, double
    quote and newline must be escaped or the line is unparseable."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v) -> str:
    """HELP text escaping (backslash and newline only, per the format)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def render_prometheus(per_node: Dict[str, Dict[str, dict]]) -> str:
    """{node_hex: snapshot_all()} -> Prometheus text exposition."""
    lines: List[str] = []
    seen_help = set()
    for node, snap in sorted(per_node.items()):
        for name, m in sorted(snap.items()):
            if name not in seen_help:
                lines.append(f"# HELP {name} {_escape_help(m['description'])}")
                lines.append(f"# TYPE {name} {m['kind']}")
                seen_help.add(name)
            for tags_tuple, value in m["values"]:
                tag_parts = [f'node="{_escape_label(node)}"'] + [
                    f'{k}="{_escape_label(v)}"'
                    for k, v in zip(m["tag_keys"], tags_tuple)]
                tag_str = "{" + ",".join(tag_parts) + "}"
                if m["kind"] == "histogram":
                    bounds = value.get("boundaries") or []
                    cum = 0
                    for bi, count in enumerate(value["buckets"]):
                        cum += count
                        le = (f"{bounds[bi]}" if bi < len(bounds)
                              else "+Inf")
                        btags = tag_str[:-1] + f',le="{le}"}}'
                        lines.append(f"{name}_bucket{btags} {cum}")
                    lines.append(
                        f"{name}_sum{tag_str} {value['sum']}")
                    lines.append(
                        f"{name}_count{tag_str} {value['count']}")
                else:
                    lines.append(f"{name}{tag_str} {value}")
    return "\n".join(lines) + "\n"
