"""Metrics: Counter/Gauge/Histogram with a process-local registry.

Analogue of the reference's metrics stack (reference: src/ray/stats/
metric.cc + python/ray/util/metrics.py user-defined metrics; export via
the per-node agent to Prometheus). Here: components record into the
process registry; node agents push snapshots to the controller every
``metrics_report_period_ms``; the controller aggregates and renders a
Prometheus-style text exposition for scraping/CLI.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}


class Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._values: Dict[Tuple, float] = {}
        with _lock:
            _registry[name] = self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        tags = tags or {}
        return tuple(tags.get(k, "") for k in self.tag_keys)

    def snapshot(self) -> List[Tuple[Tuple, float]]:
        with _lock:
            return list(self._values.items())


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        k = self._key(tags)
        with _lock:
            self._values[k] = self._values.get(k, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with _lock:
            self._values[self._key(tags)] = float(value)


class Histogram(Metric):
    """Fixed-boundary histogram (counts per bucket + sum/count)."""

    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or
                                 [0.001, 0.01, 0.1, 1, 10, 100])
        self._buckets: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._counts: Dict[Tuple, int] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        k = self._key(tags)
        with _lock:
            b = self._buckets.setdefault(
                k, [0] * (len(self.boundaries) + 1))
            i = 0
            while i < len(self.boundaries) and value > self.boundaries[i]:
                i += 1
            b[i] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1

    def snapshot(self):
        with _lock:
            return [(k, {"buckets": list(v),
                         "boundaries": list(self.boundaries),
                         "sum": self._sums.get(k, 0.0),
                         "count": self._counts.get(k, 0)})
                    for k, v in self._buckets.items()]


def snapshot_all() -> Dict[str, dict]:
    """Serializable registry snapshot (pushed to the controller)."""
    with _lock:
        metrics = list(_registry.values())
    return {m.name: {"kind": m.kind, "description": m.description,
                     "tag_keys": m.tag_keys, "values": m.snapshot()}
            for m in metrics}


def render_prometheus(per_node: Dict[str, Dict[str, dict]]) -> str:
    """{node_hex: snapshot_all()} -> Prometheus text exposition."""
    lines: List[str] = []
    seen_help = set()
    for node, snap in sorted(per_node.items()):
        for name, m in sorted(snap.items()):
            if name not in seen_help:
                lines.append(f"# HELP {name} {m['description']}")
                lines.append(f"# TYPE {name} {m['kind']}")
                seen_help.add(name)
            for tags_tuple, value in m["values"]:
                tag_parts = [f'node="{node}"'] + [
                    f'{k}="{v}"' for k, v in zip(m["tag_keys"],
                                                 tags_tuple)]
                tag_str = "{" + ",".join(tag_parts) + "}"
                if m["kind"] == "histogram":
                    bounds = value.get("boundaries") or []
                    cum = 0
                    for bi, count in enumerate(value["buckets"]):
                        cum += count
                        le = (f"{bounds[bi]}" if bi < len(bounds)
                              else "+Inf")
                        btags = tag_str[:-1] + f',le="{le}"}}'
                        lines.append(f"{name}_bucket{btags} {cum}")
                    lines.append(
                        f"{name}_sum{tag_str} {value['sum']}")
                    lines.append(
                        f"{name}_count{tag_str} {value['count']}")
                else:
                    lines.append(f"{name}{tag_str} {value}")
    return "\n".join(lines) + "\n"
