"""Asyncio helpers.

``spawn`` exists because asyncio event loops keep only WEAK references to
tasks: a fire-and-forget ``ensure_future(...)`` whose return value is
discarded can be garbage-collected mid-flight, which closes the coroutine by
throwing GeneratorExit into its current await — surfacing as phantom
"WorkerCrashedError: GeneratorExit()" failures under load. Every
fire-and-forget task in the runtime must go through ``spawn`` (the reference
runtime doesn't have this class of bug because its event loops are C++
boost::asio, where handlers are owned by the io_context).
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Set

_BACKGROUND: Set["asyncio.Task"] = set()


def spawn(coro: Awaitable) -> "asyncio.Task":
    """ensure_future with a strong reference until completion."""
    task = asyncio.ensure_future(coro)
    _BACKGROUND.add(task)
    task.add_done_callback(_discard)
    return task


def _discard(task: "asyncio.Task") -> None:
    _BACKGROUND.discard(task)
    if not task.cancelled():
        exc = task.exception()
        if exc is not None and not isinstance(exc, asyncio.CancelledError):
            import logging
            logging.getLogger("ray_tpu.aio").error(
                "background task %r failed: %r", task.get_coro(), exc)
