"""Structured per-process logging (analogue of reference src/ray/util logging +
python/ray/_private/ray_logging). Each process logs to stderr and, when a
session directory is configured, to ``<session>/logs/<component>-<pid>.log``
(size-capped and rotated — see ``log_file_max_bytes``/``log_file_backups``).

Every ``ray_tpu.*`` record is also routed into the graftlog plane with its
level preserved: the handler appends to this process's crash-persistent
ring (or its pending buffer before the ring opens), so logger output is
queryable cluster-wide and survives a SIGKILL for postmortem salvage.
"""

from __future__ import annotations

import logging
import logging.handlers
import os
import sys

_FORMAT = "%(asctime)s %(levelname).1s %(process)d %(name)s] %(message)s"
_configured = False
_graftlog_attached = False
_file_handlers: set[str] = set()


def _file_limits() -> tuple[int, int]:
    try:
        from ray_tpu.utils.config import GlobalConfig
        return (int(GlobalConfig.log_file_max_bytes),
                int(GlobalConfig.log_file_backups))
    except Exception:
        return 16 << 20, 3


def configure(component: str = "driver", session_dir: str | None = None,
              level: int = logging.INFO) -> logging.Logger:
    global _configured, _graftlog_attached
    root = logging.getLogger("ray_tpu")
    if not _configured:
        root.setLevel(level)
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(h)
        root.propagate = False
        _configured = True
    if not _graftlog_attached:
        try:
            from ray_tpu.core._native import graftlog
            if graftlog.enabled():
                root.addHandler(graftlog.GraftlogHandler())
            _graftlog_attached = True
        except Exception:
            pass
    if session_dir:
        log_dir = os.path.join(session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        path = os.path.join(log_dir, f"{component}-{os.getpid()}.log")
        if path not in _file_handlers:  # one handler per file, ever
            _file_handlers.add(path)
            max_bytes, backups = _file_limits()
            if max_bytes > 0:
                fh: logging.Handler = logging.handlers.RotatingFileHandler(
                    path, maxBytes=max_bytes, backupCount=backups)
            else:
                fh = logging.FileHandler(path)
            fh.setFormatter(logging.Formatter(_FORMAT))
            root.addHandler(fh)
    return root


def get_logger(name: str) -> logging.Logger:
    configure()
    return logging.getLogger(f"ray_tpu.{name}")
