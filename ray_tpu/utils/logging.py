"""Structured per-process logging (analogue of reference src/ray/util logging +
python/ray/_private/ray_logging). Each process logs to stderr and, when a
session directory is configured, to ``<session>/logs/<component>-<pid>.log``.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s %(levelname).1s %(process)d %(name)s] %(message)s"
_configured = False
_file_handlers: set[str] = set()


def configure(component: str = "driver", session_dir: str | None = None,
              level: int = logging.INFO) -> logging.Logger:
    global _configured
    root = logging.getLogger("ray_tpu")
    if not _configured:
        root.setLevel(level)
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(h)
        root.propagate = False
        _configured = True
    if session_dir:
        log_dir = os.path.join(session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        path = os.path.join(log_dir, f"{component}-{os.getpid()}.log")
        if path not in _file_handlers:  # one handler per file, ever
            _file_handlers.add(path)
            fh = logging.FileHandler(path)
            fh.setFormatter(logging.Formatter(_FORMAT))
            root.addHandler(fh)
    return root


def get_logger(name: str) -> logging.Logger:
    configure()
    return logging.getLogger(f"ray_tpu.{name}")
