"""Flag/config system for ray_tpu.

TPU-native analogue of the reference's X-macro ``RAY_CONFIG`` system
(reference: src/ray/common/ray_config_def.h — 223 flags, each overridable via a
``RAY_<name>`` env var) and the Python-side constants
(python/ray/_private/ray_constants.py).

Here a single declarative registry defines every flag with a type and default;
every flag is overridable via ``RAY_TPU_<NAME>`` environment variables, and a
serialized config dict can be passed down to spawned node processes (the
reference passes ``--config-list`` at process spawn; we pass a JSON blob).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict

_ENV_PREFIX = "RAY_TPU_"


def _parse_bool(v: str) -> bool:
    return v.lower() in ("1", "true", "yes", "on")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: int,
    float: float,
    str: str,
}


@dataclass
class _Flag:
    name: str
    type: type
    default: Any
    doc: str = ""


_REGISTRY: Dict[str, _Flag] = {}


def _flag(name: str, typ: type, default: Any, doc: str = "") -> None:
    _REGISTRY[name] = _Flag(name, typ, default, doc)


# ---------------------------------------------------------------------------
# Flag definitions (mirrors the spirit of ray_config_def.h; grouped by area).
# ---------------------------------------------------------------------------

# --- core timeouts / intervals ---
_flag("health_check_period_ms", int, 1000, "Controller->node health-check period.")
_flag("health_check_timeout_ms", int, 10000, "Mark node dead after this long without a heartbeat.")
_flag("resource_broadcast_period_ms", int, 100, "Node resource gossip period.")
_flag("handler_warning_timeout_ms", int, 1000, "Warn on event-loop handlers slower than this.")
_flag("worker_register_timeout_s", int, 30, "Worker must register with its node agent within this.")
_flag("task_retry_delay_ms", int, 100, "Delay before retrying a failed task.")

# --- object store ---
_flag("object_store_memory_bytes", int, 2 * 1024**3, "Default shm arena size per node.")
_flag("store_fastpath", bool, True, "Native store sidecar: workers do put/get over a C unix-socket path (no event loop); falls back to agent RPC when off or unavailable.")
_flag("data_memory_budget_bytes", int, 0, "Streaming Data executor byte budget for in-flight blocks; 0 = auto (object store / 4).")
_flag("container_run_template", str, '["podman", "run", "--rm", "--network=host", "-v", "{session_dir}:{session_dir}", "-v", "/dev/shm:/dev/shm", "{memory_flags}", "{env_flags}", "{image}", "python3", "-m", "ray_tpu.core.worker_main"]', "JSON argv template for image_uri runtime envs ({image}/{session_dir}/{env_flags}/{memory_flags} placeholders); swap for docker or a test stub.")
_flag("runtime_env_cache_bytes", int, 10 * 1024**3, "LRU size cap for cached runtime-env venvs per session; oldest unused evict first.")
_flag("object_store_min_spill_bytes", int, 100 * 1024**2, "Batch spills until this many bytes.")
_flag("max_direct_call_object_size", int, 100 * 1024, "Inline results smaller than this in-process.")
_flag("object_transfer_chunk_bytes", int, 5 * 1024**2, "Chunk size for node-to-node object transfer.")
_flag("max_concurrent_object_pulls", int, 4, "Active inbound object transfers per node; excess pulls queue by priority (reference: pull_manager.cc bandwidth-bounded active pulls).")
_flag("object_spill_dir", str, "", "Directory for spilled objects (default: session dir).")

# --- dispatch plane (graftrpc) ---
_flag("graftrpc", bool, True, "Native dispatch plane for the actor-call hot path: co-located workers exchange push_task_batch frames over the C reactor (csrc/rpc_core.cc) instead of the asyncio RpcServer; falls back to the asyncio path when off or the native library is unavailable.")

# --- copy plane (graftcopy) ---
_flag("graftcopy", bool, True, "Native put plane: fused sidecar OP_PUT (O_TMPFILE+linkat staging, oid-derived names) with large copies routed through the csrc/copy_core.cc scatter engine; falls back to the pwritev + OP_INGEST path when off or the native library is unavailable.")
_flag("graftcopy_threads", int, 0, "Copy-engine worker threads for scatter writes; 0 = auto (host cores - 1, so 1-core hosts run sequentially on the calling thread).")
_flag("graftcopy_min_bytes", int, 16 * 1024**2, "Route puts at least this large through the native scatter engine; smaller payloads use one os.pwritev (a pool handoff costs more than it saves).")
_flag("put_executor_offload_bytes", int, 4 * 1024**2, "Loop-path puts larger than this copy on the default executor instead of the event loop; the same knob caps the legacy (graftcopy-off) synchronous fast-put path.")
_flag("graftcopy_scratch_max_bytes", int, 2 * 1024**3, "Per-worker staging-inode recycling cap: the put plane keeps one private hardlink ('scratch-<pid>') to its last staging file so a delete drops only the store's name and the next put of at most this size rewrites the same hot tmpfs pages (cold page allocation halves write bandwidth); 0 disables recycling.")
_flag("graftcopy_deferred_ack", bool, True, "Deferred-ack small puts: sub-graftshm_min_bytes graftcopy puts send their OP_PUT and return without reading the reply (the sidecar processes in order, so the object is visible to every later op); the ack rides the next client op and a failed adoption is repaired through the spill-capable agent path. Off = every put blocks on its reply.")

# --- shared-memory object plane (graftshm) ---
_flag("graftshm", bool, True, "Store-owned shared-memory put plane: OP_CREATE hands the worker a slab fd over SCM_RIGHTS, SerializedValue serializes in place through the mapping, OP_SEAL publishes — no staging file, no bulk copy phase. Falls back to the graftcopy path when off, the native library is unavailable, fd-passing fails, or the allocation cannot fit (ENOSPC).")
_flag("graftshm_min_bytes", int, 1024**2, "Route puts at least this large through the shm create/seal plane; smaller payloads keep the single-round-trip OP_PUT (create+seal costs two round-trips, which dominates below ~1 MiB).")

# --- scheduling ---
_flag("scheduler_spread_threshold", float, 0.5, "Hybrid policy: pack below this utilization, then spread.")
_flag("max_pending_lease_requests_per_class", int, 8, "Pipelined lease requests per scheduling class (aligned with worker_pool_max_idle_workers so steady-state bursts cause no worker churn).")
_flag("lease_queue_wait_ms", int, 1000, "Server-side park time for an unsatisfiable lease request before the client must re-request (kills client-side poll loops).")
_flag("worker_lease_pipeline_depth", int, 16, "Task pushes kept in flight per leased worker (hides RPC latency; execution on the worker stays serial).")
_flag("worker_pool_max_idle_workers", int, 8, "Idle workers kept warm per node.")
_flag("worker_prestart", int, 0, "Workers to spawn at agent startup (reference: worker_pool.cc PrestartWorkers) — warm pools make burst workloads spawn-free.")
_flag("locality_min_bytes", int, 128 * 1024, "Stored-arg bytes on a node before a task prefers leasing there (reference: lease_policy.cc locality-aware scheduling).")
_flag("worker_pool_idle_ttl_s", int, 300, "Kill idle workers after this long.")
_flag("graftsched", bool, True, "Lease-based scheduling fast path (graftsched): lease waves are granted in ONE batched agent RPC per wave (reference: cluster_lease_manager.cc grants locally, ray_syncer broadcasts the delta), drained lease runners park on a keep-alive TTL instead of returning the lease per burst, the agent syncs the controller with coalesced fire-and-forget resource deltas, and one-round placement-group create/remove folds prepare+commit into a single agent op per node. RAY_TPU_GRAFTSCHED=0 restores the per-op legacy paths.")
_flag("graftsched_inline_bytes", int, 8192, "Small-object provenance threshold: results/puts at or under this size that ride inline in the reply frame (never touching the store) get owner-attested grafttrail object events on the 'inline' plane so `audit` still balances; larger inline objects stay untracked as before.")
_flag("graftsched_keepalive_ms", int, 250, "Lease keep-alive: a drained lease runner holds its leased worker this long waiting for new same-class tasks before returning the lease (kills the request/return round-trip pair between bursts). 0 returns leases eagerly (legacy).")
_flag("sched_delta_ms", int, 20, "Coalescing window for the agent's fire-and-forget scheduling-delta sync to the controller (lease grants/returns between heartbeats); keeps spillback picks fresh without per-grant RPCs.")

# --- streaming generators ---
_flag("streaming_generator_backpressure_items", int, 16, "Yielded-but-unconsumed items before the producer stalls (reference: generator_waiter.cc backpressure).")

# --- fault tolerance ---
_flag("reply_ref_grace_s", int, 600, "Fallback window for proxy borrows on refs forwarded in task replies; a live receiver acks long before this, so it only bounds leaks when the receiver died.")
_flag("max_task_retries_default", int, 3, "Default retries for retriable tasks.")
_flag("actor_max_restarts_default", int, 0, "Default actor restarts.")
_flag("lineage_pinning_enabled", bool, True, "Pin lineage for object reconstruction.")
_flag("gcs_storage_path", str, "", "Controller durable-state path: empty = in-memory; *.db/*.sqlite = sqlite store (put on shared storage for head failover); else a pickle snapshot file (the reference's Redis-backed GCS fault tolerance analogue).")
_flag("gcs_storage_allow_empty_start", bool, False, "Override: let the controller start with EMPTY in-memory state when the configured gcs_storage_path fails to open. Default off — an unopenable durable store fails fast instead of silently 'restoring' an empty cluster (the reference's redis-backed GCS does the same).")

# --- worker isolation (reference: src/ray/common/cgroup2/) ---
_flag("cgroup_isolation", bool, True, "Put dedicated actor workers with memory/CPU requests into cgroup v2 scopes when the unified hierarchy is writable.")
_flag("worker_rlimit_memory", bool, False, "Fallback when cgroups are unavailable: cap a dedicated worker's heap (RLIMIT_DATA) at its 'memory' resource request.")

# --- memory monitor / OOM (reference: src/ray/common/memory_monitor.h + raylet/worker_killing_policy.cc) ---
_flag("memory_monitor_refresh_ms", int, 500, "Node memory poll period; 0 disables OOM killing.")
_flag("memory_usage_threshold", float, 0.95, "Kill a worker when node memory use exceeds this fraction.")
_flag("memory_monitor_test_file", str, "", "Test seam: read memory usage fraction from this file instead of /proc/meminfo.")

# --- chaos / testing (reference: src/ray/rpc/rpc_chaos.cc, RAY_testing_rpc_failure) ---
_flag("testing_rpc_failure", str, "", "Comma list 'method=prob' to randomly fail RPCs.")
_flag("testing_event_loop_delay_us", int, 0, "Inject delay into event-loop handlers (asio-delay analogue).")

# --- TPU / accelerator plane ---
_flag("tpu_chips_per_host", int, 0, "Explicit chip count (0 = auto-detect).")
_flag("tpu_visible_chips", str, "", "Analogue of TPU_VISIBLE_CHIPS pinning.")
_flag("collective_cpu_fallback", bool, True, "Allow CPU fallback collectives when no TPU present.")
_flag("cross_slice_device_dma", bool, False, "Let the PJRT transfer plane pull device objects ACROSS slice boundaries. Off (default): cross-slice device_get host-relays through the object plane (device->host->DCN RPC->device), the safe path when slices share no ICI/DMA domain.")

# --- logging / observability ---
_flag("event_export_path", str, "", "JSONL sink for structured lifecycle events (node/actor/job/serve pubsub + task transitions); empty disables (reference: export-API aggregator pipeline).")
_flag("log_to_driver", bool, True, "Stream worker stdout/stderr lines to the driver via the controller log_events channel. NOTE: the channel is cluster-global (no per-job scoping yet); multiple concurrent drivers see each other's worker output.")
_flag("event_stats_enabled", bool, True, "Record per-handler event-loop stats.")
_flag("task_events_batch_size", int, 1000, "Task events per batch sent to controller.")
_flag("metrics_report_period_ms", int, 5000, "Metrics push period.")
_flag("graftscope", bool, True, "Native-plane flight recorder (graftscope): per-thread ring buffers in the graftrpc/graftcopy/sidecar hot paths, drained into metrics and the stitched timeline. RAY_TPU_GRAFTSCOPE=0 disables recording everywhere (Python seam and C planes read the same env).")
_flag("graftpulse", bool, True, "Cluster telemetry plane (graftpulse): each node agent ships a fixed-schema pulse (scope counter deltas + log2 latency histograms + store/shm/worker stats) to the controller every tick; the controller folds them into SLO time series, health state and autoscaling signals. RAY_TPU_GRAFTPULSE=0 disables assembly and shipping.")
_flag("pulse_period_ms", int, 1000, "graftpulse tick period: one pulse per node per tick.")
_flag("pulse_suspect_ticks", int, 2, "Missed pulses before the controller marks a node suspect.")
_flag("pulse_dead_ms", int, 8000, "Pulse silence before a suspect node is declared dead (actors restarted, owned objects re-resolved). Heartbeat liveness still applies independently.")
_flag("pulse_history", int, 300, "Pulse samples retained per node in the controller ring buffer.")
_flag("event_buffer_max", int, 4096, "Max buffered (unflushed) events in the exporter; beyond this the oldest are dropped and counted in the events_dropped gauge.")
_flag("grafttrail", bool, True, "State-observability plane (grafttrail): workers emit per-attempt task lifecycle transitions (SUBMITTED/LEASED/RUNNING/FINISHED/FAILED/CANCELLED) and agents export the store journal as object provenance; batches ride the worker flush tick and a fire-and-forget agent->controller path into the indexed controller ledger behind `ray_tpu list/summary/get/audit`. RAY_TPU_GRAFTTRAIL=0 falls back to the legacy submitted/finished/failed pipeline.")
_flag("trail_flush_ms", int, 1000, "grafttrail agent->controller batch period.")
_flag("trail_task_cap", int, 20000, "Task records retained in the controller trail ledger (terminal records evict first; drops are counted).")
_flag("trail_object_cap", int, 50000, "Object records retained in the controller trail ledger (freed records evict first; drops are counted).")
_flag("trail_audit_grace_s", float, 300.0, "Audit grace: a non-terminal task with no transition for this long counts as lost.")
_flag("autoscale_p99_ms", float, 0.0, "Scale up when the cluster-wide native op p99 (from graftpulse histograms) exceeds this many milliseconds while work is queued; 0 disables the latency signal.")
_flag("graftprof", bool, True, "Continuous profiling plane (graftprof): a native per-process sampler snapshots registered-thread CPU time and GIL-acquire latency while a Python wall-stack sampler folds task-attributed flamegraph profiles; deltas ride the worker flush tick to the controller store behind `ray_tpu prof top/flame`. RAY_TPU_GRAFTPROF=0 disables both samplers (Python seam and C sampler read the same env).")
_flag("prof_hz", int, 67, "graftprof sampling rate (ticks/s) for both the native CPU/GIL sampler and the Python wall-stack sampler. Off-round by default so the tick train can't alias the 2 s flush or the 1 s pulse.")
_flag("prof_history", int, 120, "Profile flush windows retained per node in the controller ProfStore (the `prof top --seconds` query window).")
_flag("prof_task_cap", int, 512, "Distinct (task, actor) merged profiles retained in the controller ProfStore (LRU eviction).")
_flag("prof_stack_cap", int, 256, "Distinct folded stacks retained per task profile (coldest evicted on merge).")
_flag("graftlog", bool, True, "Crash-persistent log plane (graftlog): every worker and agent appends task-attributed log records (logger calls + captured stdout/stderr) to a MAP_SHARED logring-<pid> file in the store dir; agents tail the rings into the controller LogStore and salvage a dead worker's final lines into its grafttrail attempt record. RAY_TPU_GRAFTLOG=0 disables emit, tailing and salvage (Python seam and C emit path read the same env).")
_flag("log_flush_ms", int, 1000, "graftlog agent tick: ring-tail and batch-ship period.")
_flag("log_cap", int, 20000, "Log records retained in the controller LogStore (oldest sub-WARNING records evict first; salvaged records last).")
_flag("log_rate_per_s", float, 200.0, "Per-worker sustained ingest cap at the controller LogStore (token bucket, 2x burst); suppressed records are counted, salvage bypasses.")
_flag("log_dedup_window_s", float, 5.0, "Error-storm dedup: an identical (node, pid, task, message) inside this window bumps a repeats counter instead of storing a new record.")
_flag("log_tail_lines", int, 200, "Ring records salvaged from a dead worker's logring file and attached (last 20) to its grafttrail attempt record.")
_flag("log_file_max_bytes", int, 16 << 20, "Rotation threshold for session logs/<component>-<pid>.log files (0 = unbounded legacy behavior).")
_flag("log_file_backups", int, 3, "Rotated session log files kept per component.")
_flag("graftmeta", bool, True, "Plane self-telemetry (graftmeta): the controller meters every observability plane's own fold path — per-plane ingest records/s and bytes/s, fold-latency log2 histograms, store occupancy/eviction/dedup counters, event-loop lag, controller RSS — in a bounded ring behind /api/meta, /metrics/cluster gauges and `ray_tpu status --planes`. RAY_TPU_GRAFTMETA=0 disables the meter (handlers skip the timing wrap).")
_flag("meta_history", int, 600, "Meta-plane ticks retained in the controller self-telemetry ring (one tick per meta_tick_ms).")
_flag("meta_tick_ms", int, 1000, "graftmeta tick period: loop-lag probe + RSS sample + counter snapshot per tick.")
_flag("meta_span_min_us", int, 1000, "Plane folds at least this slow emit a controller-side 'meta.fold.<plane>' span into the native timeline (`timeline --native`); 0 disables span emission.")
_flag("log_shards", int, 8, "Controller LogStore shards (node-hash partitioned, per-shard lock and eviction); 1 restores the single-store layout.")
_flag("prof_shards", int, 8, "Controller ProfStore shards (node-hash partitioned ingest, merged on query); 1 restores the single-store layout.")


class Config:
    """Process-global config singleton (thread-safe lazy resolution).

    Resolution order: explicit overrides (``initialize``) > ``RAY_TPU_*`` env
    var > registered default.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._overrides: Dict[str, Any] = {}
        self._cache: Dict[str, Any] = {}

    def initialize(self, overrides: Dict[str, Any] | None = None) -> None:
        with self._lock:
            if overrides:
                unknown = set(overrides) - set(_REGISTRY)
                if unknown:
                    raise ValueError(f"Unknown config flags: {sorted(unknown)}")
                self._overrides.update(overrides)
            self._cache.clear()

    def get(self, name: str) -> Any:
        try:
            return self._cache[name]
        except KeyError:
            pass
        flag = _REGISTRY[name]
        with self._lock:
            if name in self._overrides:
                val = self._overrides[name]
            else:
                env = os.environ.get(_ENV_PREFIX + name.upper())
                val = _PARSERS[flag.type](env) if env is not None else flag.default
            self._cache[name] = val
            return val

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in _REGISTRY:
            raise AttributeError(f"No such config flag: {name}")
        return self.get(name)

    # --- serialization for spawned processes ---
    def serialize(self) -> str:
        with self._lock:
            return json.dumps(self._overrides)

    @staticmethod
    def deserialize_into_env(blob: str) -> Dict[str, str]:
        """Return env-var dict encoding the overrides for a child process."""
        overrides = json.loads(blob) if blob else {}
        return {
            _ENV_PREFIX + k.upper(): str(int(v) if isinstance(v, bool) else v)
            for k, v in overrides.items()
        }

    def all_flags(self) -> Dict[str, Any]:
        return {name: self.get(name) for name in _REGISTRY}


GlobalConfig = Config()
