"""Worker resource isolation — cgroup v2 slices with an rlimit fallback.

Analogue of the reference's cgroup layer (reference: src/ray/common/
cgroup2/ — system vs worker cgroup slices with memory/cpu limits).
TPU-host reality: clusters run workers as root on dedicated VMs (cgroup
v2 writable) OR inside containers where only rlimits apply — so this is
a two-tier seam:

  1. cgroup v2 (preferred): a `raytpu-workers/<name>` subtree per
     dedicated worker with memory.max / cpu.max from the actor's
     resource request; removed when the worker exits.
  2. RLIMIT_DATA fallback (opt-in via worker_rlimit_memory): caps the
     worker's heap at spawn — a hard per-process backstop under the
     node-level memory-monitor OOM policy.

Isolation applies to DEDICATED actor workers only: pooled task workers
are reused across requests with different shapes, so a per-process
limit would outlive the request that asked for it.
"""

from __future__ import annotations

import os
import resource
from typing import Optional

from ray_tpu.utils import get_logger

logger = get_logger("cgroups")

CGROUP_ROOT = "/sys/fs/cgroup"
_SUBTREE = "raytpu-workers"


def _v2_available(root: str = CGROUP_ROOT) -> bool:
    """cgroup v2 unified hierarchy, writable by this process."""
    ctrl = os.path.join(root, "cgroup.controllers")
    return os.path.exists(ctrl) and os.access(root, os.W_OK)


class WorkerCgroup:
    """One worker's cgroup scope (no-op object when v2 is unavailable)."""

    def __init__(self, path: Optional[str]):
        self._path = path

    @property
    def active(self) -> bool:
        return self._path is not None

    def add_pid(self, pid: int) -> None:
        if self._path is None:
            return
        try:
            with open(os.path.join(self._path, "cgroup.procs"), "w") as f:
                f.write(str(pid))
        except OSError as e:
            logger.warning("could not move pid %d into %s: %r", pid,
                           self._path, e)

    def cleanup(self) -> None:
        if self._path is None:
            return
        try:
            os.rmdir(self._path)  # cgroup dirs remove via rmdir
        except OSError:
            pass
        self._path = None


def create_worker_cgroup(name: str, *,
                         memory_bytes: Optional[int] = None,
                         cpus: Optional[float] = None,
                         root: str = CGROUP_ROOT) -> WorkerCgroup:
    """Create a limited scope for one worker; returns an inactive scope
    when cgroup v2 isn't available/writable (callers fall back to
    rlimits / the memory monitor)."""
    if not _v2_available(root):
        return WorkerCgroup(None)
    try:
        base = os.path.join(root, _SUBTREE)
        os.makedirs(base, exist_ok=True)
        # Delegate the controllers down BOTH levels: enabling them only
        # at the root surfaces memory.max/cpu.max in raytpu-workers but
        # NOT in its children — the leaf writes below would ENOENT.
        for level in (root, base):
            try:
                with open(os.path.join(level, "cgroup.subtree_control"),
                          "w") as f:
                    f.write("+memory +cpu")
            except OSError:
                pass  # may already be enabled / partially available
        path = os.path.join(base, name)
        os.makedirs(path, exist_ok=True)
        if memory_bytes:
            with open(os.path.join(path, "memory.max"), "w") as f:
                f.write(str(int(memory_bytes)))
        if cpus:
            # cpu.max: "<quota> <period>" microseconds.
            period = 100_000
            with open(os.path.join(path, "cpu.max"), "w") as f:
                f.write(f"{int(cpus * period)} {period}")
        return WorkerCgroup(path)
    except OSError as e:
        logger.warning("cgroup isolation unavailable (%r); relying on "
                       "the memory-monitor OOM policy", e)
        return WorkerCgroup(None)


def rlimit_preexec(memory_bytes: int):
    """preexec_fn capping the child's heap (RLIMIT_DATA covers brk +
    data mmaps on Linux >= 4.7). Runs in the forked child, pre-exec —
    `resource` is imported at module level and captured here because an
    import inside the fork of a multithreaded parent can deadlock on
    the inherited import lock."""
    setrlimit = resource.setrlimit
    limit = resource.RLIMIT_DATA

    def apply():
        setrlimit(limit, (memory_bytes, memory_bytes))

    return apply
