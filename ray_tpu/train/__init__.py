from ray_tpu.train.api_config import (CheckpointConfig, FailureConfig,
                                      Result, RunConfig, ScalingConfig)
from ray_tpu.train.checkpointing import (AsyncCheckpointer, Checkpoint,
                                         CheckpointManager,
                                         load_checkpoint_host,
                                         restore_checkpoint)
from ray_tpu.train.jax_trainer import JaxTrainer
from ray_tpu.train.scaling_policy import (ElasticScalingPolicy,
                                          FixedScalingPolicy,
                                          ScalingPolicy)
from ray_tpu.train.session import (get_context, get_dataset_shard, profile,
                                   report, save_checkpoint)
from ray_tpu.train.spmd import (default_optimizer, make_train_fns,
                                state_shardings)

__all__ = [
    "AsyncCheckpointer", "Checkpoint", "CheckpointConfig",
    "CheckpointManager",
    "ElasticScalingPolicy", "FailureConfig", "FixedScalingPolicy",
    "JaxTrainer", "Result", "RunConfig", "ScalingConfig", "ScalingPolicy",
    "default_optimizer", "get_context", "get_dataset_shard",
    "load_checkpoint_host", "make_train_fns", "profile", "report",
    "restore_checkpoint", "save_checkpoint", "state_shardings",
]
