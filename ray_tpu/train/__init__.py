from ray_tpu.train.spmd import default_optimizer, make_train_fns, state_shardings

__all__ = ["default_optimizer", "make_train_fns", "state_shardings"]
