from ray_tpu.train.api_config import (CheckpointConfig, FailureConfig,
                                      Result, RunConfig, ScalingConfig)
from ray_tpu.train.jax_trainer import JaxTrainer
from ray_tpu.train.session import get_context, get_dataset_shard, report
from ray_tpu.train.spmd import (default_optimizer, make_train_fns,
                                state_shardings)

__all__ = [
    "CheckpointConfig", "FailureConfig", "JaxTrainer", "Result", "RunConfig",
    "ScalingConfig", "default_optimizer", "get_context",
    "get_dataset_shard", "make_train_fns", "report", "state_shardings",
]
