from ray_tpu.train.api_config import (CheckpointConfig, FailureConfig,
                                      Result, RunConfig, ScalingConfig)
from ray_tpu.train.checkpointing import (Checkpoint, CheckpointManager,
                                         load_checkpoint_host,
                                         restore_checkpoint)
from ray_tpu.train.jax_trainer import JaxTrainer
from ray_tpu.train.session import (get_context, get_dataset_shard, profile,
                                   report, save_checkpoint)
from ray_tpu.train.spmd import (default_optimizer, make_train_fns,
                                state_shardings)

__all__ = [
    "Checkpoint", "CheckpointConfig", "CheckpointManager", "FailureConfig",
    "JaxTrainer", "Result", "RunConfig", "ScalingConfig",
    "default_optimizer", "get_context", "get_dataset_shard",
    "load_checkpoint_host", "make_train_fns", "profile", "report",
    "restore_checkpoint", "save_checkpoint", "state_shardings",
]
