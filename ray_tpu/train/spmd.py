"""SPMD training step: sharded init + jitted train step over a ParallelContext.

This is the per-worker compute path that ray_tpu.train's JaxTrainer workers
run (the analogue of the user's train_loop_per_worker in the reference,
python/ray/train/v2/jax/jax_trainer.py:19 — but here the framework owns the
sharded step, optimizer-state sharding, and donation).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models import llama
from ray_tpu.parallel.context import ParallelContext
from ray_tpu.parallel.sharding import tree_shardings

TrainState = Dict[str, Any]  # {"params", "opt_state", "step"}


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.1,
                      grad_clip: float = 1.0) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def state_shardings(cfg: llama.LlamaConfig, ctx: ParallelContext,
                    opt: optax.GradientTransformation) -> TrainState:
    param_sh = tree_shardings(llama.logical_axes(cfg), ctx.mesh, ctx.rules)
    replicated = NamedSharding(ctx.mesh, P())
    opt_shapes = jax.eval_shape(
        lambda: opt.init(llama.init_params(cfg, jax.random.PRNGKey(0))))
    opt_sh = optax.tree_map_params(
        opt, lambda _, s: s, opt_shapes, param_sh,
        transform_non_params=lambda _: replicated)
    return {"params": param_sh, "opt_state": opt_sh, "step": replicated}


def make_train_fns(cfg: llama.LlamaConfig, ctx: ParallelContext,
                   opt: Optional[optax.GradientTransformation] = None,
                   loss_fn: Optional[Callable] = None,
                   ) -> Tuple[Callable[[jax.Array], TrainState],
                              Callable[[TrainState, jax.Array],
                                       Tuple[TrainState, Dict[str, jax.Array]]]]:
    """Returns (init_fn(key) -> state, step_fn(state, tokens) -> (state, metrics)),
    both jitted with explicit shardings; step donates the state."""
    opt = opt or default_optimizer()
    loss = loss_fn or (lambda p, toks: llama.loss_fn(p, toks, cfg, ctx))
    shardings = state_shardings(cfg, ctx, opt)
    batch_sh = ctx.batch_sharding()

    def init_fn(key: jax.Array) -> TrainState:
        params = llama.init_params(cfg, key)
        return {"params": params, "opt_state": opt.init(params),
                "step": jnp.zeros((), jnp.int32)}

    def step_fn(state: TrainState, tokens: jax.Array):
        (l, metrics), grads = jax.value_and_grad(loss, has_aux=True)(
            state["params"], tokens)
        updates, new_opt = opt.update(grads, state["opt_state"], state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        gnorm = optax.global_norm(grads)
        metrics = dict(metrics, grad_norm=gnorm)
        return ({"params": new_params, "opt_state": new_opt,
                 "step": state["step"] + 1}, metrics)

    init_jit = jax.jit(init_fn, out_shardings=shardings)
    step_jit = jax.jit(step_fn,
                       in_shardings=(shardings, batch_sh),
                       out_shardings=(shardings, None),
                       donate_argnums=(0,))
    return init_jit, step_jit
