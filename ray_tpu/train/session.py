"""Worker-side train session: report() + get_context().

Analogue of the reference's train session (reference: python/ray/train/
_internal/session.py get_session / ray.train.report, v2 via
train/v2/_internal/execution/worker_group/thread_runner.py): the user's
train loop runs in a thread inside the worker actor and communicates with
the controller through this module.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple


class TrainContext:
    def __init__(self, rank: int, world_size: int,
                 experiment_name: str = "", storage_path: str = "",
                 restored_checkpoint: Optional[Any] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None):
        self.rank = rank
        self.world_size = world_size
        self.experiment_name = experiment_name
        self.storage_path = storage_path
        self._restored_checkpoint = restored_checkpoint
        self._dataset_shards = dict(dataset_shards or {})

    def get_world_rank(self) -> int:
        return self.rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_checkpoint(self) -> Optional[Any]:
        """Checkpoint to resume from (set on group restart), else None."""
        return self._restored_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        """This worker's DataIterator for the trainer's datasets= entry
        (reference: ray.train.get_dataset_shard)."""
        if name not in self._dataset_shards:
            raise KeyError(
                f"no dataset shard {name!r}; trainer datasets= had "
                f"{sorted(self._dataset_shards)}")
        return self._dataset_shards[name]


class _Session:
    def __init__(self, ctx: TrainContext):
        self.ctx = ctx
        self.lock = threading.Lock()
        # (metrics, checkpoint) tuples not yet drained by the controller.
        self.reported: List[Tuple[Dict[str, Any], Optional[Any]]] = []
        self.finished = False
        self.error: Optional[str] = None

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Any] = None) -> None:
        with self.lock:
            self.reported.append((dict(metrics), checkpoint))

    def drain(self) -> List[Tuple[Dict[str, Any], Optional[Any]]]:
        with self.lock:
            out = self.reported
            self.reported = []
            return out


_session: Optional[_Session] = None


def _start_session(ctx: TrainContext) -> _Session:
    global _session
    _session = _Session(ctx)
    return _session


def _end_session() -> None:
    global _session, _async_ckptr
    _session = None
    # Flush any in-flight async save: the worker reporting "finished"
    # (and getting killed) must not strand an uncommitted checkpoint.
    ckptr, _async_ckptr = _async_ckptr, None
    if ckptr is not None:
        try:
            ckptr.close()
        except Exception:
            from ray_tpu.utils import get_logger
            get_logger("train.session").warning(
                "async checkpoint flush at session end failed",
                exc_info=True)


def get_context() -> TrainContext:
    if _session is None:
        raise RuntimeError("not inside a train worker session")
    return _session.ctx


def get_dataset_shard(name: str = "train"):
    """This worker's dataset shard (reference: ray.train.get_dataset_shard)."""
    return get_context().get_dataset_shard(name)


def profile():
    """Context manager: capture a JAX profiler trace (XPlane, viewable in
    TensorBoard/XProf) into the run's storage path (reference analogue:
    SURVEY §5.1 — task timeline + JAX profiler as the TPU tracing story).

        with ray_tpu.train.profile():
            state, m = step_fn(state, batch)
    """
    import contextlib
    import os

    @contextlib.contextmanager
    def _ctx():
        import jax
        ctx = get_context()
        base = ctx.storage_path or "/tmp/ray_tpu_profiles"
        out = os.path.join(base, ctx.experiment_name or "train_run",
                           f"profile-rank{ctx.rank}")
        os.makedirs(out, exist_ok=True)
        jax.profiler.start_trace(out)
        try:
            yield out
        finally:
            jax.profiler.stop_trace()

    return _ctx()


def save_checkpoint(state: Any, step: int,
                    metrics: Optional[Dict[str, Any]] = None, *,
                    block: bool = True):
    """Sharded save of a jax pytree into the run's storage path; call from
    EVERY rank (per-host shard writes + commit barrier), then report the
    returned handle: ``report(metrics, checkpoint=save_checkpoint(...))``.

    block=False (async, SURVEY §5.4 Orbax pattern): only the
    device->host snapshot runs here; file writes + the commit barrier
    run on a background thread and a Future[Checkpoint] is returned —
    call ``.result()`` (or save again, which serializes) before
    reporting it."""
    from ray_tpu.train.checkpointing import run_dir
    from ray_tpu.train.checkpointing import save_checkpoint as _save
    ctx = get_context()
    if not ctx.storage_path:
        raise RuntimeError("RunConfig.storage_path is not set")
    directory = run_dir(ctx.storage_path, ctx.experiment_name)
    if block:
        return _save(directory, state, step, metrics)
    global _async_ckptr
    if _async_ckptr is None:
        from ray_tpu.train.checkpointing import AsyncCheckpointer
        _async_ckptr = AsyncCheckpointer()
    return _async_ckptr.save(directory, state, step, metrics)


_async_ckptr = None


def report(metrics: Dict[str, Any], checkpoint: Optional[Any] = None) -> None:
    """Report metrics (and optionally a checkpoint) from the train loop.

    Reference analogue: ray.train.report (train/_internal/session.py).
    """
    if _session is None:
        raise RuntimeError("report() called outside a train worker session")
    _session.report(metrics, checkpoint)
