"""ScalingPolicy — when and how the Train worker group resizes.

Analogue of the reference's scaling-policy seam (reference:
python/ray/train/v2/_internal/execution/scaling_policy/ ScalingPolicy ->
ResizeDecision, executed by controller.py:171 _execute_resize_decision).
TPU-shaped: a decision is just a target WORLD SIZE — the controller
checkpoints, rebuilds the gang (new PG, new jax.distributed world, fresh
XLA compile at the new mesh), and resumes from the latest committed
checkpoint. SPMD jobs can't absorb workers in place the way a
parameter-server could; a clean re-gang IS the resize primitive.
"""

from __future__ import annotations

from typing import Dict, List


class ScalingPolicy:
    """Seam: map observed cluster state to a target worker count."""

    def target_workers(self, current: int, nodes: List[dict],
                       bundle: Dict[str, float]) -> int:
        raise NotImplementedError


class FixedScalingPolicy(ScalingPolicy):
    """Always the configured world size (the non-elastic default)."""

    def __init__(self, num_workers: int):
        self.num_workers = num_workers

    def target_workers(self, current, nodes, bundle) -> int:
        return self.num_workers


class ElasticScalingPolicy(ScalingPolicy):
    """Track cluster capacity between [min_workers, max_workers]: a node
    join grows the job at the next decision point; a node loss shrinks
    it instead of wedging the gang (reference: elastic resize decisions
    in train/v2 controller).

    Growth is computed from AVAILABLE resources (what a resize could
    actually reserve beyond the running group — other jobs' usage is
    respected); shrink-to-capacity uses TOTAL resources (on a node loss
    the dead node's totals vanish)."""

    def __init__(self, min_workers: int, max_workers: int):
        assert 1 <= min_workers <= max_workers
        self.min_workers = min_workers
        self.max_workers = max_workers

    @staticmethod
    def _fits(res: Dict[str, float], bundle: Dict[str, float]) -> int:
        fits = None
        for r, amount in bundle.items():
            if amount <= 0:
                continue
            n = int(float(res.get(r, 0.0)) // amount)
            fits = n if fits is None else min(fits, n)
        return fits or 0

    def target_workers(self, current, nodes, bundle) -> int:
        alive = [n for n in nodes
                 if n.get("state", "ALIVE") == "ALIVE"]
        cap_total = sum(self._fits(n.get("resources_total", {}), bundle)
                        for n in alive)
        extra = sum(self._fits(n.get("resources_available", {}), bundle)
                    for n in alive)
        # Up to current+extra is reservable right now; never above what
        # the (possibly shrunken) cluster could hold at all.
        target = min(cap_total, current + extra)
        return max(self.min_workers, min(self.max_workers, target))
