"""Sharded jax.Array checkpointing: per-host shard writes + commit barrier
+ top-K manager.

Analogue of the reference's checkpoint stack (reference:
python/ray/train/_checkpoint.py Checkpoint directory handle,
train/v2/_internal/execution/checkpoint/checkpoint_manager.py top-K
tracking, checkpoint/sync_actor.py rank barrier; SURVEY §5.4 maps these to
Orbax-style async multi-host saves). TPU-native layout:

    {dir}/step-{N}/
        _METADATA.json          # pytree structure + per-leaf shape/dtype
                                # (written by process 0); restore derives
                                # shard indices from the target's sharding
        leaf{i}.{indexkey}.npy  # one file per UNIQUE array shard
        COMMIT                  # written after the cross-host barrier —
                                # a checkpoint without it is incomplete

Every process writes only the shards it addresses with replica_id == 0
(replicated shards are written once cluster-wide); after the
``sync_global_devices`` barrier process 0 drops the COMMIT marker, so a
partially-written checkpoint is never observed as valid.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def run_dir(storage_path: str, name: str) -> str:
    """Canonical checkpoint directory for a run — the ONE derivation shared
    by the controller's CheckpointManager and worker-side save_checkpoint
    (divergence would silently break auto-resume)."""
    return os.path.join(storage_path, name or "train_run")


class Checkpoint:
    """Handle to one committed checkpoint directory (reference:
    python/ray/train/_checkpoint.py Checkpoint)."""

    def __init__(self, path: str, step: int = 0,
                 metrics: Optional[Dict[str, Any]] = None):
        self.path = path
        self.step = step
        self.metrics = dict(metrics or {})

    def is_valid(self) -> bool:
        return os.path.exists(os.path.join(self.path, "COMMIT"))

    def __repr__(self):
        return f"Checkpoint(step={self.step}, path={self.path!r})"


def _recover_trashed(directory: str, step: int) -> None:
    """Crash recovery for the commit swap: a crash between the two renames
    in save_checkpoint leaves NO step-N while the previously committed
    checkpoint sits in _trash-step-N — rename it back so the guarantee
    (an existing committed step stays restorable until the new save is
    durable) holds across that microsecond window too."""
    final_dir = os.path.join(directory, f"step-{step}")
    trash = os.path.join(directory, f"_trash-step-{step}")
    if (not os.path.isdir(final_dir)
            and os.path.exists(os.path.join(trash, "COMMIT"))):
        os.rename(trash, final_dir)


def _recover_all_trashed(directory: str) -> None:
    if not os.path.isdir(directory):
        return
    for name in os.listdir(directory):
        if not name.startswith("_trash-step-"):
            continue
        try:
            step = int(name[len("_trash-step-"):])
        except ValueError:
            continue
        try:
            _recover_trashed(directory, step)
        except OSError:
            continue
        # Superseded trash (a crash landed after the final rename but
        # before the cleanup rmtree): step-N exists, so the trash copy is
        # garbage — delete it or it leaks a full checkpoint forever.
        trash = os.path.join(directory, name)
        if os.path.isdir(trash) and os.path.isdir(
                os.path.join(directory, f"step-{step}")):
            shutil.rmtree(trash, ignore_errors=True)


def _index_key(index: Tuple, shape: Tuple[int, ...]) -> str:
    """Stable filename key for one shard's global slice tuple."""
    parts = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        parts.append(f"{start}-{stop}")
    return "_".join(parts) or "scalar"


def _leaf_paths(tree) -> List[Tuple[str, Any]]:
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        name = jax.tree_util.keystr(path).strip("[]'\".").replace(
            "']['", ".").replace("']", "").replace("['", ".")
        out.append((name, leaf))
    return out


def save_checkpoint(directory: str, state: Any, step: int,
                    metrics: Optional[Dict[str, Any]] = None) -> Checkpoint:
    """Save a pytree of jax.Arrays (or numpy/scalars). Call from EVERY
    process in a multi-host run — each writes its replica-0 addressable
    shards; commit happens after the global barrier. (The sync flavor:
    snapshot + write on this thread with DEVICE barriers; the async
    flavor below runs the same phases with a marker-file barrier.)"""
    import jax

    _prepare_save(directory, step)
    # No host copies on the sync path: nothing overlaps the write, so
    # shards stream zero-copy (async saves must copy — see _snapshot).
    snap = _snapshot(state, step, metrics, copy=False)
    ckpt = _write_snapshot(directory, snap, device_barrier=True)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"ckpt-visible-{step}")
    return ckpt


def _prepare_save(directory: str, step: int) -> None:
    """On-thread pre-save: recover any trashed commit, clear stale tmp
    state (process 0), and line every process up behind that clear."""
    import jax

    ckpt_dir = os.path.join(directory, f"_tmp-step-{step}")
    if jax.process_index() == 0:
        _recover_trashed(directory, step)
        if os.path.isdir(ckpt_dir):
            shutil.rmtree(ckpt_dir, ignore_errors=True)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"ckpt-begin-{step}")


def _snapshot(state: Any, step: int,
              metrics: Optional[Dict[str, Any]],
              copy: bool = True) -> dict:
    """Device->host snapshot + metadata plan — the ONLY phase that must
    pause the training loop (HBM->RAM copies of this process's replica-0
    shards). With copy=True (the ASYNC path) arrays are deep-copied: on
    backends where __array__ is zero-copy (CPU), a donated buffer would
    otherwise be reused by the next train step while the background
    writer still reads it. The sync path passes copy=False and streams
    shards without doubling host memory."""
    import jax

    proc = jax.process_index()
    flat = _leaf_paths(state)
    meta: Dict[str, Any] = {"step": step, "leaves": [],
                            "metrics": dict(metrics or {})}
    writes: List[Tuple[str, np.ndarray]] = []  # (filename, host array)
    for li, (name, leaf) in enumerate(flat):
        if isinstance(leaf, jax.Array):
            shape = tuple(leaf.shape)
            for shard in leaf.addressable_shards:
                if shard.replica_id == 0:
                    key = _index_key(shard.index, shape)
                    # asarray (copy only if the backend must) for sync;
                    # forced copy for async (numpy 2 rejects copy=False
                    # when a device->host copy is unavoidable).
                    host = np.array(shard.data, copy=True) if copy \
                        else np.asarray(shard.data)
                    writes.append((f"leaf{li}.{key}.npy", host))
            # Manifest: the exact global shard-key set (computable on any
            # process from the global sharding) — readers trust only
            # these files, so stale shards from a crashed save are never
            # merged.
            all_keys = sorted({_index_key(idx, shape) for idx in
                               leaf.sharding.devices_indices_map(
                                   shape).values()})
            meta["leaves"].append({"name": name, "kind": "array",
                                   "shape": shape,
                                   "dtype": str(leaf.dtype),
                                   "files": all_keys})
        else:
            if proc == 0:
                writes.append((f"leaf{li}.host.npy",
                               np.array(leaf, copy=True) if copy
                               else np.asarray(leaf)))
            meta["leaves"].append({"name": name, "kind": "host",
                                   "shape": tuple(np.shape(leaf)),
                                   "dtype": str(np.asarray(leaf).dtype),
                                   "files": ["host"]})
    return {"meta": meta, "writes": writes, "step": step,
            "proc": proc, "nprocs": jax.process_count()}


def _write_snapshot(directory: str, snap: dict,
                    barrier_timeout: float = 600.0,
                    device_barrier: bool = False) -> Checkpoint:
    """Write a snapshot's files and commit (the shared back half of sync
    AND async saves). Two barrier flavors:

      device_barrier=True  — sync path, runs ON the training thread:
        sync_global_devices between writes and commit.
      device_barrier=False — async path, runs on a background thread:
        rank MARKER FILES on the shared checkpoint storage (a device
        collective off-thread would interleave with the training step's
        collectives). Every rank's Checkpoint resolves only once COMMIT
        is visible, so reporting a resolved future is always safe.

    All writes land in a TEMP dir; the committed dir is replaced by an
    atomic swap at the very end, so (a) a crashed save never mixes stale
    shards into a later save of the same step and (b) an existing
    COMMITTED step-N stays restorable until the new save is durable.
    """
    step, proc, nprocs = snap["step"], snap["proc"], snap["nprocs"]
    final_dir = os.path.join(directory, f"step-{step}")
    ckpt_dir = os.path.join(directory, f"_tmp-step-{step}")
    os.makedirs(ckpt_dir, exist_ok=True)
    for fname, arr in snap["writes"]:
        np.save(os.path.join(ckpt_dir, fname), arr, allow_pickle=False)

    # Commit barrier: every process must have finished its writes before
    # the checkpoint becomes observable (reference: sync_actor.py
    # barrier; Orbax per-host write + commit).
    if nprocs > 1:
        if device_barrier:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"ckpt-commit-{step}")
        else:
            with open(os.path.join(ckpt_dir, f"_rank-{proc}.done"),
                      "w") as f:
                f.write("ok")
    if proc != 0:
        if not device_barrier:
            _await_commit(final_dir, ckpt_dir, proc, barrier_timeout)
        return Checkpoint(final_dir, step, snap["meta"]["metrics"])
    if nprocs > 1 and not device_barrier:
        deadline = time.monotonic() + barrier_timeout
        want = {f"_rank-{r}.done" for r in range(nprocs)}
        while want - set(os.listdir(ckpt_dir)):
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"checkpoint commit barrier: missing "
                    f"{sorted(want - set(os.listdir(ckpt_dir)))}")
            time.sleep(0.05)
        for r in range(nprocs):
            try:
                os.unlink(os.path.join(ckpt_dir, f"_rank-{r}.done"))
            except OSError:
                pass
    with open(os.path.join(ckpt_dir, "_METADATA.json"), "w") as f:
        json.dump(snap["meta"], f)
    with open(os.path.join(ckpt_dir, "COMMIT"), "w") as f:
        f.write("ok")
    trash = os.path.join(directory, f"_trash-step-{step}")
    shutil.rmtree(trash, ignore_errors=True)
    if os.path.isdir(final_dir):
        os.rename(final_dir, trash)
    os.rename(ckpt_dir, final_dir)
    shutil.rmtree(trash, ignore_errors=True)
    return Checkpoint(final_dir, step, snap["meta"]["metrics"])


def _await_commit(final_dir: str, ckpt_dir: str, proc: int,
                  timeout: float) -> None:
    """Non-zero async ranks resolve only once THIS save committed — a
    resolved Checkpoint must always be restorable. A pre-existing
    committed step-N (re-save of an old step) must not satisfy the wait,
    so first wait for rank 0 to consume OUR marker file (it unlinks all
    markers immediately before writing COMMIT; the residual
    crash-between-unlink-and-commit window is microseconds vs the whole
    write window)."""
    marker = os.path.join(ckpt_dir, f"_rank-{proc}.done")
    deadline = time.monotonic() + timeout
    while os.path.exists(marker):
        if time.monotonic() > deadline:
            raise TimeoutError(f"commit barrier: rank-0 never consumed "
                               f"{marker} within {timeout}s")
        time.sleep(0.05)
    while not os.path.exists(os.path.join(final_dir, "COMMIT")):
        if time.monotonic() > deadline:
            raise TimeoutError(f"no COMMIT at {final_dir} after "
                               f"{timeout}s (rank-0 writer lost?)")
        time.sleep(0.05)


class AsyncCheckpointer:
    """Orbax-style async multi-host saves (SURVEY §5.4): ``save`` pauses
    training only for the device->host snapshot, then writes + commits
    on a background thread; a kill mid-save leaves the previous
    committed step restorable (no COMMIT until every rank's shards are
    durable).

        ckptr = AsyncCheckpointer()
        fut = ckptr.save(directory, state, step)   # returns immediately
        ...keep training...
        ckpt = fut.result()                        # or ckptr.wait()
    """

    def __init__(self):
        import concurrent.futures
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="async-ckpt")
        self._inflight: Optional[Any] = None

    def save(self, directory: str, state: Any, step: int,
             metrics: Optional[Dict[str, Any]] = None):
        """Snapshot now; write+commit in the background. Returns a
        Future[Checkpoint]. Back-to-back saves serialize (one writer
        thread), so at most one step of training overlaps a save."""
        self.wait()  # surface a prior save's failure HERE, not silently
        # On-thread (training-thread) prepare: clear + device barrier are
        # safe here, between steps.
        _prepare_save(directory, step)
        snap = _snapshot(state, step, metrics)
        self._inflight = self._pool.submit(_write_snapshot, directory,
                                           snap)
        return self._inflight

    def wait(self) -> Optional[Checkpoint]:
        """Block until the in-flight save (if any) committed."""
        fut, self._inflight = self._inflight, None
        return fut.result() if fut is not None else None

    def close(self) -> None:
        self.wait()
        self._pool.shutdown(wait=True)


def restore_checkpoint(ckpt: "Checkpoint | str", target: Any) -> Any:
    """Restore into the structure/shardings of `target` (a pytree of
    jax.Arrays with the desired shardings, e.g. the freshly-initialized
    train state). Each process loads only the shard files its devices
    need."""
    import jax

    path = ckpt.path if isinstance(ckpt, Checkpoint) else ckpt
    if not os.path.exists(os.path.join(path, "COMMIT")):
        base, name = os.path.split(os.path.abspath(path))
        if name.startswith("step-"):
            try:
                _recover_trashed(base, int(name[len("step-"):]))
            except (ValueError, OSError):
                pass
    if not os.path.exists(os.path.join(path, "COMMIT")):
        raise FileNotFoundError(f"no committed checkpoint at {path}")
    with open(os.path.join(path, "_METADATA.json")) as f:
        meta = json.load(f)

    flat_target = _leaf_paths(target)
    assert len(flat_target) == len(meta["leaves"]), \
        (len(flat_target), len(meta["leaves"]))
    new_leaves = []
    for li, ((name, leaf), lm) in enumerate(zip(flat_target,
                                                meta["leaves"])):
        if lm["kind"] == "host" or not isinstance(leaf, jax.Array):
            arr = np.load(os.path.join(path, f"leaf{li}.host.npy"))
            new_leaves.append(arr if arr.shape else arr.item())
            continue
        shape = tuple(lm["shape"])
        dtype = np.dtype(lm["dtype"])
        sharding = leaf.sharding
        index_map = sharding.addressable_devices_indices_map(shape)
        manifest = lm.get("files")
        cache: Dict[str, np.ndarray] = {}
        bufs = []
        for device, index in index_map.items():
            key = _index_key(index, shape)
            if manifest is not None and key not in manifest:
                raise FileNotFoundError(
                    f"checkpoint {path} leaf{li} has no shard {key!r} "
                    f"(saved under a different sharding — use "
                    f"load_checkpoint_host for cross-topology restore)")
            if key not in cache:
                cache[key] = np.load(
                    os.path.join(path, f"leaf{li}.{key}.npy")
                ).astype(dtype, copy=False)
            bufs.append(jax.device_put(cache[key], device))
        new_leaves.append(jax.make_array_from_single_device_arrays(
            shape, sharding, bufs))

    treedef = jax.tree_util.tree_structure(target)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def load_checkpoint_host(ckpt: "Checkpoint | str") -> Dict[str, np.ndarray]:
    """Assemble the full (unsharded) arrays on host as {leaf_name: array}
    — for inspection, serving, or cross-topology restore."""
    path = ckpt.path if isinstance(ckpt, Checkpoint) else ckpt
    if not os.path.exists(os.path.join(path, "COMMIT")):
        raise FileNotFoundError(f"no committed checkpoint at {path}")
    with open(os.path.join(path, "_METADATA.json")) as f:
        meta = json.load(f)
    out: Dict[str, np.ndarray] = {}
    for li, lm in enumerate(meta["leaves"]):
        if lm["kind"] == "host":
            out[lm["name"]] = np.load(os.path.join(path,
                                                   f"leaf{li}.host.npy"))
            continue
        shape = tuple(lm["shape"])
        full = np.empty(shape, dtype=np.dtype(lm["dtype"]))
        prefix = f"leaf{li}."
        # Read only manifest-listed shards (never stray files from an
        # earlier crashed save); fall back to listdir for old checkpoints.
        if lm.get("files") is not None:
            fnames = [f"{prefix}{key}.npy" for key in lm["files"]]
        else:
            fnames = [f for f in os.listdir(path)
                      if f.startswith(prefix) and f.endswith(".npy")]
        for fname in fnames:
            key = fname[len(prefix):-4]
            data = np.load(os.path.join(path, fname))
            if key == "scalar":
                full = data
                continue
            slices = tuple(slice(*map(int, part.split("-")))
                           for part in key.split("_"))
            full[slices] = data
        out[lm["name"]] = full
    return out


class CheckpointManager:
    """Top-K checkpoint retention (reference:
    v2/_internal/execution/checkpoint/checkpoint_manager.py): registers
    committed checkpoints, keeps the best `max_to_keep` by `metric`
    (or most recent when metric is None), deletes the rest."""

    def __init__(self, directory: str, *, max_to_keep: Optional[int] = 2,
                 metric: Optional[str] = None, mode: str = "min"):
        """max_to_keep=None keeps everything (no pruning) — the reference's
        num_to_keep=None semantics."""
        assert mode in ("min", "max")
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.metric = metric
        self.mode = mode
        self._ckpts: List[Checkpoint] = []
        self._discover()

    def _discover(self) -> None:
        """Pick up committed checkpoints already on disk (resume path)."""
        if not os.path.isdir(self.directory):
            return
        _recover_all_trashed(self.directory)
        for name in sorted(os.listdir(self.directory)):
            if not name.startswith("step-"):
                continue
            path = os.path.join(self.directory, name)
            if os.path.exists(os.path.join(path, "COMMIT")):
                try:
                    with open(os.path.join(path, "_METADATA.json")) as f:
                        meta = json.load(f)
                except Exception:
                    continue
                self._ckpts.append(Checkpoint(path, meta.get("step", 0),
                                              meta.get("metrics")))
        self._ckpts.sort(key=lambda c: c.step)

    def register(self, ckpt: Checkpoint) -> None:
        self._ckpts.append(ckpt)
        self._prune()

    def _rank_key(self, c: Checkpoint):
        """Higher = better. A checkpoint missing the metric ranks WORST in
        both modes (it must never shadow a scored one as best())."""
        if self.metric is None:
            return c.step  # most recent wins
        v = c.metrics.get(self.metric)
        if v is None:
            return float("-inf")
        return -v if self.mode == "min" else v

    def _prune(self) -> None:
        if self.max_to_keep is None:
            return
        while len(self._ckpts) > self.max_to_keep:
            # Never prune the newest checkpoint: crash-resume depends on
            # it even when its metric ranks worst.
            newest = max(self._ckpts, key=lambda c: c.step)
            candidates = [c for c in self._ckpts if c is not newest]
            if not candidates:
                return
            worst = min(candidates, key=self._rank_key)
            self._ckpts.remove(worst)
            shutil.rmtree(worst.path, ignore_errors=True)

    def latest(self) -> Optional[Checkpoint]:
        return max(self._ckpts, key=lambda c: c.step) if self._ckpts \
            else None

    def best(self) -> Optional[Checkpoint]:
        return max(self._ckpts, key=self._rank_key) if self._ckpts else None

    def checkpoints(self) -> List[Checkpoint]:
        return list(self._ckpts)
