"""Train configuration dataclasses.

Analogue of the reference's AIR/Train configs (reference: python/ray/air/
config.py ScalingConfig/RunConfig/FailureConfig/CheckpointConfig and
python/ray/train/v2/api/config.py — incl. use_tpu/topology at :89-90),
slimmed to the TPU-first surface.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    """How many training workers and what each one needs.

    num_workers: one JAX process per worker (usually one per TPU host, with
      all the host's chips, or one per chip with chips_per_worker=1).
    use_tpu: request TPU chips from the scheduler.
    chips_per_worker: TPU chips pinned to each worker (TPU_VISIBLE_CHIPS).
    resources_per_worker: extra scheduler resources per worker.
    placement_strategy: bundle placement (PACK | SPREAD | STRICT_SPREAD).
    topology: informational TPU topology string (e.g. "4x4").
    """
    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 0
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    placement_strategy: str = "PACK"
    topology: str = ""
    # Elastic mode (reference: train/v2 ScalingPolicy resize decisions):
    # with max_workers set, the controller tracks cluster capacity in
    # [min_workers or num_workers, max_workers] — a node join re-gangs
    # the job larger from the latest checkpoint; a loss shrinks it.
    min_workers: Optional[int] = None
    max_workers: Optional[int] = None

    def bundle(self) -> Dict[str, float]:
        res = {"CPU": 1.0}
        res.update(self.resources_per_worker)
        if self.use_tpu:
            res["TPU"] = float(self.chips_per_worker or 1)
        return res


@dataclass
class FailureConfig:
    """max_failures: worker-group restarts allowed (-1 = unlimited)."""
    max_failures: int = 0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"  # or "min"


@dataclass
class RunConfig:
    name: str = ""
    storage_path: str = ""
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(
        default_factory=CheckpointConfig)


@dataclass
class Result:
    """What fit() returns (reference: python/ray/air/result.py)."""
    metrics: Dict[str, Any] = field(default_factory=dict)
    metrics_history: list = field(default_factory=list)
    checkpoint: Optional[Any] = None
    error: Optional[BaseException] = None
    path: str = ""
