"""JaxTrainer — the public data-parallel trainer for JAX/TPU.

Analogue of the reference's trainers (reference:
python/ray/train/v2/api/data_parallel_trainer.py:60 DataParallelTrainer /
fit():118 and v2/jax/jax_trainer.py:19 JaxTrainer), TPU-first: the worker
group is one JAX process per worker, ``jax.distributed`` is initialized
from env the controller injects at spawn, and inside the loop the user
composes this framework's SPMD stack (ray_tpu.parallel / ray_tpu.train.spmd)
over the global device mesh.

Example::

    def loop(config):
        ctx = ray_tpu.train.get_context()
        ... jax code over jax.devices() (global across workers) ...
        ray_tpu.train.report({"loss": loss})

    trainer = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=4, use_tpu=True,
                                           chips_per_worker=4))
    result = trainer.fit()
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.train.api_config import Result, RunConfig, ScalingConfig
from ray_tpu.train.controller import TrainController


class JaxTrainer:
    def __init__(self, train_loop_per_worker, *,
                 train_loop_config: Optional[dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 worker_env: Optional[Dict[str, Optional[str]]] = None):
        """worker_env: extra env vars for every worker process (value None
        unsets a var). JAX reads its env at interpreter start, so platform
        selection (JAX_PLATFORMS, XLA_FLAGS, TPU_VISIBLE_CHIPS overrides)
        must ride here rather than inside the train loop.

        datasets: {name: ray_tpu.data.Dataset} — each is streaming_split
        across the worker group (equal=True for SPMD step parity); the loop
        reads its shard via ray_tpu.train.get_dataset_shard(name)
        (reference: DataParallelTrainer datasets= + train v2 data ingest).
        """
        self._controller = TrainController(
            train_loop_per_worker, train_loop_config,
            scaling_config or ScalingConfig(),
            run_config or RunConfig(), worker_env, datasets)

    def fit(self) -> Result:
        result = self._controller.run()
        if result.error is not None:
            raise result.error
        return result
