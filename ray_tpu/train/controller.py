"""TrainController — gang-schedules and supervises the worker group.

Analogue of the reference's Train v2 controller (reference:
python/ray/train/v2/_internal/execution/controller/controller.py:96
_run_control_loop_iteration/:259 _poll_workers, worker_group/worker_group.py,
failure_policy/). Differences by design: runs in the driver process (fit()
blocks anyway; a detached controller actor is the reference's resume story,
ours is the checkpoint manager), and the JAX coordinator address is chosen
up front because JAX env must be frozen at worker-process spawn.

Control loop: reserve a placement group (one bundle per worker, TPU chips
first-class) → create one TrainWorker actor per bundle with the JAX env in
its runtime_env → start() everyone → poll; on any worker failure tear the
group down and restart it (FailureConfig.max_failures), seeding the new
group with the latest reported checkpoint.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu import api as _api
from ray_tpu.train.api_config import (FailureConfig, Result, RunConfig,
                                      ScalingConfig)
from ray_tpu.train.worker import TrainWorker
from ray_tpu.utils import get_logger

logger = get_logger("train.controller")


class TrainingFailedError(RuntimeError):
    pass


class _ResizeRequested(Exception):
    """Control-flow signal: the scaling policy wants a new world size."""

    def __init__(self, target: int):
        super().__init__(f"resize to {target} workers")
        self.target = target


class TrainController:
    def __init__(self, train_loop, train_loop_config: Optional[dict],
                 scaling_config: ScalingConfig, run_config: RunConfig,
                 worker_env: Optional[Dict[str, Optional[str]]] = None,
                 datasets: Optional[Dict[str, Any]] = None):
        self._fn_blob = cloudpickle.dumps(train_loop)
        self._config = train_loop_config
        self._scaling = scaling_config
        self._run_cfg = run_config
        self._worker_env = dict(worker_env or {})
        self._datasets = dict(datasets or {})
        self._latest_checkpoint: Any = None
        self._metrics_history: List[Dict[str, Any]] = []
        # World size is policy-owned: fixed by default, capacity-tracked
        # when ScalingConfig.max_workers is set (reference: train/v2
        # ScalingPolicy + controller.py:171 _execute_resize_decision).
        from ray_tpu.train.scaling_policy import (ElasticScalingPolicy,
                                                  FixedScalingPolicy)
        if scaling_config.max_workers is not None:
            self._policy = ElasticScalingPolicy(
                scaling_config.min_workers or scaling_config.num_workers,
                scaling_config.max_workers)
        else:
            self._policy = FixedScalingPolicy(scaling_config.num_workers)
        self._world = scaling_config.num_workers
        self._resize_pending = 0
        self._resize_target = None
        self._last_policy_check = 0.0
        self._policy_err_logged = False
        # Set while a resize attempt hasn't proven schedulable yet so a
        # failed re-gang rolls back instead of burning failure budget;
        # a rolled-back target is backed off for a while.
        self._pre_resize_world: Optional[int] = None
        self._failed_resize_target: Optional[int] = None
        self._resize_backoff_until = 0.0
        # Top-K retention + auto-resume over the run's storage path
        # (reference: checkpoint_manager.py owned by the controller).
        self._ckpt_manager = None
        if run_config.storage_path:
            from ray_tpu.train.checkpointing import (CheckpointManager,
                                                     run_dir)
            ccfg = run_config.checkpoint_config
            self._ckpt_manager = CheckpointManager(
                run_dir(run_config.storage_path, run_config.name),
                max_to_keep=ccfg.num_to_keep,  # None = keep all
                metric=ccfg.checkpoint_score_attribute,
                mode=ccfg.checkpoint_score_order)
            latest = self._ckpt_manager.latest()
            if latest is not None:  # auto-resume from a prior run
                logger.info("auto-resuming from %s", latest)
                self._latest_checkpoint = latest

    def _make_shards(self, n: int) -> List[Dict[str, Any]]:
        """streaming_split every dataset across the group; one fresh split
        per attempt (a restarted group must not resume half-consumed
        iterators). Returns per-rank {name: DataIterator}."""
        per_rank: List[Dict[str, Any]] = [{} for _ in range(n)]
        self._coordinators: List[Any] = []
        for name, ds in self._datasets.items():
            its = ds.streaming_split(n, equal=True)
            self._coordinators.append(its[0]._coordinator)
            for rank, it in enumerate(its):
                per_rank[rank][name] = it
        return per_rank

    # -- worker group lifecycle -----------------------------------------
    def _make_group(self, pg, n: int):
        if not pg.ready(timeout=120):
            raise TrainingFailedError(
                f"could not reserve {n}x{self._scaling.bundle()} "
                f"({self._scaling.placement_strategy})")
        # Coordinator runs inside rank 0's process — pick a free port ON
        # rank 0's node via its agent (a driver-side probe would test the
        # wrong host on multi-host clusters).
        cw = _api._cw()
        info = cw._run(cw.controller.call("get_pg_info",
                                          pg.id.binary())).result()
        nodes = {n_["node_id"]: n_ for n_ in ray_tpu.nodes()}
        addr0 = tuple(nodes[info["bundle_nodes"][0]]["addr"])
        port = cw._run(cw._client_for_worker(addr0).call(
            "probe_free_port")).result()
        coord = f"{addr0[0]}:{port}"

        actor_cls = ray_tpu.remote(TrainWorker)
        workers = []
        for rank in range(n):
            env: Dict[str, Optional[str]] = dict(self._worker_env)
            env["RAY_TPU_TRAIN_COORD"] = coord
            env["RAY_TPU_TRAIN_RANK"] = str(rank)
            env["RAY_TPU_TRAIN_WORLD"] = str(n)
            opts = dict(
                placement_group=pg,
                placement_group_bundle_index=rank,
                runtime_env={"env_vars": env},
                max_restarts=0,  # restarts are group-level, not per-worker
            )
            if self._scaling.use_tpu:
                opts["num_tpus"] = float(self._scaling.chips_per_worker or 1)
            workers.append(actor_cls.options(**opts).remote())
        return workers

    def _teardown(self, pg, workers) -> None:
        for w in workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        # Split coordinators are per-attempt: kill them or each restart
        # leaks a worker process (and the blocks its parked streaming
        # tasks pin in the object store).
        for coord in getattr(self, "_coordinators", []):
            try:
                ray_tpu.kill(coord)
            except Exception:
                pass
        self._coordinators = []
        try:
            ray_tpu.remove_placement_group(pg)
        except Exception:
            pass

    # -- control loop ----------------------------------------------------
    def run(self) -> Result:
        max_failures = self._run_cfg.failure_config.max_failures
        attempt = 0
        last_error: Optional[BaseException] = None
        while max_failures == -1 or attempt <= max_failures:
            if attempt > 0:
                logger.info("restarting worker group (attempt %d/%s)",
                            attempt, max_failures)
            try:
                result = self._run_attempt()
                result.metrics_history = self._metrics_history
                result.checkpoint = self._latest_checkpoint
                return result
            except _ResizeRequested as r:
                # Elastic resize is PROGRESS, not failure: re-gang at the
                # new world size from the latest checkpoint without
                # burning a failure budget (reference:
                # controller.py:171 _execute_resize_decision).
                logger.info("elastic resize: %d -> %d workers",
                            self._world, r.target)
                self._pre_resize_world = self._world
                self._world = r.target
            except TrainingFailedError as e:
                if self._pre_resize_world is not None:
                    # The resized gang never became schedulable/healthy:
                    # roll back to the size that WAS working instead of
                    # burning the failure budget on an optimistic target.
                    logger.warning(
                        "resize to %d failed (%s); rolling back to %d",
                        self._world, e, self._pre_resize_world)
                    self._failed_resize_target = self._world
                    self._resize_backoff_until = time.monotonic() + 60.0
                    self._world = self._pre_resize_world
                    self._pre_resize_world = None
                    continue
                last_error = e
                attempt += 1
        return Result(metrics=(self._metrics_history[-1]
                               if self._metrics_history else {}),
                      metrics_history=self._metrics_history,
                      checkpoint=self._latest_checkpoint, error=last_error)

    def _maybe_request_resize(self) -> None:
        """Poll-loop hook: ask the policy for a target world size; two
        consecutive IDENTICAL non-current answers trigger the resize
        (debounce against node-state flaps); a target that just failed
        to re-gang is backed off."""
        now = time.monotonic()
        if now - self._last_policy_check < 1.0:
            return
        self._last_policy_check = now
        try:
            target = self._policy.target_workers(
                self._world, ray_tpu.nodes(), self._scaling.bundle())
        except Exception:
            if not self._policy_err_logged:
                self._policy_err_logged = True
                logger.warning("scaling policy check failed (elastic "
                               "resize disabled until it recovers)",
                               exc_info=True)
            return
        self._policy_err_logged = False
        if target == self._world or target < 1 or (
                target == self._failed_resize_target
                and now < self._resize_backoff_until):
            self._resize_pending = 0
            self._resize_target = None
            return
        if target != self._resize_target:
            self._resize_target = target
            self._resize_pending = 1
            return
        self._resize_pending += 1
        if self._resize_pending >= 2:
            self._resize_pending = 0
            self._resize_target = None
            raise _ResizeRequested(target)

    def _run_attempt(self) -> Result:
        # Attempt-start policy check (no debounce): after a FAILURE the
        # poll loop never saw the capacity change — a node loss must
        # shrink the re-gang here instead of wedging on an unreservable
        # world size (the healthy-path growth stays debounced in
        # _maybe_request_resize).
        try:
            target = self._policy.target_workers(
                self._world, ray_tpu.nodes(), self._scaling.bundle())
            if (target >= 1 and target != self._world
                    and not (target == self._failed_resize_target
                             and time.monotonic()
                             < self._resize_backoff_until)):
                logger.info("attempt-start resize: %d -> %d workers",
                            self._world, target)
                self._world = target
        except Exception:
            pass
        n = self._world
        pg = ray_tpu.placement_group(
            [self._scaling.bundle() for _ in range(n)],
            strategy=self._scaling.placement_strategy)
        workers: list = []
        try:
            workers = self._make_group(pg, n)
            shards = self._make_shards(n)
            starts = [
                w.start.remote(
                    self._fn_blob, self._config,
                    self._run_cfg.name, self._run_cfg.storage_path,
                    self._latest_checkpoint,
                    cloudpickle.dumps(shards[rank]))
                for rank, w in enumerate(workers)]
            ray_tpu.get(starts, timeout=120)
            # The (possibly resized) gang is live: later failures are
            # real failures, not a bad resize target.
            self._pre_resize_world = None
            return self._poll_until_done(workers)
        except (TrainingFailedError, _ResizeRequested):
            raise
        except Exception as e:
            raise TrainingFailedError(f"worker group failed: {e!r}") from e
        finally:
            self._teardown(pg, workers)

    def _ingest_polls(self, polls) -> None:
        """Fold workers' reported (metrics, checkpoint) pairs into the
        run state (rank 0's metrics are the history)."""
        for rank, p in enumerate(polls):
            for metrics, ckpt in p["reported"]:
                if rank == 0:
                    self._metrics_history.append(metrics)
                if ckpt is not None:
                    # Ranks drain independently: only advance, never
                    # regress, the resume point.
                    new_step = getattr(ckpt, "step", None)
                    cur_step = getattr(self._latest_checkpoint, "step",
                                       None)
                    if (new_step is None or cur_step is None
                            or new_step >= cur_step):
                        self._latest_checkpoint = ckpt
                    if rank == 0 and self._ckpt_manager is not None:
                        from ray_tpu.train.checkpointing import Checkpoint
                        if isinstance(ckpt, Checkpoint):
                            self._ckpt_manager.register(ckpt)

    def _poll_until_done(self, workers) -> Result:
        poll_period = 0.2
        while True:
            try:
                polls = ray_tpu.get([w.poll.remote() for w in workers],
                                    timeout=60)
            except Exception as e:  # worker/actor death mid-training
                raise TrainingFailedError(
                    f"worker poll failed: {e!r}") from e
            self._ingest_polls(polls)
            errs = [(i, p["error"]) for i, p in enumerate(polls)
                    if p["status"] == "error"]
            if errs:
                rank, tb = errs[0]
                raise TrainingFailedError(
                    f"train loop failed on rank {rank}:\n{tb}")
            if all(p["status"] == "finished" for p in polls):
                final = self._metrics_history[-1] \
                    if self._metrics_history else {}
                return Result(metrics=final)
            try:
                self._maybe_request_resize()
            except _ResizeRequested:
                # A report can race the resize decision (the worker
                # reported between our poll and the policy check): drain
                # once more so the pre-resize history survives the
                # attempt restart.
                try:
                    self._ingest_polls(ray_tpu.get(
                        [w.poll.remote() for w in workers], timeout=30))
                except Exception:
                    pass
                raise
            time.sleep(poll_period)
            poll_period = min(poll_period * 1.5, 2.0)
