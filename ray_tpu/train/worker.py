"""TrainWorker — the per-process training actor.

Analogue of the reference's Train v2 worker (reference:
python/ray/train/v2/_internal/execution/worker_group/worker.py +
thread_runner.py — run the user loop in a thread, poll status), with the
JAX backend bolted in: ``start()`` initializes ``jax.distributed`` from the
env the controller set at actor spawn (reference:
python/ray/train/v2/jax/config.py _JaxBackend.on_start).

JAX env (JAX_PLATFORMS, XLA_FLAGS, TPU_VISIBLE_CHIPS, coordinator vars) is
frozen at interpreter start, which is why the controller passes it through
``runtime_env={"env_vars": ...}`` rather than setting it here.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu.train import session as _session_mod


class TrainWorker:
    """Gang-scheduled by the TrainController; one JAX process per actor."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._session: Optional[_session_mod._Session] = None
        self._jax_initialized = False

    # -- backend ---------------------------------------------------------
    def _init_jax_distributed(self) -> Dict[str, Any]:
        coord = os.environ.get("RAY_TPU_TRAIN_COORD", "")
        world = int(os.environ.get("RAY_TPU_TRAIN_WORLD", "1"))
        rank = int(os.environ.get("RAY_TPU_TRAIN_RANK", "0"))
        import jax
        if world > 1 and coord and not self._jax_initialized:
            # Blocks until all `world` processes join the coordinator
            # (worker 0 hosts it — reference: v2/jax/config.py on_start).
            jax.distributed.initialize(coord, num_processes=world,
                                       process_id=rank)
            self._jax_initialized = True
        return {"rank": rank, "world": world,
                "local_devices": jax.local_device_count(),
                "global_devices": jax.device_count()}

    # -- controller API --------------------------------------------------
    def start(self, fn_blob: bytes, config: Optional[dict],
              experiment_name: str = "", storage_path: str = "",
              restored_checkpoint: Any = None,
              shards_blob: Optional[bytes] = None) -> None:
        """Launch the user train loop in a thread and return immediately
        (the actor stays responsive to poll())."""
        assert self._thread is None, "start() called twice"
        rank = int(os.environ.get("RAY_TPU_TRAIN_RANK", "0"))
        world = int(os.environ.get("RAY_TPU_TRAIN_WORLD", "1"))
        shards = cloudpickle.loads(shards_blob) if shards_blob else {}
        ctx = _session_mod.TrainContext(rank, world, experiment_name,
                                        storage_path, restored_checkpoint,
                                        dataset_shards=shards)
        self._session = _session_mod._start_session(ctx)
        fn = cloudpickle.loads(fn_blob)

        def _run():
            try:
                self._init_jax_distributed()
                if config is None:
                    fn()
                else:
                    fn(config)
            except BaseException:
                self._session.error = traceback.format_exc()
            finally:
                self._session.finished = True

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="train-loop")
        self._thread.start()

    def poll(self) -> dict:
        """Drain new report()s + liveness/status (reference:
        controller.py _poll_workers)."""
        s = self._session
        if s is None:
            return {"status": "idle", "reported": []}
        reported = s.drain()
        if s.error is not None:
            return {"status": "error", "error": s.error, "reported": reported}
        if s.finished:
            return {"status": "finished", "reported": reported}
        return {"status": "running", "reported": reported}

    def jax_info(self) -> dict:
        import jax
        return {"backend": jax.default_backend(),
                "local_devices": jax.local_device_count(),
                "global_devices": jax.device_count()}

    def shutdown_worker(self) -> str:
        return "ok"
