"""Autoscaler — demand-driven node scale-up/down over a NodeProvider.

Analogue of the reference's autoscaler v2 (reference: python/ray/
autoscaler/v2/autoscaler.py Autoscaler.update -> scheduler.py
ResourceDemandScheduler.schedule bin-packing -> instance_manager/
reconciling cloud instances; demand aggregated GCS-side by
gcs_autoscaler_state_manager.cc). Slimmed loop:

  demand  = pending actors + pending PG bundles + recent infeasible leases
  supply  = alive nodes' total resources
  scale UP when demand doesn't bin-pack into idle supply (one node per
  tick, up to max_nodes); scale DOWN nodes fully idle past
  idle_timeout_s (down to min_nodes).

NodeProvider is the cloud seam (reference: autoscaler node providers);
LocalNodeProvider spawns agent processes on this host — the fake-multinode
analogue used by tests and single-host elasticity.
"""

from __future__ import annotations

import subprocess
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core.common import resources_fit, resources_sub
from ray_tpu.utils import get_logger

logger = get_logger("autoscaler")


class NodeProvider:
    """Cloud seam: create/terminate worker nodes.

    node_port(handle) is the scale-down correlation key: the agent RPC
    port of the launched node (the autoscaler only terminates nodes it
    can correlate to a handle; returning None opts a node out of
    scale-down)."""

    def create_node(self, resources: Dict[str, float]) -> Any:
        raise NotImplementedError

    def terminate_node(self, handle: Any) -> None:
        raise NotImplementedError

    def node_port(self, handle: Any) -> Optional[int]:
        return None

    def handle_failed(self, handle: Any) -> bool:
        """True if this launch is known-dead (will never register) — the
        autoscaler drops such handles and can retry the scale-up."""
        return False


class LocalNodeProvider(NodeProvider):
    """Spawns node agents on this host (reference:
    autoscaler/_private/fake_multi_node)."""

    def __init__(self, controller_addr, session_dir: Optional[str] = None):
        from ray_tpu.core.node import make_session_dir
        self._controller_addr = tuple(controller_addr)
        self._session_dir = session_dir or make_session_dir()

    def create_node(self, resources: Dict[str, float]):
        from ray_tpu.core.node import start_agent
        proc, port = start_agent(self._controller_addr, self._session_dir,
                                 dict(resources))
        return {"proc": proc, "port": port}

    def node_port(self, handle) -> Optional[int]:
        return handle["port"]

    def terminate_node(self, handle) -> None:
        proc = handle["proc"] if isinstance(handle, dict) else handle
        if isinstance(proc, subprocess.Popen) and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()


class TPUPodProvider(NodeProvider):
    """TPU-slice provider: launches whole TPU pod slices as cluster
    nodes (reference: python/ray/autoscaler/_private/gcp/ node provider +
    SURVEY phase 12's GKE/TPU-pod target).

    Cloud access rides COMMAND TEMPLATES (gcloud by default) instead of
    a baked-in SDK — the same seam the reference fills per cloud. Each
    template is a list of argv strings formatted with {name},
    {accelerator_type}, {zone}, plus {controller} and {agent_port} for
    the startup script. Defaults target `gcloud compute tpus tpu-vm`;
    tests substitute stub commands.

        provider = TPUPodProvider(
            zone="us-central2-b", accelerator_type="v5litepod-8",
            controller_addr=("10.0.0.2", 7001))
        Autoscaler(provider, node_resources={"TPU": 8, "CPU": 64}, ...)
    """

    AGENT_PORT = 7011  # fixed agent port on every slice (correlation key)

    def __init__(self, *, zone: str, accelerator_type: str,
                 controller_addr, runtime_version: str = "tpu-ubuntu2204-base",
                 name_prefix: str = "raytpu",
                 create_cmd: Optional[List[str]] = None,
                 delete_cmd: Optional[List[str]] = None):
        self._zone = zone
        self._acc = accelerator_type
        self._controller = tuple(controller_addr)
        self._prefix = name_prefix
        self._seq = 0
        self._create_cmd = create_cmd or [
            "gcloud", "compute", "tpus", "tpu-vm", "create", "{name}",
            "--zone", "{zone}", "--accelerator-type", "{accelerator_type}",
            "--version", runtime_version,
            "--metadata", ("startup-script=pip install ray_tpu && "
                           "python -m ray_tpu.cli start "
                           "--address {controller} --port {agent_port}"),
        ]
        self._delete_cmd = delete_cmd or [
            "gcloud", "compute", "tpus", "tpu-vm", "delete", "{name}",
            "--zone", "{zone}", "--quiet",
        ]

    def _fmt(self, template: List[str], name: str) -> List[str]:
        # Placeholder-only substitution (str.replace, NOT str.format):
        # user templates legitimately carry literal braces (inline JSON,
        # bash ${VAR} in startup scripts).
        subs = {
            "{name}": name, "{zone}": self._zone,
            "{accelerator_type}": self._acc,
            "{controller}": f"{self._controller[0]}:{self._controller[1]}",
            "{agent_port}": str(self.AGENT_PORT),
        }
        out = []
        for part in template:
            for token, value in subs.items():
                part = part.replace(token, value)
            out.append(part)
        return out

    def _launch(self, cmd: List[str], what: str,
                handle: Optional[dict] = None):
        """Start the cloud CLI WITHOUT blocking the reconcile thread
        (slice create/delete takes minutes; the reference's instance
        manager is similarly asynchronous). An immediately-failing
        command (bad binary/flags) still raises here; a background
        reaper wait()s the child (no zombies), drops the log on success,
        and marks `handle['failed']` on a late nonzero exit (quota,
        capacity, auth) so the autoscaler's reconcile can drop the
        handle and retry instead of waiting forever on a node that will
        never register."""
        import tempfile
        import threading
        log = tempfile.NamedTemporaryFile(
            mode="w+", prefix=f"raytpu-{what}-", suffix=".log",
            delete=False)
        try:
            proc = subprocess.Popen(cmd, stdout=log,
                                    stderr=subprocess.STDOUT)
            time.sleep(0.2)
            rc = proc.poll()
            if rc is not None and rc != 0:
                log.seek(0)
                tail = log.read()[-500:]
                proc.wait()
                raise RuntimeError(
                    f"TPU slice {what} failed fast "
                    f"({' '.join(cmd[:6])}...): {tail}")
        finally:
            log.close()

        def reap():
            rc = proc.wait()
            if rc == 0:
                try:
                    import os
                    os.unlink(log.name)
                except OSError:
                    pass
            else:
                if handle is not None:
                    handle["failed"] = True
                logger.warning("TPU slice %s exited rc=%d (log: %s)",
                               what, rc, log.name)

        threading.Thread(target=reap, daemon=True,
                         name=f"tpu-{what}-reaper").start()
        return proc

    def create_node(self, resources: Dict[str, float]):
        self._seq += 1
        name = f"{self._prefix}-{self._seq}"
        handle = {"name": name, "port": self.AGENT_PORT, "failed": False}
        handle["proc"] = self._launch(self._fmt(self._create_cmd, name),
                                      "create", handle=handle)
        logger.info("creating TPU slice %s (%s in %s)", name, self._acc,
                    self._zone)
        return handle

    def node_port(self, handle) -> Optional[int]:
        return handle.get("port")

    def handle_failed(self, handle) -> bool:
        return bool(handle.get("failed"))

    def terminate_node(self, handle) -> None:
        try:
            self._launch(self._fmt(self._delete_cmd, handle["name"]),
                         "delete")
        except RuntimeError as e:
            logger.warning("%s", e)


class Autoscaler:
    def __init__(self, provider: NodeProvider, *,
                 node_resources: Dict[str, float],
                 min_nodes: int = 0, max_nodes: int = 4,
                 idle_timeout_s: float = 30.0,
                 update_period_s: float = 1.0,
                 p99_scale_up_ms: Optional[float] = None):
        """node_resources: the shape of one launchable node (homogeneous
        node groups; the reference's multi-node-type scheduler is the
        extension point).

        p99_scale_up_ms: graftpulse latency signal — scale up when the
        cluster-wide native-op p99 exceeds this many milliseconds while
        leases are queued, even with zero pending demand (the reference
        scales on request counts only). Default from the
        autoscale_p99_ms config flag; 0/None disables."""
        from ray_tpu import api
        from ray_tpu.utils.config import GlobalConfig
        self._cw = api._cw()
        self._provider = provider
        self._node_resources = dict(node_resources)
        self._min = min_nodes
        self._max = max_nodes
        self._idle_timeout = idle_timeout_s
        self._period = update_period_s
        if p99_scale_up_ms is None:
            p99_scale_up_ms = float(GlobalConfig.autoscale_p99_ms)
        self._p99_ms = float(p99_scale_up_ms or 0.0)
        self._launched: List[Any] = []   # provider handles
        self._idle_since: Dict[bytes, float] = {}
        self._running = False
        self._thread: Optional[threading.Thread] = None
        # Exponential backoff after failed launches, so a persistent
        # cloud failure (quota exhausted) doesn't become an endless
        # create+delete CLI pair every tick.
        self._failure_backoff_s = 0.0
        self._next_launch_at = 0.0

    # -- scheduling math -------------------------------------------------
    @staticmethod
    def _bin_packs(demands: List[Dict[str, float]],
                   free: List[Dict[str, float]]) -> List[Dict[str, float]]:
        """First-fit-decreasing: returns the demands that DON'T fit."""
        free = [dict(f) for f in free]
        unmet = []
        for d in sorted(demands, key=lambda d: -sum(d.values())):
            for f in free:
                if resources_fit(f, d):
                    resources_sub(f, d)
                    break
            else:
                unmet.append(d)
        return unmet

    def _state(self) -> dict:
        return self._cw._run(self._cw.controller.call(
            "autoscaler_state")).result(30)

    def update(self) -> Optional[str]:
        """One reconcile tick; returns the action taken (for tests)."""
        # Drop launches the provider knows are dead (create failed after
        # the fail-fast window) so their capacity doesn't suppress the
        # next scale-up forever.
        dead = [h for h in self._launched
                if self._provider.handle_failed(h)]
        for h in dead:
            logger.warning("dropping failed node launch %s",
                           h.get("name", h) if isinstance(h, dict) else h)
            self._launched.remove(h)
            # Best-effort terminate: a late create failure may still have
            # provisioned the cloud resource (e.g. the VM came up but the
            # startup script failed) — never leak it. Providers treat
            # deleting a nonexistent node as a quiet no-op.
            try:
                self._provider.terminate_node(h)
            except Exception as e:
                logger.warning("terminate of failed launch: %r", e)
        if dead:
            self._failure_backoff_s = min(
                300.0, max(2.0, self._failure_backoff_s * 2))
            self._next_launch_at = time.time() + self._failure_backoff_s
            logger.warning("launch backoff %.0fs after failure",
                           self._failure_backoff_s)
        st = self._state()
        alive = [n for n in st["nodes"] if n["state"] == "ALIVE"]
        # Correlate launched handles with registered nodes by agent port
        # so scale-down terminates the node it drained, never a random
        # launch (and never a node someone else started).
        node_addr_ports = {}
        full = self._cw._run(
            self._cw.controller.call("get_nodes")).result(30)
        for n in full:
            node_addr_ports[n["node_id"]] = n["addr"][1]
        handles_by_port = {}
        for h in self._launched:
            port = self._provider.node_port(h)
            if port is not None:
                handles_by_port[port] = h
        # A launched node registering ALIVE proves the provider works
        # again: clear the failure backoff.
        if self._failure_backoff_s and any(
                node_addr_ports.get(n["node_id"]) in handles_by_port
                for n in alive):
            self._failure_backoff_s = 0.0
            self._next_launch_at = 0.0
        demands = (st["pending_actors"] + st["pending_pg_bundles"]
                   + st["infeasible"])
        demands = [d for d in demands if d]
        unmet = self._bin_packs(demands, [n["available"] for n in alive])
        # graftpulse latency signal: the controller folds every node's
        # pulse histograms into a cluster p99 per native op; when the
        # worst op's p99 blows the budget WHILE leases are queued, the
        # cluster is saturated even if nothing is pending-infeasible —
        # scale up on latency alone (request counts can be flat).
        p99_budget_ms = getattr(self, "_p99_ms", 0.0)
        p99_ms = float(st.get("native_p99_ms") or 0.0)
        queue_depth = int(st.get("queue_depth") or 0)
        latency_pressure = (p99_budget_ms > 0 and p99_ms > p99_budget_ms
                            and queue_depth > 0)
        if (unmet or latency_pressure) and len(alive) < self._max \
                and time.time() >= self._next_launch_at:
            # One node per tick (the reference batches; conservative here).
            fits_new = self._bin_packs(unmet, [self._node_resources])
            if len(fits_new) < len(unmet) or (latency_pressure
                                              and not unmet):
                if unmet:
                    logger.info("scaling UP (+1 node) for %d unmet "
                                "demands", len(unmet))
                else:
                    logger.info("scaling UP (+1 node): native p99 "
                                "%.1fms > %.1fms with %d leases queued",
                                p99_ms, p99_budget_ms, queue_depth)
                self._launched.append(
                    self._provider.create_node(self._node_resources))
                return "up"
            logger.warning("demand %s does not fit node shape %s",
                           unmet[:3], self._node_resources)
        # Scale down: nodes with zero usage for idle_timeout_s.
        if len(alive) > self._min and len(self._launched) > 0:
            now = time.time()
            for n in alive:
                nid = n["node_id"]
                busy = any(n["available"].get(k, 0) < v - 1e-9
                           for k, v in n["total"].items())
                if busy or demands or latency_pressure:
                    self._idle_since.pop(nid, None)
                    continue
                handle = handles_by_port.get(node_addr_ports.get(nid))
                if handle is None:
                    continue  # not one of ours: never terminate it
                first = self._idle_since.setdefault(nid, now)
                if now - first > self._idle_timeout:
                    # Drain via the controller, terminate via provider.
                    try:
                        self._cw._run(self._cw.controller.call(
                            "drain_node", nid)).result(30)
                    except Exception:
                        pass
                    self._launched.remove(handle)
                    self._provider.terminate_node(handle)
                    self._idle_since.pop(nid, None)
                    logger.info("scaled DOWN one idle node")
                    return "down"
        return None

    # -- loop ------------------------------------------------------------
    def start(self) -> "Autoscaler":
        self._running = True

        def loop():
            while self._running:
                try:
                    self.update()
                except Exception as e:
                    logger.debug("autoscaler tick failed: %r", e)
                time.sleep(self._period)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=5)
        for handle in self._launched:
            self._provider.terminate_node(handle)
        self._launched.clear()
