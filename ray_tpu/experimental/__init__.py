"""Experimental accelerator-plane features: device channels, DAG tensor
transport (reference: python/ray/experimental/channel/)."""

from ray_tpu.experimental.channel import DeviceChannel  # noqa: F401
