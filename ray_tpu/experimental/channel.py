"""Device channels: single-writer, multi-reader rings with
acquire/release backpressure over the device transfer plane.

Analogue of the reference's experimental mutable-object channels
(src/ray/core_worker/experimental_mutable_object_manager.h:44 — a ring of
mutable buffers with acquire/release; NCCL variants in
python/ray/experimental/channel/torch_tensor_accelerator_channel.py:49).
TPU redesign: the PJRT transfer plane is pull-based, so a "slot" is a
staged pull ticket. The writer publishes item n to every reader (tiny
control RPC; the tensor moves device-to-device on the reader's pull) and
blocks once `capacity` items are unreleased — the same backpressure
contract as the reference's ring, without a pinned mutable buffer.

    ch = DeviceChannel.create([actor_a], capacity=2)   # anywhere
    # writer process:            reader process:
    ch.write(jax_array)          val = ch.read()        # pull + release
    ch.write(jax_array2)         val2 = ch.read()

Handles pickle freely; per-process state initializes lazily, so the same
handle object works on the writer, every reader, and the driver.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional

# Per-process writer/reader state, keyed by channel id.
_writer_states: Dict[bytes, "_WriterState"] = {}
_reader_states: Dict[bytes, "_ReaderState"] = {}
_state_lock = threading.Lock()


class _WriterState:
    def __init__(self):
        self.seq = 0


class _ReaderState:
    def __init__(self):
        self.pending_release: Optional[int] = None
        self.pending_writer: Optional[tuple] = None


def _resolve_reader_addr(reader) -> tuple:
    """An actor handle -> its worker address; None -> this process."""
    from ray_tpu.core.ref import ActorHandle, get_core_worker

    cw = get_core_worker()
    if reader is None:
        return tuple(cw.address)
    if isinstance(reader, ActorHandle):
        client = cw._run(
            cw._actor_client(reader.actor_id.binary())).result(30)
        return tuple(client._address)
    return tuple(reader)  # already an address


class DeviceChannel:
    """Picklable channel handle. Exactly one process writes; each address
    in `reader_addrs` reads."""

    def __init__(self, channel_id: bytes, reader_addrs: List[tuple],
                 capacity: int):
        self.channel_id = channel_id
        self.reader_addrs = [tuple(a) for a in reader_addrs]
        self.capacity = capacity

    @staticmethod
    def create(readers: List[Any], capacity: int = 2) -> "DeviceChannel":
        """readers: actor handles (or None for the driver/this process).
        Callable from any process in the cluster."""
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        addrs = [_resolve_reader_addr(r) for r in readers]
        if not addrs:
            raise ValueError("a channel needs at least one reader")
        if len(set(addrs)) != len(addrs):
            # Acks key by reader address; duplicates would make the
            # writer's release barrier unsatisfiable (permanent timeout).
            raise ValueError("duplicate reader processes in channel")
        return DeviceChannel(os.urandom(16), addrs, capacity)

    def __reduce__(self):
        return (DeviceChannel, (self.channel_id, self.reader_addrs,
                                self.capacity))

    # ------------------------------------------------------------------
    # writer side
    # ------------------------------------------------------------------
    def write(self, value: Any, timeout: Optional[float] = 60.0) -> None:
        """Publish one array. Blocks (acquire) while `capacity` items are
        outstanding, until every reader releases the oldest."""
        from ray_tpu.core.ref import get_core_worker
        from ray_tpu.experimental.device_plane import DevicePlane

        cw = get_core_worker()
        with _state_lock:
            st = _writer_states.setdefault(self.channel_id, _WriterState())
        n = st.seq + 1
        if n > self.capacity:
            # Acquire BEFORE committing the seq: a timed-out write leaves
            # the ring unchanged and is safely retryable.
            cw._run(cw.channel_wait_acks(
                self.channel_id, n - self.capacity,
                len(self.reader_addrs), timeout)).result()
        st.seq = n
        plane = DevicePlane.get()
        # Reform once (a sharded value gathers to one device here);
        # staging per reader below is then copy-free.
        value = plane._pullable(value)
        for reader in self.reader_addrs:
            # One staged ticket per reader: each pull consumes a ticket.
            addr, uuid, descs = plane.stage([value])
            if reader == tuple(cw.address):
                cw._run(cw.channel_notify(
                    self.channel_id, n, cw.address, addr, uuid,
                    descs)).result(timeout)
            else:
                client = cw._client_for_worker(reader)
                cw._run(client.call(
                    "channel_notify", self.channel_id, n, cw.address,
                    addr, uuid, descs)).result(timeout)

    # ------------------------------------------------------------------
    # reader side
    # ------------------------------------------------------------------
    def read(self, timeout: Optional[float] = 60.0,
             release: bool = True) -> Any:
        """Next item (acquire): waits for the writer's publish, pulls the
        tensor device-to-device, and (by default) releases the slot. Pass
        release=False to hold the slot until an explicit release() — the
        writer's ring stays blocked meanwhile."""
        from ray_tpu.core.ref import get_core_worker
        from ray_tpu.experimental.device_plane import DevicePlane

        cw = get_core_worker()
        with _state_lock:
            rst = _reader_states.setdefault(self.channel_id,
                                            _ReaderState())
        if rst.pending_release is not None:
            self.release()
        seq, writer_addr, addr, uuid, descs = cw._run(
            cw.channel_next(self.channel_id, timeout)).result()
        value = DevicePlane.get().pull(addr, uuid, descs)[0]
        rst.pending_release = seq
        rst.pending_writer = writer_addr
        if release:
            self.release()
        return value

    def release(self) -> None:
        """Release the last-read slot back to the writer (idempotent)."""
        from ray_tpu.core.ref import get_core_worker

        rst = _reader_states.get(self.channel_id)
        if rst is None or rst.pending_release is None:
            return
        cw = get_core_worker()
        seq, writer_addr = rst.pending_release, rst.pending_writer
        rst.pending_release = rst.pending_writer = None
        if tuple(writer_addr) == tuple(cw.address):
            cw._run(cw.channel_release(
                self.channel_id, cw.address, seq)).result(30)
        else:
            client = cw._client_for_worker(tuple(writer_addr))
            cw._run(client.call("channel_release", self.channel_id,
                                cw.address, seq)).result(30)

    # ------------------------------------------------------------------
    def close(self) -> None:
        """Drop local channel state (both roles; idempotent)."""
        from ray_tpu.core.ref import get_core_worker

        with _state_lock:
            _writer_states.pop(self.channel_id, None)
            _reader_states.pop(self.channel_id, None)
        try:
            get_core_worker().drop_channel(self.channel_id)
        except Exception:
            pass
