"""The per-process device-transfer plane.

Accelerator-plane data transport between processes WITHOUT a host pickle
round-trip: arrays move device-to-device through the JAX/PJRT transfer
server (`jax.experimental.transfer` — DMA over ICI/DCN on TPU, a bulk
socket transport on CPU). The control plane (who pulls what, from where)
stays on the ordinary RPC layer; only tiny (address, uuid, aval) tuples
cross it.

Analogue of the reference's accelerator channel transports
(python/ray/experimental/channel/torch_tensor_accelerator_channel.py:49 —
NCCL send/recv backing GPU-to-GPU channels; ours is pull-based because the
PJRT transfer server is pull-based).

One `DevicePlane` per process, created lazily on first use so processes
that never touch device objects never pay for a server.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

_lock = threading.Lock()
_instance: Optional["DevicePlane"] = None


def _host_ip() -> str:
    """The IP peers should dial. Single-host default; multi-host nodes
    export their routable address via RAY_TPU_NODE_IP."""
    import os
    return os.environ.get("RAY_TPU_NODE_IP", "127.0.0.1")


class DevicePlane:
    """Wraps one PJRT transfer server + a connection cache."""

    def __init__(self):
        import jax
        import jax.extend as jex
        from jax.experimental import transfer

        host = _host_ip()
        # Socket bulk transports (not the same-process-only local
        # transport) so cross-process pulls work; the PJRT plugin picks
        # DMA transports on real TPU slices.
        self._server = transfer.start_transfer_server(
            jex.backend.get_backend(), "[::]:0", [f"{host}:0"])
        self.address: str = self._server.address().replace("[::]", host)
        self._conns: Dict[str, Any] = {}
        self._next_uuid = (id(self) & 0xFFFF) << 32 | 1
        self._uuid_lock = threading.Lock()
        # Stats (tests assert transfers rode the device plane).
        self.staged = 0
        self.pulls = 0

    @staticmethod
    def get() -> "DevicePlane":
        global _instance
        with _lock:
            if _instance is None:
                _instance = DevicePlane()
            return _instance

    @staticmethod
    def maybe() -> Optional["DevicePlane"]:
        """The plane if it was ever started in this process."""
        return _instance

    # ------------------------------------------------------------------
    def _uuid(self) -> int:
        with self._uuid_lock:
            u = self._next_uuid
            self._next_uuid += 1
            return u

    @staticmethod
    def _pullable(arr: Any) -> Any:
        """Reform to a single-device array when needed: a cross-process
        pull targets the reader's (single) local placement, so gather a
        sharded source on-device first (device-to-device, never host)."""
        import jax

        if isinstance(arr, jax.Array) and len(arr.sharding.device_set) > 1:
            dev = next(iter(arr.sharding.device_set))
            return jax.device_put(
                arr, jax.sharding.SingleDeviceSharding(dev))
        return arr

    def stage(self, arrays: List[Any]) -> Tuple[str, int, list]:
        """Make arrays pullable by ONE remote peer. Returns
        (address, uuid, aval_descs) — the tiny control-plane tuple.

        Constraint: the PJRT transfer server exposes no unstage/cancel,
        so a ticket whose peer never pulls (peer death, failed pull that
        fell back to host bytes) pins its array until the server is
        dropped — callers should treat staging as committed-to-a-pull."""
        import jax
        import numpy as np

        staged = []
        descs = []
        for a in arrays:
            if not isinstance(a, jax.Array):
                a = jax.device_put(np.asarray(a))
            a = self._pullable(a)
            staged.append(a)
            descs.append((tuple(a.shape), str(a.dtype)))
        uuid = self._uuid()
        self._server.await_pull(uuid, staged)
        self.staged += 1
        return self.address, uuid, descs

    def pull(self, address: str, uuid: int, descs: list) -> List[Any]:
        """Pull arrays staged by a peer, onto this process's devices."""
        import jax
        import jax.numpy as jnp

        conn = self._conns.get(address)
        if conn is None:
            conn = self._server.connect(address)
            self._conns[address] = conn
        dev = jax.devices()[0]
        sharding = jax.sharding.SingleDeviceSharding(dev)
        specs = [jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype),
                                      sharding=sharding)
                 for shape, dtype in descs]
        out = conn.pull(uuid, specs)
        self.pulls += 1
        return list(out)
