"""Tuner + TuneController — the experiment driver.

Analogue of the reference's Tuner/TuneController (reference:
python/ray/tune/tuner.py Tuner, tune/execution/tune_controller.py:68 —
manage trial actors up to a concurrency cap, feed results to the
scheduler, collect a ResultGrid).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler
from ray_tpu.tune.search import generate_variants
from ray_tpu.tune.trial import (ERROR, PENDING, RUNNING, STOPPED,
                                TERMINATED, TrialRunner)
from ray_tpu.utils import get_logger

logger = get_logger("tune")


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "min"                 # or "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Any = None             # FIFO | ASHA | PBT
    search_alg: Any = None            # Searcher (suggest/on_trial_complete)
    seed: Optional[int] = None
    resources_per_trial: Dict[str, float] = field(default_factory=dict)


@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any] = field(default_factory=dict)  # last report
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    status: str = PENDING
    error: Optional[str] = None
    iterations: int = 0


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __iter__(self):
        return iter(self._results)

    def __len__(self):
        return len(self._results)

    @property
    def results(self) -> List[TrialResult]:
        return list(self._results)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        assert metric, "a metric is required to rank results"
        scored = [r for r in self._results if metric in r.metrics]
        if not scored:
            raise ValueError("no trial reported the metric "
                             f"{metric!r}")
        return (min if mode == "min" else max)(
            scored, key=lambda r: r.metrics[metric])

    def num_errors(self) -> int:
        return sum(1 for r in self._results if r.status == ERROR)


class Tuner:
    def __init__(self, trainable: Callable[[dict], Any], *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None):
        self._fn_blob = cloudpickle.dumps(trainable)
        self._space = param_space or {}
        self._cfg = tune_config or TuneConfig()

    def fit(self) -> ResultGrid:
        cfg = self._cfg
        scheduler = cfg.scheduler or FIFOScheduler()
        if getattr(scheduler, "metric", "x") is None:
            scheduler.metric = cfg.metric
        if cfg.search_alg is not None:
            # Searcher seam (reference: search/searcher.py): the search
            # algorithm proposes each trial's config.
            variants = []
            for i in range(cfg.num_samples):
                v = cfg.search_alg.suggest(f"trial_{i:05d}")
                if v is None:
                    break
                variants.append(v)
        else:
            variants = list(generate_variants(self._space, cfg.num_samples,
                                              cfg.seed))
        trials = [TrialResult(trial_id=f"trial_{i:05d}", config=v)
                  for i, v in enumerate(variants)]
        if hasattr(scheduler, "track"):  # PBT needs live configs
            for t in trials:
                scheduler.track(t.trial_id, t.config)
        pending = list(trials)
        running: Dict[str, Any] = {}   # trial_id -> actor handle
        stopping: set = set()
        actor_cls = ray_tpu.remote(TrialRunner)
        opts: Dict[str, Any] = {}
        if cfg.resources_per_trial:
            res = dict(cfg.resources_per_trial)
            if "CPU" in res:
                opts["num_cpus"] = res.pop("CPU")
            if "TPU" in res:
                opts["num_tpus"] = res.pop("TPU")
            if res:
                opts["resources"] = res
        if opts:
            actor_cls = actor_cls.options(**opts)

        by_id = {t.trial_id: t for t in trials}
        while pending or running:
            while pending and len(running) < cfg.max_concurrent_trials:
                t = pending.pop(0)
                t.status = RUNNING
                running[t.trial_id] = actor_cls.remote(self._fn_blob,
                                                       t.config)
            done: List[str] = []
            for tid, actor in running.items():
                t = by_id[tid]
                try:
                    p = ray_tpu.get(actor.poll.remote(), timeout=60)
                except Exception as e:
                    t.status = ERROR
                    t.error = f"trial actor died: {e!r}"
                    done.append(tid)
                    continue
                for m in p["reported"]:
                    t.metrics_history.append(m)
                    t.metrics = m
                t.iterations = p["iteration"]
                # The scheduler may rank on its OWN metric (e.g. ASHA on
                # accuracy while the tuner reports best-loss).
                metric = getattr(scheduler, "metric", None) or cfg.metric
                if metric and p["reported"] and tid not in stopping:
                    decision = CONTINUE
                    for i, m in enumerate(p["reported"]):
                        if metric in m:
                            it = (t.iterations - len(p["reported"]) + 1
                                  + i)
                            decision = scheduler.on_result(
                                tid, it, float(m[metric]))
                            if decision != CONTINUE:
                                break
                    if decision == STOP:
                        stopping.add(tid)
                        try:
                            actor.stop_trial.remote()
                        except Exception:
                            pass
                    elif isinstance(decision, tuple) \
                            and decision[0] == "EXPLOIT" \
                            and not p["finished"]:
                        # PBT: restart this trial from the source's
                        # checkpoint with the mutated config. A trial
                        # whose SAME poll already reported finished is
                        # past exploiting (the replacement would be
                        # killed by the done-handling below).
                        _, source_tid, new_config = decision
                        replaced = self._exploit(
                            actor_cls, running, by_id, tid, source_tid,
                            new_config)
                        if replaced is not None:
                            running[tid] = replaced
                if p["finished"]:
                    if p["error"]:
                        t.status = ERROR
                        t.error = p["error"]
                    else:
                        t.status = STOPPED if tid in stopping \
                            else TERMINATED
                    done.append(tid)
            for tid in done:
                actor = running.pop(tid)
                try:
                    ray_tpu.kill(actor)
                except Exception:
                    pass
            if running:
                time.sleep(0.2)
        if cfg.search_alg is not None:
            for t in trials:
                cfg.search_alg.on_trial_complete(
                    t.trial_id, t.metrics or None, error=t.status == ERROR)
        logger.info("tune finished: %d trials (%d errors)", len(trials),
                    sum(1 for t in trials if t.status == ERROR))
        return ResultGrid(trials, cfg.metric, cfg.mode)

    def _exploit(self, actor_cls, running, by_id, tid: str,
                 source_tid: str, new_config: dict):
        """PBT exploit: clone the source's checkpoint into a replacement
        actor for `tid` running `new_config` (reference: pbt.py
        _exploit — checkpoint copy + explore)."""
        source = running.get(source_tid)
        if source is None:
            return None  # source finished: skip this round
        try:
            ckpt = ray_tpu.get(source.get_trial_checkpoint.remote(),
                               timeout=60)
        except Exception:
            return None
        if ckpt is None:
            return None  # source never checkpointed: nothing to copy
        t = by_id[tid]
        old = running[tid]
        try:
            ray_tpu.kill(old)
        except Exception:
            pass
        t.config = dict(new_config)
        logger.info("PBT exploit: %s <- %s (config %s)", tid, source_tid,
                    new_config)
        return actor_cls.remote(self._fn_blob, dict(new_config),
                                restored=ckpt,
                                start_iteration=t.iterations)
