"""Tuner + TuneController — the experiment driver.

Analogue of the reference's Tuner/TuneController (reference:
python/ray/tune/tuner.py Tuner, tune/execution/tune_controller.py:68 —
manage trial actors up to a concurrency cap, feed results to the
scheduler, collect a ResultGrid).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler
from ray_tpu.tune.search import generate_variants
from ray_tpu.tune.trial import (ERROR, PENDING, RUNNING, STOPPED,
                                TERMINATED, TrialRunner)
from ray_tpu.utils import get_logger

logger = get_logger("tune")


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "min"                 # or "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Any = None             # FIFO | ASHA | BOHB | PBT
    search_alg: Any = None            # Searcher (suggest/on_trial_complete)
    seed: Optional[int] = None
    resources_per_trial: Dict[str, float] = field(default_factory=dict)
    # In-run trial fault tolerance (reference: FailureConfig.max_failures):
    # a trial whose actor dies (node loss) is rescheduled from its latest
    # controller-held checkpoint up to this many times.
    max_failures: int = 0


@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any] = field(default_factory=dict)  # last report
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    status: str = PENDING
    error: Optional[str] = None
    iterations: int = 0


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __iter__(self):
        return iter(self._results)

    def __len__(self):
        return len(self._results)

    @property
    def results(self) -> List[TrialResult]:
        return list(self._results)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        assert metric, "a metric is required to rank results"
        scored = [r for r in self._results if metric in r.metrics]
        if not scored:
            raise ValueError("no trial reported the metric "
                             f"{metric!r}")
        return (min if mode == "min" else max)(
            scored, key=lambda r: r.metrics[metric])

    def num_errors(self) -> int:
        return sum(1 for r in self._results if r.status == ERROR)


class Tuner:
    def __init__(self, trainable: Callable[[dict], Any], *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 storage_path: Optional[str] = None,
                 name: str = "tune_run"):
        """storage_path: persist experiment state (trial table + searcher
        state) under storage_path/name after every trial completion —
        Tuner.restore() resumes an interrupted run from it (reference:
        tune/execution/experiment_state.py + Tuner.restore)."""
        self._fn_blob = cloudpickle.dumps(trainable)
        self._space = param_space or {}
        self._cfg = tune_config or TuneConfig()
        self._storage = storage_path
        self._name = name
        self._restored_trials: List[TrialResult] = []
        self._restart_errored = False

    @property
    def experiment_path(self) -> Optional[str]:
        import os
        if not self._storage:
            return None
        return os.path.join(self._storage, self._name)

    @classmethod
    def restore(cls, path: str, trainable: Callable[[dict], Any], *,
                restart_errored: bool = False) -> "Tuner":
        """Resume an interrupted experiment from its state file
        (reference: Tuner.restore). Completed trials keep their results;
        pending/interrupted trials re-run; errored trials re-run only
        with restart_errored=True. The searcher resumes with everything
        it had learned."""
        import os
        import pickle
        with open(os.path.join(path, "experiment_state.pkl"), "rb") as f:
            st = pickle.load(f)
        tuner = cls(trainable,
                    param_space=cloudpickle.loads(st["space_blob"]),
                    tune_config=cloudpickle.loads(st["cfg_blob"]),
                    storage_path=os.path.dirname(os.path.abspath(path)),
                    name=os.path.basename(os.path.abspath(path)))
        tuner._restored_trials = [
            TrialResult(**rec) for rec in st["trials"]]
        tuner._restart_errored = restart_errored
        return tuner

    def _save_state(self, trials: List[TrialResult]) -> None:
        import os
        import pickle
        path = self.experiment_path
        if not path:
            return
        os.makedirs(path, exist_ok=True)
        # cfg_blob captures the searcher/scheduler OBJECTS — including
        # everything an adaptive searcher learned so far.
        st = {
            "space_blob": cloudpickle.dumps(self._space),
            "cfg_blob": cloudpickle.dumps(self._cfg),
            "trials": [{
                "trial_id": t.trial_id, "config": t.config,
                "metrics": t.metrics,
                "metrics_history": t.metrics_history,
                "status": t.status, "error": t.error,
                "iterations": t.iterations,
            } for t in trials],
        }
        tmp = os.path.join(path, "experiment_state.pkl.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(st, f)
        os.replace(tmp, os.path.join(path, "experiment_state.pkl"))

    def fit(self) -> ResultGrid:
        cfg = self._cfg
        scheduler = cfg.scheduler or FIFOScheduler()
        if getattr(scheduler, "metric", "x") is None:
            scheduler.metric = cfg.metric

        # Restored trial table: finished trials keep their results;
        # interrupted (and optionally errored) ones re-run.
        trials: List[TrialResult] = list(self._restored_trials)
        rerun: List[TrialResult] = []
        for t in trials:
            if t.status in (TERMINATED, STOPPED):
                continue
            if t.status == ERROR and not self._restart_errored:
                continue
            t.status = PENDING
            t.error = None
            t.metrics = {}
            t.metrics_history = []
            t.iterations = 0
            rerun.append(t)
        next_index = len(trials)

        # Variant source: the searcher proposes LAZILY (one config per
        # launch slot, so completions can inform later suggestions —
        # reference: SearchGenerator), the default generator is a
        # precomputed sequence.
        if cfg.search_alg is None:
            # Same seed -> same sequence: skip the variants the restored
            # trials (completed AND re-queued) already consumed.
            seq = iter(list(generate_variants(
                self._space, cfg.num_samples, cfg.seed))[next_index:])

            def next_variant(trial_id: str):
                return next(seq, None)
        else:
            def next_variant(trial_id: str):
                return cfg.search_alg.suggest(trial_id)

        def launch_next() -> Optional[TrialResult]:
            nonlocal next_index
            if rerun:
                t = rerun.pop(0)
                if cfg.search_alg is not None:
                    # Re-register so the searcher attributes the re-run's
                    # completion (its pending entry died with phase 1).
                    cfg.search_alg.on_trial_restore(t.trial_id, t.config)
                if hasattr(scheduler, "on_trial_restore"):
                    # Restored scheduler state (pickled with the config)
                    # must drop the trial's phase-1 records: it restarts
                    # from iteration 0.
                    scheduler.on_trial_restore(t.trial_id)
                return t
            # Searcher runs are capped at num_samples trials; the
            # default generator's sequence bounds itself (num_samples
            # MULTIPLIES the grid there, reference semantics).
            if cfg.search_alg is not None \
                    and next_index >= cfg.num_samples:
                return None
            tid = f"trial_{next_index:05d}"
            v = next_variant(tid)
            if v is None:
                return None
            t = TrialResult(trial_id=tid, config=v)
            next_index += 1
            trials.append(t)
            by_id[t.trial_id] = t
            if hasattr(scheduler, "track"):  # PBT needs live configs
                scheduler.track(t.trial_id, t.config)
            return t

        running: Dict[str, Any] = {}   # trial_id -> actor handle
        stopping: set = set()
        # Controller-held latest (checkpoint_blob, iteration) + failure
        # count per trial (the reschedule-with-checkpoint FT path).
        ckpts: Dict[str, tuple] = {}
        failures: Dict[str, int] = {}
        # Probe once whether the searcher accepts the budget kwarg (a
        # live-call TypeError fallback would double-invoke a searcher
        # whose BODY raised TypeError).
        searcher_takes_budget = False
        if cfg.search_alg is not None:
            import inspect
            try:
                searcher_takes_budget = "budget" in inspect.signature(
                    cfg.search_alg.on_trial_complete).parameters
            except (TypeError, ValueError):
                pass
        actor_cls = ray_tpu.remote(TrialRunner)
        opts: Dict[str, Any] = {}
        if cfg.resources_per_trial:
            res = dict(cfg.resources_per_trial)
            if "CPU" in res:
                opts["num_cpus"] = res.pop("CPU")
            if "TPU" in res:
                opts["num_tpus"] = res.pop("TPU")
            if res:
                opts["resources"] = res
        if opts:
            actor_cls = actor_cls.options(**opts)

        by_id = {t.trial_id: t for t in trials}
        if hasattr(scheduler, "track"):
            for t in rerun:
                scheduler.track(t.trial_id, t.config)
        exhausted = False
        while True:
            while not exhausted and len(running) < cfg.max_concurrent_trials:
                t = launch_next()
                if t is None:
                    exhausted = True
                    break
                t.status = RUNNING
                running[t.trial_id] = actor_cls.remote(self._fn_blob,
                                                       t.config)
            if not running and exhausted:
                break
            done: List[str] = []
            for tid, actor in running.items():
                t = by_id[tid]
                try:
                    p = ray_tpu.get(actor.poll.remote(), timeout=60)
                except Exception as e:
                    failures[tid] = failures.get(tid, 0) + 1
                    if tid in stopping:
                        # The scheduler already cut this trial; losing
                        # its actor finalizes the stop instead of
                        # resurrecting a full-budget run.
                        t.status = STOPPED
                        done.append(tid)
                        continue
                    if failures[tid] <= cfg.max_failures:
                        # Node/actor loss mid-trial: reschedule from the
                        # latest controller-held checkpoint (fresh start
                        # if it never checkpointed). Reference:
                        # tune_controller restoring FAILED trials under
                        # FailureConfig. Kill the old actor first — a
                        # poll TIMEOUT (not death) must never leave two
                        # copies of the trial running.
                        try:
                            ray_tpu.kill(actor)
                        except Exception:
                            pass
                        ck = ckpts.get(tid)
                        start_it = ck[1] if ck else 0
                        logger.warning(
                            "trial %s lost (%r): rescheduling "
                            "(failure %d/%d, checkpoint_iter=%s)", tid, e,
                            failures[tid], cfg.max_failures, start_it)
                        if hasattr(scheduler, "on_trial_restore") \
                                and ck is None:
                            scheduler.on_trial_restore(tid)
                        # Iteration numbering restarts AT the checkpoint
                        # so scheduler rungs stay aligned.
                        t.iterations = start_it
                        running[tid] = actor_cls.remote(
                            self._fn_blob, t.config,
                            restored=ck[0] if ck else None,
                            start_iteration=start_it)
                        continue
                    t.status = ERROR
                    t.error = f"trial actor died: {e!r}"
                    done.append(tid)
                    continue
                if p.get("checkpoint") is not None:
                    ckpts[tid] = (p["checkpoint"],
                                  p.get("checkpoint_iteration",
                                        p["iteration"]))
                for m in p["reported"]:
                    t.metrics_history.append(m)
                    t.metrics = m
                t.iterations = p["iteration"]
                # The scheduler may rank on its OWN metric (e.g. ASHA on
                # accuracy while the tuner reports best-loss).
                metric = getattr(scheduler, "metric", None) or cfg.metric
                if metric and p["reported"] and tid not in stopping:
                    decision = CONTINUE
                    for i, m in enumerate(p["reported"]):
                        if metric in m:
                            it = (t.iterations - len(p["reported"]) + 1
                                  + i)
                            decision = scheduler.on_result(
                                tid, it, float(m[metric]))
                            if decision != CONTINUE:
                                break
                    if decision == STOP:
                        stopping.add(tid)
                        try:
                            actor.stop_trial.remote()
                        except Exception:
                            pass
                    elif isinstance(decision, tuple) \
                            and decision[0] == "EXPLOIT" \
                            and not p["finished"]:
                        # PBT: restart this trial from the source's
                        # checkpoint with the mutated config. A trial
                        # whose SAME poll already reported finished is
                        # past exploiting (the replacement would be
                        # killed by the done-handling below).
                        _, source_tid, new_config = decision
                        replaced = self._exploit(
                            actor_cls, running, by_id, tid, source_tid,
                            new_config)
                        if replaced is not None:
                            running[tid] = replaced[0]
                            # The FT reschedule path must restore the
                            # EXPLOITED state, not the trial's stale
                            # pre-exploit checkpoint.
                            ckpts[tid] = (replaced[1], t.iterations)
                if p["finished"]:
                    if p["error"]:
                        t.status = ERROR
                        t.error = p["error"]
                    else:
                        t.status = STOPPED if tid in stopping \
                            else TERMINATED
                    done.append(tid)
            for tid in done:
                actor = running.pop(tid)
                try:
                    ray_tpu.kill(actor)
                except Exception:
                    pass
                t = by_id[tid]
                # Completions feed the searcher IMMEDIATELY so later
                # suggestions learn from them (reference: SearchGenerator
                # on_trial_complete).
                if cfg.search_alg is not None:
                    kw = {"budget": t.iterations} \
                        if searcher_takes_budget else {}
                    cfg.search_alg.on_trial_complete(
                        tid, t.metrics or None,
                        error=t.status == ERROR, **kw)
            if done:
                # One snapshot per poll round (it serializes the whole
                # trial table + searcher state).
                self._save_state(trials)
            if running:
                time.sleep(0.2)
        logger.info("tune finished: %d trials (%d errors)", len(trials),
                    sum(1 for t in trials if t.status == ERROR))
        self._save_state(trials)
        return ResultGrid(trials, cfg.metric, cfg.mode)

    def _exploit(self, actor_cls, running, by_id, tid: str,
                 source_tid: str, new_config: dict):
        """PBT exploit: clone the source's checkpoint into a replacement
        actor for `tid` running `new_config` (reference: pbt.py
        _exploit — checkpoint copy + explore). Returns (new_actor,
        checkpoint_blob) or None."""
        source = running.get(source_tid)
        if source is None:
            return None  # source finished: skip this round
        try:
            ckpt = ray_tpu.get(source.get_trial_checkpoint.remote(),
                               timeout=60)
        except Exception:
            return None
        if ckpt is None:
            return None  # source never checkpointed: nothing to copy
        t = by_id[tid]
        old = running[tid]
        try:
            ray_tpu.kill(old)
        except Exception:
            pass
        t.config = dict(new_config)
        logger.info("PBT exploit: %s <- %s (config %s)", tid, source_tid,
                    new_config)
        return (actor_cls.remote(self._fn_blob, dict(new_config),
                                 restored=ckpt,
                                 start_iteration=t.iterations), ckpt)
