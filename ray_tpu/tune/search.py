"""Search spaces + variant generation.

Analogue of the reference's search layer (reference: python/ray/tune/
search/sample.py Domain/Float/Integer/Categorical, search/basic_variant.py
BasicVariantGenerator — grid cross-product x num_samples random sampling).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math
        self._lo, self._hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(self._lo, self._hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, options: List[Any]):
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


class GridSearch:
    """Marker: every value is tried (cross-product with other grids)."""

    def __init__(self, values: List[Any]):
        self.values = list(values)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(options: List[Any]) -> Choice:
    return Choice(options)


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(values)


def generate_variants(param_space: Dict[str, Any], num_samples: int,
                      seed: Optional[int] = None
                      ) -> Iterator[Dict[str, Any]]:
    """Grid keys expand to their cross-product; Domain keys are sampled
    fresh per variant; plain values pass through. num_samples multiplies
    the grid (reference: BasicVariantGenerator semantics)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items()
                 if isinstance(v, GridSearch)]
    grid_values = [param_space[k].values for k in grid_keys]
    grid_points = list(itertools.product(*grid_values)) if grid_keys \
        else [()]
    for _ in range(num_samples):
        for point in grid_points:
            cfg: Dict[str, Any] = {}
            for k, v in param_space.items():
                if isinstance(v, GridSearch):
                    cfg[k] = point[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            yield cfg


# ---------------------------------------------------------------------------
# Searcher seam (reference: python/ray/tune/search/searcher.py Searcher +
# basic_variant.py BasicVariantGenerator): pluggable suggestion
# algorithms — the Tuner asks `suggest(trial_id)` for each trial's config
# and feeds completions back for adaptive searchers.
# ---------------------------------------------------------------------------

class Searcher:
    """Base: subclass and implement suggest(); optionally learn from
    on_trial_complete()."""

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False,
                          budget: int = 0) -> None:
        """`budget`: the iteration count the trial reached — multi-
        fidelity searchers (BOHB's TPE) compare observations only
        within a budget level."""
        pass

    def on_trial_restore(self, trial_id: str,
                         config: Dict[str, Any]) -> None:
        """A restored (re-run) trial is back in flight with `config`:
        adaptive searchers re-register it so its eventual completion is
        attributable (Tuner.restore path)."""
        pass


class BasicVariantGenerator(Searcher):
    """Random/grid sampling over a param space — the default search
    behavior expressed through the Searcher seam."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self._variants = list(generate_variants(param_space, num_samples,
                                                seed))
        self._i = 0

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._i >= len(self._variants):
            return None
        v = self._variants[self._i]
        self._i += 1
        return v
