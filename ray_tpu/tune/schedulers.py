"""Trial schedulers: FIFO + ASHA.

Analogue of the reference's schedulers (reference: python/ray/tune/
schedulers/trial_scheduler.py FIFOScheduler, async_hyperband.py
AsyncHyperBandScheduler/ASHAScheduler — rungs at reduction_factor
intervals; a trial reaching a rung survives only if it is in the top
1/reduction_factor of results recorded at that rung).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, iteration: int,
                  metric_value: float) -> str:
        return CONTINUE


class ASHAScheduler:
    """Asynchronous Successive Halving (reference:
    schedulers/async_hyperband.py:29)."""

    def __init__(self, *, max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3, metric: Optional[str] = None,
                 mode: str = "min"):
        assert mode in ("min", "max")
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.metric = metric  # default: the tuner's metric
        self.mode = mode
        # Rung milestones: grace, grace*rf, grace*rf^2, ... <= max_t
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung milestone -> recorded metric values
        self._recorded: Dict[int, List[float]] = {r: [] for r in self.rungs}

    def on_result(self, trial_id: str, iteration: int,
                  metric_value: float) -> str:
        if iteration >= self.max_t:
            return STOP  # budget exhausted (not a failure)
        for rung in reversed(self.rungs):
            if iteration == rung:
                vals = self._recorded[rung]
                vals.append(metric_value)
                if len(vals) < self.rf:
                    return CONTINUE  # not enough peers yet: optimistic
                ranked = sorted(vals)
                if self.mode == "max":
                    ranked = ranked[::-1]
                cutoff = ranked[max(0, len(vals) // self.rf - 1)]
                good = metric_value <= cutoff if self.mode == "min" \
                    else metric_value >= cutoff
                return CONTINUE if good else STOP
        return CONTINUE
