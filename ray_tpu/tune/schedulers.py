"""Trial schedulers: FIFO + ASHA.

Analogue of the reference's schedulers (reference: python/ray/tune/
schedulers/trial_scheduler.py FIFOScheduler, async_hyperband.py
AsyncHyperBandScheduler/ASHAScheduler — rungs at reduction_factor
intervals; a trial reaching a rung survives only if it is in the top
1/reduction_factor of results recorded at that rung).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


def _rung_decision(vals: Dict[str, float], metric_value: float,
                   rf: int, mode: str) -> str:
    """Successive-halving cut at one rung: survive only in the top
    1/rf of the values recorded there (optimistic until rf peers
    exist). Shared by ASHA and the BOHB brackets."""
    if len(vals) < rf:
        return CONTINUE
    ranked = sorted(vals.values())
    if mode == "max":
        ranked = ranked[::-1]
    cutoff = ranked[max(0, len(vals) // rf - 1)]
    good = metric_value <= cutoff if mode == "min" \
        else metric_value >= cutoff
    return CONTINUE if good else STOP


class FIFOScheduler:
    def on_result(self, trial_id: str, iteration: int,
                  metric_value: float) -> str:
        return CONTINUE


class ASHAScheduler:
    """Asynchronous Successive Halving (reference:
    schedulers/async_hyperband.py:29)."""

    def __init__(self, *, max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3, metric: Optional[str] = None,
                 mode: str = "min"):
        assert mode in ("min", "max")
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.metric = metric  # default: the tuner's metric
        self.mode = mode
        # Rung milestones: grace, grace*rf, grace*rf^2, ... <= max_t
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung milestone -> {trial_id: recorded metric}. Trial-keyed so a
        # re-run (Tuner.restore) REPLACES its old entry instead of
        # double-counting it against peers.
        self._recorded: Dict[int, Dict[str, float]] = {
            r: {} for r in self.rungs}

    def on_trial_restore(self, trial_id: str) -> None:
        """A restored trial restarts from iteration 0: drop its phase-1
        rung entries so its re-reports don't double-count."""
        for vals in self._recorded.values():
            vals.pop(trial_id, None)

    def on_result(self, trial_id: str, iteration: int,
                  metric_value: float) -> str:
        if iteration >= self.max_t:
            return STOP  # budget exhausted (not a failure)
        for rung in reversed(self.rungs):
            if iteration == rung:
                vals = self._recorded[rung]
                vals[trial_id] = metric_value
                return _rung_decision(vals, metric_value, self.rf,
                                      self.mode)
        return CONTINUE


EXPLOIT = "EXPLOIT"


class PBTScheduler:
    """Population Based Training (reference:
    python/ray/tune/schedulers/pbt.py PopulationBasedTraining — Jaderberg
    et al. 2017). Every `perturbation_interval` iterations, a trial in
    the bottom quantile EXPLOITS a top-quantile peer: the tuner restarts
    it from the peer's checkpoint with perturbed hyperparameters.
    Trainables must save state via tune.report(..., checkpoint=...) and
    resume via tune.get_checkpoint().

    on_result returns CONTINUE, STOP, or ("EXPLOIT", source_trial_id,
    mutated_config_delta)."""

    def __init__(self, *, hyperparam_mutations: Dict[str, Any],
                 perturbation_interval: int = 5,
                 quantile_fraction: float = 0.25,
                 metric: Optional[str] = None, mode: str = "max",
                 perturbation_factors=(0.8, 1.2), seed: int = 0):
        import random as _random
        assert mode in ("min", "max")
        assert 0 < quantile_fraction <= 0.5
        self.mutations = hyperparam_mutations
        self.interval = perturbation_interval
        self.quantile = quantile_fraction
        self.metric = metric
        self.mode = mode
        self.factors = perturbation_factors
        self._rng = _random.Random(seed)
        self._latest: Dict[str, float] = {}       # trial -> last metric
        self._configs: Dict[str, dict] = {}       # trial -> live config
        self._last_perturb: Dict[str, int] = {}

    def track(self, trial_id: str, config: dict) -> None:
        """The tuner registers each trial's (live) config."""
        self._configs[trial_id] = dict(config)
        self._last_perturb.setdefault(trial_id, 0)

    def on_trial_restore(self, trial_id: str) -> None:
        """A restored trial restarts from iteration 0: clear its stale
        metric and perturb clock (track() re-registers the config)."""
        self._latest.pop(trial_id, None)
        self._last_perturb[trial_id] = 0

    def _quantiles(self):
        ranked = sorted(self._latest.items(), key=lambda kv: kv[1],
                        reverse=self.mode == "max")
        n = max(1, int(len(ranked) * self.quantile))
        top = [t for t, _ in ranked[:n]]
        bottom = [t for t, _ in ranked[-n:]] if len(ranked) > 1 else []
        return top, bottom

    def _mutate(self, config: dict) -> dict:
        """Perturb each mutated hyperparam: resample with p=0.25, else
        scale by a perturbation factor (the reference's explore())."""
        from ray_tpu.tune.search import Domain
        out = dict(config)
        for key, spec in self.mutations.items():
            old = out.get(key)
            if self._rng.random() < 0.25 or old is None \
                    or not isinstance(old, (int, float)):
                if isinstance(spec, Domain):
                    out[key] = spec.sample(self._rng)
                elif isinstance(spec, (list, tuple)):
                    out[key] = self._rng.choice(list(spec))
                elif callable(spec):
                    out[key] = spec()
            else:
                out[key] = old * self._rng.choice(self.factors)
                if isinstance(old, int):
                    out[key] = max(1, int(round(out[key])))
        return out

    def on_result(self, trial_id: str, iteration: int,
                  metric_value: float):
        self._latest[trial_id] = metric_value
        if iteration - self._last_perturb.get(trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = iteration
        if len(self._latest) < 2:
            return CONTINUE
        top, bottom = self._quantiles()
        if trial_id not in bottom or trial_id in top:
            return CONTINUE
        source = self._rng.choice(top)
        if source == trial_id:
            return CONTINUE
        new_config = self._mutate(self._configs.get(source, {}))
        self._configs[trial_id] = new_config
        return (EXPLOIT, source, new_config)


class BOHBScheduler:
    """HyperBand bracketing for BOHB (reference: python/ray/tune/
    schedulers/hb_bohb.py HyperBandForBOHB + Falkner et al. 2018): pair
    this scheduler with TPESearcher as the search_alg and you have BOHB —
    model-based proposals + multi-bracket successive halving. Each trial
    is assigned (round-robin over the HyperBand bracket allocation) to a
    bracket whose rung ladder starts at grace_period * rf^s; within a
    bracket the asynchronous successive-halving rule applies, so
    aggressive brackets kill weak trials with tiny budgets while the
    conservative bracket lets slow starters mature."""

    def __init__(self, *, max_t: int = 81, grace_period: int = 1,
                 reduction_factor: int = 3,
                 metric: Optional[str] = None, mode: str = "min"):
        assert mode in ("min", "max")
        self.max_t = max_t
        self.rf = reduction_factor
        self.metric = metric
        self.mode = mode
        # Brackets s = s_max .. 0; bracket s's first rung is
        # grace * rf^s (HyperBand's r_s = R / rf^s budget schedule,
        # expressed as rung milestones).
        s_max = 0
        t = grace_period
        while t * reduction_factor < max_t:
            t *= reduction_factor
            s_max += 1
        # Bracket i (aggressive-first): rung ladder starting at
        # grace * rf^i — bracket 0 halves from the smallest budget,
        # bracket s_max runs near-full budget before any cut.
        self._brackets: List[List[int]] = []
        for i in range(s_max + 1):
            rungs = []
            r = grace_period * (reduction_factor ** i)
            while r < max_t:
                rungs.append(r)
                r *= reduction_factor
            self._brackets.append(rungs or [grace_period])
        # HyperBand allocates ~rf^s / (s+1) trials to the bracket doing
        # s rounds of halving (more to aggressive brackets); bracket i
        # halves s = s_max - i times.
        weights = [max(1, round((reduction_factor ** (s_max - i))
                                / (s_max - i + 1)))
                   for i in range(s_max + 1)]
        self._cycle: List[int] = []
        for idx, w in enumerate(weights):
            self._cycle.extend([idx] * w)
        self._next = 0
        self._bracket_of: Dict[str, int] = {}
        # (bracket, rung) -> {trial_id: metric}
        self._recorded: Dict[tuple, Dict[str, float]] = {}

    def track(self, trial_id: str, config: dict) -> None:
        if trial_id in self._bracket_of:
            return
        self._bracket_of[trial_id] = self._cycle[self._next
                                                 % len(self._cycle)]
        self._next += 1

    def on_trial_restore(self, trial_id: str) -> None:
        for vals in self._recorded.values():
            vals.pop(trial_id, None)

    def on_result(self, trial_id: str, iteration: int,
                  metric_value: float) -> str:
        if iteration >= self.max_t:
            return STOP
        b = self._bracket_of.get(trial_id)
        if b is None:  # untracked (restored mid-run): conservative
            b = len(self._brackets) - 1
            self._bracket_of[trial_id] = b
        for rung in reversed(self._brackets[b]):
            if iteration == rung:
                vals = self._recorded.setdefault((b, rung), {})
                vals[trial_id] = metric_value
                return _rung_decision(vals, metric_value, self.rf,
                                      self.mode)
        return CONTINUE
