"""ray_tpu.tune — hyperparameter search over the actor runtime.

Analogue of Ray Tune (reference: python/ray/tune/ — Tuner, TuneController
execution/tune_controller.py:68, search spaces search/sample.py, ASHA
schedulers/async_hyperband.py), minimum slice: function trainables report
per-iteration metrics; the controller runs trials as actors up to a
concurrency cap; ASHA stops under-performers at rungs.

    from ray_tpu import tune

    def objective(config):
        for _ in range(20):
            tune.report({"loss": (config["x"] - 3) ** 2})

    grid = tune.Tuner(objective,
                      param_space={"x": tune.uniform(0, 5)},
                      tune_config=tune.TuneConfig(metric="loss",
                                                  num_samples=8)).fit()
    best = grid.get_best_result()
"""

from ray_tpu.tune.schedulers import (ASHAScheduler, BOHBScheduler,
                                     FIFOScheduler, PBTScheduler)
from ray_tpu.tune.search import (BasicVariantGenerator, Searcher, choice,
                                 grid_search, loguniform, randint, uniform)
from ray_tpu.tune.tpe import TPESearcher
from ray_tpu.tune.trial import get_checkpoint, report
from ray_tpu.tune.tuner import (ResultGrid, TrialResult, TuneConfig, Tuner)

__all__ = [
    "ASHAScheduler", "BOHBScheduler", "BasicVariantGenerator",
    "FIFOScheduler",
    "PBTScheduler", "ResultGrid", "Searcher", "TPESearcher", "TrialResult",
    "TuneConfig", "Tuner", "choice", "get_checkpoint", "grid_search",
    "loguniform", "randint", "report", "uniform",
]
