"""Trial state + the TrialRunner actor.

Analogue of the reference's trial execution (reference: python/ray/tune/
experiment/trial.py Trial states, tune/trainable/function_trainable.py —
the user function runs in a thread and reports through a session). One
TrialRunner actor per trial; the controller polls it like the Train
controller polls its workers.
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Dict, List, Optional

import cloudpickle

PENDING, RUNNING, TERMINATED, ERROR, STOPPED = (
    "PENDING", "RUNNING", "TERMINATED", "ERROR", "STOPPED")


class _TuneSession:
    def __init__(self, restored: Optional[bytes] = None,
                 start_iteration: int = 0):
        self.lock = threading.Lock()
        self.reported: List[Dict[str, Any]] = []
        self.iteration = start_iteration
        self.stop_requested = False
        self.finished = False
        self.error: Optional[str] = None
        self.checkpoint: Optional[bytes] = None  # latest saved state
        self.ckpt_version = 0                    # bumps on every save
        self.ckpt_iteration = 0                  # iteration it captured
        self.restored = restored                 # state to resume from


_session: Optional[_TuneSession] = None


def report(metrics: Dict[str, Any], *,
           checkpoint: Any = None) -> None:
    """Report one iteration's metrics from inside a trainable (reference:
    ray.tune.report, with checkpoint= as in train.report). Raises
    StopIteration-like exit when the scheduler stopped this trial.
    Checkpoints make the trial PBT-exploitable."""
    if _session is None:
        raise RuntimeError("tune.report() called outside a trial")
    with _session.lock:
        _session.iteration += 1
        _session.reported.append(dict(metrics))
        if checkpoint is not None:
            _session.checkpoint = cloudpickle.dumps(checkpoint)
            _session.ckpt_iteration = _session.iteration
            _session.ckpt_version += 1
        if _session.stop_requested:
            raise _TrialStopped()


def get_checkpoint() -> Any:
    """State this trial should resume from (None on a fresh start;
    a PBT exploit restarts the trial with the source's checkpoint —
    reference: ray.tune.get_checkpoint)."""
    if _session is None:
        raise RuntimeError("tune.get_checkpoint() outside a trial")
    if _session.restored is None:
        return None
    return cloudpickle.loads(_session.restored)


class _TrialStopped(BaseException):
    """Control-flow exception: scheduler stopped the trial (not an error)."""


class TrialRunner:
    """Actor hosting one trial's trainable function."""

    def __init__(self, fn_blob: bytes, config: dict,
                 restored: Optional[bytes] = None,
                 start_iteration: int = 0):
        global _session
        self._session = _TuneSession(restored, start_iteration)
        _session = self._session
        fn = cloudpickle.loads(fn_blob)

        def run():
            try:
                fn(config)
            except _TrialStopped:
                pass
            except BaseException:
                self._session.error = traceback.format_exc()
            finally:
                self._session.finished = True

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="trial")
        self._thread.start()
        self._ckpt_sent = 0

    def poll(self) -> dict:
        s = self._session
        with s.lock:
            reported = s.reported
            s.reported = []
            out = {
                "reported": reported,
                "iteration": s.iteration,
                "finished": s.finished,
                "error": s.error,
            }
            # Ship NEW checkpoints to the controller so a trial can be
            # rescheduled from its latest state after a node loss
            # (reference: trial checkpoints persist to storage; here the
            # controller is the storage).
            if s.ckpt_version > self._ckpt_sent:
                out["checkpoint"] = s.checkpoint
                out["checkpoint_iteration"] = s.ckpt_iteration
                self._ckpt_sent = s.ckpt_version
            return out

    def stop_trial(self) -> None:
        with self._session.lock:
            self._session.stop_requested = True

    def get_trial_checkpoint(self) -> Optional[bytes]:
        """Latest checkpoint blob (PBT exploit source)."""
        with self._session.lock:
            return self._session.checkpoint
