"""TPE — Tree-structured Parzen Estimator searcher.

Analogue of the reference's adaptive search integrations (reference:
python/ray/tune/search/hyperopt/hyperopt_search.py wraps hyperopt's TPE;
search/optuna defaults to the same family). Implemented natively against
this framework's Domain types rather than wrapping an external library:
per-dimension Parzen mixtures over the observed trials, split into a
GOOD quantile and the rest; candidates are sampled from the good mixture
and ranked by the density ratio l(x)/g(x) (Bergstra et al., NeurIPS'11 —
the standard independent-factorization simplification).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search import (Choice, Domain, GridSearch, LogUniform,
                                 RandInt, Searcher, Uniform,
                                 generate_variants)


class TPESearcher(Searcher):
    """suggest() returns random draws for the first ``n_initial`` trials,
    then per-dimension TPE proposals; feed completions back through
    on_trial_complete (the Tuner does this automatically)."""

    def __init__(self, param_space: Dict[str, Any], *, metric: str,
                 mode: str = "min", n_initial: int = 8,
                 n_candidates: int = 24, gamma: float = 0.25,
                 seed: Optional[int] = None):
        assert mode in ("min", "max")
        if any(isinstance(v, GridSearch) for v in param_space.values()):
            raise ValueError("grid_search dimensions don't mix with TPE; "
                             "use BasicVariantGenerator for grids")
        self.space = dict(param_space)
        self.metric = metric
        self.mode = mode
        self.n_initial = n_initial
        self.n_candidates = n_candidates
        self.gamma = gamma
        self._rng = random.Random(seed)
        # trial_id -> config for pending attribution; observations are
        # (config, score) with score oriented so LOWER is better.
        self._pending: Dict[str, Dict[str, Any]] = {}
        self._obs: List[Tuple[Dict[str, Any], float]] = []

    # -- Searcher interface ---------------------------------------------
    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._obs) < self.n_initial:
            cfg = next(generate_variants(
                self.space, 1, self._rng.randrange(1 << 30)))
        else:
            cfg = self._propose()
        self._pending[trial_id] = cfg
        return cfg

    def on_trial_restore(self, trial_id: str,
                         config: Dict[str, Any]) -> None:
        self._pending[trial_id] = dict(config)

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False,
                          budget: int = 0) -> None:
        cfg = self._pending.pop(trial_id, None)
        if cfg is None or error or not result \
                or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "max":
            score = -score
        self._obs.append((cfg, score, int(budget)))

    # -- TPE core --------------------------------------------------------
    def _split(self) -> Tuple[list, list]:
        # Multi-fidelity (BOHB, Falkner et al. 2018): model the HIGHEST
        # budget with enough observations — scores from different rungs
        # are not comparable (an early-stopped trial's loss carries the
        # low-fidelity bias). With a single budget level (no early
        # stopping) this is all observations, plain TPE.
        n_min = max(2, len([d for d in self.space.values()
                            if isinstance(d, Domain)]) + 1)
        by_budget: Dict[int, list] = {}
        for o in self._obs:
            by_budget.setdefault(o[2], []).append(o)
        pool = self._obs
        for b in sorted(by_budget, reverse=True):
            if len(by_budget[b]) >= n_min:
                pool = by_budget[b]
                break
        ranked = sorted(pool, key=lambda o: o[1])
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        return ranked[:n_good], ranked[n_good:]

    def _propose(self) -> Dict[str, Any]:
        good, bad = self._split()
        cfg: Dict[str, Any] = {}
        for key, dom in self.space.items():
            if isinstance(dom, Domain):
                cfg[key] = self._propose_dim(key, dom, good, bad)
            else:
                cfg[key] = dom  # constant passthrough
        return cfg

    def _propose_dim(self, key: str, dom: Domain, good: list, bad: list):
        if isinstance(dom, Choice):
            return self._propose_choice(key, dom, good, bad)
        lo, hi, fwd, inv = _numeric_transform(dom)
        g_vals = [fwd(o[0][key]) for o in good]
        b_vals = [fwd(o[0][key]) for o in bad]
        # Parzen bandwidth: range-scaled, shrinking with the TOTAL
        # observation count (a good-count-only denominator leaves the
        # mixture near-uniform and proposals barely better than random).
        n_total = len(g_vals) + len(b_vals)
        bw = max((hi - lo) / max(4.0, float(n_total)), 1e-12)
        best_x, best_score = None, -math.inf
        for _ in range(self.n_candidates):
            center = self._rng.choice(g_vals)
            x = min(hi, max(lo, self._rng.gauss(center, bw)))
            score = (_log_parzen(x, g_vals, bw, lo, hi)
                     - _log_parzen(x, b_vals, bw, lo, hi))
            if score > best_score:
                best_x, best_score = x, score
        out = inv(best_x)
        if isinstance(dom, RandInt):
            out = min(dom.high - 1, max(dom.low, int(round(out))))
        return out

    def _propose_choice(self, key: str, dom: Choice, good: list,
                        bad: list):
        def probs(obs):
            counts = {repr(opt): 1.0 for opt in dom.options}  # +1 prior
            for o in obs:
                counts[repr(o[0][key])] = counts.get(
                    repr(o[0][key]), 1.0) + 1.0
            total = sum(counts.values())
            return {k: v / total for k, v in counts.items()}

        pg, pb = probs(good), probs(bad)
        # Sample ∝ density ratio (not argmax: keep exploring ties).
        scored = [(pg[repr(opt)] / pb[repr(opt)], opt)
                  for opt in dom.options]
        r = self._rng.uniform(0, sum(w for w, _ in scored))
        acc = 0.0
        for w, opt in scored:
            acc += w
            if r <= acc:
                return opt
        return scored[-1][1]


def _numeric_transform(dom: Domain):
    """(lo, hi, forward, inverse) in the search's metric space."""
    if isinstance(dom, Uniform):
        return dom.low, dom.high, (lambda v: float(v)), (lambda x: x)
    if isinstance(dom, LogUniform):
        return dom._lo, dom._hi, (lambda v: math.log(v)), \
            (lambda x: math.exp(x))
    if isinstance(dom, RandInt):
        return float(dom.low), float(dom.high - 1), \
            (lambda v: float(v)), (lambda x: x)
    raise TypeError(f"TPE cannot search domain {type(dom).__name__}")


def _log_parzen(x: float, centers: List[float], bw: float,
                lo: float, hi: float) -> float:
    """log density of a uniform-floored Gaussian mixture (the floor keeps
    the ratio finite where one side has no mass)."""
    floor = 1.0 / max(hi - lo, 1e-12)
    if not centers:
        return math.log(floor)
    total = 0.0
    norm = 1.0 / (bw * math.sqrt(2 * math.pi))
    for c in centers:
        total += norm * math.exp(-0.5 * ((x - c) / bw) ** 2)
    mix = 0.9 * (total / len(centers)) + 0.1 * floor
    return math.log(max(mix, 1e-300))
